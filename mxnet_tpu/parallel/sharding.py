"""Mesh construction + sharding rules — THE placement layer.

Reference mapping (SURVEY.md §2.3): contexts -> mesh axes. The reference
placed whole layers on devices (group2ctx + PlaceDevice inserting
_CrossDeviceCopy); here placement is a sharding annotation and XLA inserts
the transfers/collectives.

Every device placement in the training stack routes through this module:
``place``/``constrain`` are the only sanctioned ``jax.device_put`` /
``with_sharding_constraint`` call sites for ``module/`` and
``parallel/trainer.py`` (tools/perf_smoke.sh lints those files against raw
calls), and a *layout* object decides every parameter / optimizer-state /
batch sharding. Two layouts implement one interface:

- ``_HeuristicLayout`` — the original name-suffix heuristics
  (``param_sharding`` / ``zero1_sharding`` / ``batch_sharding`` below);
  what a bare ``mesh=`` argument binds to. Semantics unchanged.
- ``SpecLayout`` — the GSPMD partition-spec REGISTRY over a named
  ``data × fsdp × tp`` mesh (docs/parallelism.md "One-jit GSPMD path"):
  ordered rules mapping parameter names (exact or glob, first match
  wins) to PartitionSpecs, an auto-rule fallback (shard the largest
  divisible dim over ``fsdp``), optimizer state folded across the
  ``data × fsdp`` replicas (the ZeRO weight-update sharding of arXiv
  2004.13336, generalizing ``zero1_sharding``), and a ``describe()``
  report of which rule claimed each parameter.

Axes convention (scaling-book style):
  data  — pure data parallelism: batch shards over it, grad all-reduce
          rides it, params replicate along it.
  fsdp  — data parallelism with parameter sharding (ZeRO-3 flavored):
          the batch ALSO shards over it, but params/opt state live
          1/|fsdp| per device and XLA all-gathers weights where used.
  tp    — tensor parallelism (hidden dimension). Matmul partials psum
          over this axis.
  model — legacy name for the heuristic layout's TP axis.
More axes (pipe, sp, expert) are added by the specific parallel modules.
"""
from __future__ import annotations

import fnmatch

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "data_parallel_mesh", "param_sharding",
           "batch_sharding", "replicated", "zero1_sharding",
           "SpecLayout", "place", "constrain", "parse_spec",
           "REPLICA_AXES", "BOUNDARY_OPS"]

# axes the batch dimension shards over and optimizer state folds across
# (in this order). Everything else (tp/model/sp/expert/pipe) partitions
# the model itself, never the batch.
REPLICA_AXES = ("data", "fsdp")

# ops whose outputs mark a module boundary: with a SpecLayout bound, the
# graph evaluator pins their batch dimension with a LENIENT
# with_sharding_constraint so GSPMD's propagation can't drift
# activations off the data axes mid-network (executor._graph_eval_fn).
BOUNDARY_OPS = frozenset({
    "FullyConnected", "Convolution", "BatchNorm", "Activation",
    "Pooling", "Embedding", "Dropout", "SoftmaxOutput", "LayerNorm",
})


def place(value, sharding=None):
    """Place an array on device — the placement layer's single
    sanctioned ``jax.device_put`` call site for the training stack
    (async dispatch; never blocks)."""
    if sharding is None:
        return jax.device_put(value)
    return jax.device_put(value, sharding)


def constrain(value, sharding):
    """Pin an in-graph value's layout — the single sanctioned
    ``with_sharding_constraint`` call site for the training stack."""
    return jax.lax.with_sharding_constraint(value, sharding)


def _ns(mesh, parts):
    """NamedSharding with trailing replicated (None) dims stripped.
    XLA normalizes the shardings it assigns to step OUTPUTS that way,
    and NamedSharding equality is syntactic (P('fsdp', None) !=
    P('fsdp')) — an un-normalized placement would differ from the
    step's own output sharding and cost a spurious step-2 recompile
    when the state feeds back (review finding on the GSPMD bench
    row)."""
    parts = tuple(parts)
    while parts and parts[-1] is None:
        parts = parts[:-1]
    return NamedSharding(mesh, P(*parts))


def make_mesh(axis_sizes, devices=None):
    """Build a Mesh from {'data': N, 'fsdp': M, ...}. Sizes must
    multiply to the device count; pass -1 for (at most) one axis to
    infer it. Raises ValueError (never a stripped-under-``python -O``
    assert) with the sizes and device count on any mismatch."""
    names = tuple(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    bad = [(k, v) for k, v in axis_sizes.items()
           if not isinstance(v, (int, np.integer)) or (v < 1 and v != -1)]
    if bad:
        raise ValueError(
            "mesh axis sizes must be positive ints (or one -1 to "
            "infer), got %r in %r" % (bad, axis_sizes))
    if sizes.count(-1) > 1:
        raise ValueError("at most one mesh axis may be -1 (inferred), "
                         "got %r" % (axis_sizes,))
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if known == 0 or n % known != 0:
            raise ValueError(
                "cannot infer the -1 axis of %r: the known sizes "
                "multiply to %d, which does not divide the %d visible "
                "devices" % (axis_sizes, known, n))
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(
            "mesh axes %r (sizes %r, product %d) don't multiply to the "
            "%d visible devices — fix the sizes, use -1 for one axis, "
            "or pass an explicit devices= subset"
            % (names, sizes, int(np.prod(sizes)), n))
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, names)


def data_parallel_mesh(devices=None):
    """1-D data mesh over all (or given) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), ("data",))


def replicated(mesh):
    return NamedSharding(mesh, P())


def batch_sharding(mesh, ndim, batch_axis=0):
    """Batch arrays: shard the batch axis over 'data' (+ nothing else)."""
    spec = [None] * ndim
    spec[batch_axis] = "data"
    return _ns(mesh, spec)


def zero1_sharding(mesh, name, shape):
    """ZeRO-1 sharding for a parameter's optimizer state (and the update).

    TPU mapping of the reference's server-side optimizer: the parameter
    server sharded big arrays over servers and ran the update where the
    shard lived (kvstore_dist_server.h:109-433, sync aggregation). Here
    each data-parallel rank owns a 1/N slice of every optimizer-state
    tensor: grads reduce-scatter onto the slice, the fused update runs on
    the slice, and the fresh params all-gather back. Expressed purely as
    shardings — XLA picks the collectives.

    Rule: start from the parameter's TP spec and additionally partition
    the first still-unsharded dim divisible by the 'data' axis size.
    Tensors with no such dim stay on the TP spec (small; not worth a
    collective).
    """
    base = param_sharding(mesh, name, shape).spec
    if "data" not in mesh.axis_names:
        return _ns(mesh, base)
    dsize = mesh.shape["data"]
    spec = list(base) + [None] * (len(shape) - len(base))
    for d in range(len(shape)):
        if spec[d] is None and shape[d] % dsize == 0 and shape[d] >= dsize:
            spec[d] = "data"
            return _ns(mesh, spec)
    return _ns(mesh, base)


def param_sharding(mesh, name, shape):
    """Default tensor-parallel rule for a parameter.

    FullyConnected weights are (num_hidden, in); sharding dim 0 over
    'model' makes the matmul column-parallel (Megatron-style) — XLA
    all-gathers activations / psums partials as needed. Conv weights are
    (O,I,H,W); shard O. Anything not divisible stays replicated. This is
    the round-1 heuristic surface; per-layer annotations (ctx_group
    analogue) override via Symbol attrs `__shard__`.

    On a mesh with an 'expert' axis, per-expert stacked weights
    (leading dim = num_experts, names carrying 'expert') live sharded
    over it — each device holds only its resident experts' parameters
    AND optimizer state, matching moe_ffn's all_to_all layout.
    """
    if "expert" in mesh.axis_names and "expert" in name and \
            len(shape) >= 1 and shape[0] % mesh.shape["expert"] == 0:
        return _ns(mesh, ["expert"] + [None] * (len(shape) - 1))
    if "model" not in mesh.axis_names:
        return NamedSharding(mesh, P())
    msize = mesh.shape["model"]
    if len(shape) >= 2 and shape[0] % msize == 0 and (
            name.endswith("_weight") or name.endswith("weight")):
        return _ns(mesh, ["model"] + [None] * (len(shape) - 1))
    if len(shape) == 1 and shape[0] % msize == 0 and \
            name.endswith("_bias"):
        return NamedSharding(mesh, P("model"))
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# the layout interface
# ---------------------------------------------------------------------------

def parse_spec(spec):
    """Rule grammar -> tuple of per-dim entries (None | axis | tuple).

    Accepts a PartitionSpec, a tuple/list (entries: None, 'axis', or a
    tuple of axes sharing one dim), or a string: comma-separated dims,
    '+'-joined axes within one dim, None/'' for replicated dims —
    ``"fsdp,None"``, ``"data+fsdp"``, ``"fsdp,tp"``.
    """
    if isinstance(spec, P):
        parts = list(spec)
    elif isinstance(spec, (tuple, list)):
        parts = list(spec)
    else:
        parts = [p.strip() for p in
                 str(spec).strip().strip("()").split(",")]
        parts = [tuple(a.strip() for a in p.split("+")) if "+" in p
                 else p for p in parts]
    out = []
    for p in parts:
        if p is None or p in ("", "None", "none"):
            out.append(None)
        elif isinstance(p, (tuple, list)):
            sub = tuple(str(a) for a in p
                        if a not in (None, "", "None", "none"))
            out.append(sub if len(sub) > 1 else
                       (sub[0] if sub else None))
        else:
            out.append(str(p))
    return tuple(out)


def _entry_axes(entry):
    """A spec entry as a tuple of axis names (possibly empty)."""
    if entry is None:
        return ()
    return entry if isinstance(entry, tuple) else (entry,)


class _HeuristicLayout:
    """The pre-registry name-suffix heuristics behind a bare ``mesh=``
    argument (param_sharding / zero1_sharding / batch_sharding) — kept
    bit-for-bit so existing mesh users are untouched, but expressed as
    a layout so TrainStep/Module have ONE placement path."""

    def __init__(self, mesh):
        self.mesh = mesh

    @property
    def batch_axes(self):
        return ("data",) if "data" in self.mesh.axis_names else ()

    # optimizer state folds over the same axes the batch shards over
    zero_axes = batch_axes

    def param_nsharding(self, name, shape):
        return param_sharding(self.mesh, name, shape)

    def opt_nsharding(self, name, shape, zero=False):
        if zero:
            return zero1_sharding(self.mesh, name, shape)
        return param_sharding(self.mesh, name, shape)

    def batch_nsharding(self, ndim, batch_axis=0):
        if not self.batch_axes:
            # sp/pipe/expert-only meshes: batch enters replicated and
            # the mesh-aware ops (ring attention etc.) shard as needed
            return replicated(self.mesh)
        return batch_sharding(self.mesh, ndim, batch_axis)

    def replicated_nsharding(self):
        return replicated(self.mesh)

    def act_parts(self, ndim):
        """No boundary constraints on the heuristic path (unchanged
        legacy behavior; __shard__/__shard_hint__ attrs still apply)."""
        return None

    def describe(self):
        return "heuristic layout over mesh %r (param_sharding " \
            "name-suffix rules; __shard__ attrs override)" \
            % dict(self.mesh.shape)


class SpecLayout:
    """Ordered partition-spec registry over a named mesh.

    rules: sequence of ``(pattern, spec)`` — pattern matches parameter
    names exactly or as a glob (``fnmatch``: ``*``, ``?``, ``[...]``),
    FIRST match wins; spec is a PartitionSpec / tuple / grammar string
    (see ``parse_spec``). Parameters no rule claims fall to the auto
    rule: shard the largest dim divisible by the ``fsdp`` axis over it,
    replicate the rest; tensors under ``min_shard_size`` elements
    (default MXNET_FSDP_MIN_SIZE) replicate — a per-layer all-gather
    costs more than the memory it saves on tiny tensors.

    Validation raises ValueError (never an assert): unknown axes at
    construction, rank/divisibility violations at first placement —
    each message names the rule, the parameter and the offending sizes.

    ``describe()`` (after placement, e.g. ``TrainStep.init_state``)
    reports which rule claimed each parameter and the per-device shard.
    """

    def __init__(self, mesh, rules=(), min_shard_size=None,
                 constrain_activations=None):
        from .. import config as _config
        self.mesh = mesh
        self.min_shard_size = int(
            _config.get("MXNET_FSDP_MIN_SIZE")
            if min_shard_size is None else min_shard_size)
        self.constrain_activations = bool(
            _config.get("MXNET_GSPMD_CONSTRAIN_ACTS")
            if constrain_activations is None else constrain_activations)
        self.rules = []
        for i, rule in enumerate(rules):
            try:
                pat, spec = rule
            except (TypeError, ValueError):
                raise ValueError(
                    "SpecLayout rule %d must be a (pattern, spec) "
                    "pair, got %r" % (i, rule))
            parts = parse_spec(spec)
            seen = set()
            for entry in parts:
                for ax in _entry_axes(entry):
                    if ax not in mesh.axis_names:
                        raise ValueError(
                            "SpecLayout rule %d (%r -> %r): axis %r is "
                            "not a mesh axis %r"
                            % (i, pat, spec, ax, mesh.axis_names))
                    if ax in seen:
                        raise ValueError(
                            "SpecLayout rule %d (%r -> %r): axis %r "
                            "appears on more than one dim"
                            % (i, pat, spec, ax))
                    seen.add(ax)
            self.rules.append((str(pat), parts))
        self._claims = {}   # name -> (label, parts, shape)

    @property
    def batch_axes(self):
        return tuple(a for a in REPLICA_AXES
                     if a in self.mesh.axis_names)

    # the replica axes optimizer state folds over under zero1 — the
    # data×fsdp product is the ZeRO shard count N
    zero_axes = batch_axes

    # -- rule resolution ---------------------------------------------------
    def spec_for(self, name, shape):
        """(per-dim parts, rule label) for a parameter. Explicit rules
        that cannot apply (rank/divisibility) fail loudly — first-match-
        wins means a bad glob silently falling through would mask a
        layout bug."""
        shape = tuple(shape)
        for i, (pat, parts) in enumerate(self.rules):
            if not fnmatch.fnmatchcase(name, pat):
                continue
            label = "rule[%d] %r" % (i, pat)
            if len(parts) > len(shape):
                raise ValueError(
                    "SpecLayout %s claims %r (shape %r) but its spec "
                    "%r has more dims than the parameter — narrow the "
                    "pattern or shorten the spec"
                    % (label, name, shape, parts))
            for d, entry in enumerate(parts):
                axes = _entry_axes(entry)
                if not axes:
                    continue
                n = int(np.prod([self.mesh.shape[a] for a in axes]))
                if shape[d] % n != 0:
                    raise ValueError(
                        "SpecLayout %s claims %r but dim %d (size %d) "
                        "is not divisible by %r (total shards %d) — "
                        "put a more specific rule first or replicate "
                        "this parameter"
                        % (label, name, d, shape[d], entry, n))
            return parts + (None,) * (len(shape) - len(parts)), label
        return self._auto(shape)

    def _auto(self, shape):
        """Auto rule: shard the LARGEST divisible dim over 'fsdp',
        replicate the rest; tiny tensors replicate outright."""
        shape = tuple(shape)
        rep = (None,) * len(shape)
        if "fsdp" not in self.mesh.axis_names or not shape:
            return rep, "auto:replicated (no fsdp axis)"
        if int(np.prod(shape)) < self.min_shard_size:
            return rep, "auto:replicated (< %d elements)" \
                % self.min_shard_size
        f = self.mesh.shape["fsdp"]
        best = None
        for d, s in enumerate(shape):
            if s % f == 0 and s >= f and (best is None
                                          or s > shape[best]):
                best = d
        if best is None:
            return rep, "auto:replicated (no dim divisible by fsdp=%d)" \
                % f
        parts = list(rep)
        parts[best] = "fsdp"
        return tuple(parts), "auto:fsdp@dim%d" % best

    # -- the layout interface ---------------------------------------------
    def param_nsharding(self, name, shape):
        parts, label = self.spec_for(name, shape)
        self._claims[name] = (label, parts, tuple(shape))
        return _ns(self.mesh, parts)

    def opt_nsharding(self, name, shape, zero=False):
        """Optimizer-state sharding. ``zero=True`` (the sharded-
        optimizer path) starts from the parameter's own spec and folds
        every still-unused replica axis (data, fsdp) into the first dim
        it divides — the weight update then runs on a
        1/(data·fsdp) slice per device and XLA inserts the
        reduce-scatter/all-gather pair (arXiv 2004.13336)."""
        parts, _ = self.spec_for(name, shape)
        if not zero:
            return _ns(self.mesh, parts)
        parts = list(parts)
        used = {a for e in parts for a in _entry_axes(e)}
        for ax in self.zero_axes:
            if ax in used:
                continue
            axn = self.mesh.shape[ax]
            for d in range(len(parts)):
                cur = _entry_axes(parts[d])
                have = int(np.prod([self.mesh.shape[a] for a in cur])) \
                    if cur else 1
                if shape[d] % (have * axn) == 0 and \
                        shape[d] >= have * axn:
                    merged = cur + (ax,)
                    parts[d] = merged if len(merged) > 1 else merged[0]
                    used.add(ax)
                    break
        return _ns(self.mesh, parts)

    def batch_nsharding(self, ndim, batch_axis=0):
        axes = self.batch_axes
        parts = [None] * ndim
        if axes and ndim > 0:
            parts[batch_axis] = axes if len(axes) > 1 else axes[0]
        return _ns(self.mesh, parts)

    def replicated_nsharding(self):
        return replicated(self.mesh)

    def act_parts(self, ndim):
        """Lenient per-dim parts pinning an activation's batch dim to
        the data axes at module boundaries (BOUNDARY_OPS), or None when
        constraints are off / there is nothing to pin. The executor
        applies these with strict=False: an indivisible or lower-rank
        tensor is skipped, never an error."""
        if not self.constrain_activations or ndim == 0:
            return None
        axes = self.batch_axes
        if not axes:
            return None
        head = axes if len(axes) > 1 else axes[0]
        return (head,) + (None,) * (ndim - 1)

    def describe(self):
        """Human-readable placement report: one line per parameter the
        layout has claimed (global shape → per-device shard, claiming
        rule), plus any rule that matched nothing."""
        lines = ["SpecLayout over mesh %r (%d devices)"
                 % (dict(self.mesh.shape), self.mesh.size)]
        matched = set()
        for name in sorted(self._claims):
            label, parts, shape = self._claims[name]
            if label.startswith("rule["):
                matched.add(label.split()[0])
            shard = NamedSharding(self.mesh, P(*parts)) \
                .shard_shape(shape)
            lines.append("  %-32s %s -> %s  spec=%r  [%s]"
                         % (name, "x".join(map(str, shape)) or "()",
                            "x".join(map(str, shard)) or "()",
                            tuple(parts), label))
        for i, (pat, _parts) in enumerate(self.rules):
            if "rule[%d]" % i not in matched:
                lines.append("  rule[%d] %r matched no parameter"
                             % (i, pat))
        if not self._claims:
            lines.append("  (no parameters placed yet — call "
                         "init_state/bind first)")
        return "\n".join(lines)


def as_layout(mesh_or_layout):
    """Normalize a mesh-or-layout argument to a layout (None stays
    None): the single seam through which TrainStep and the module
    executor group bind placement."""
    if mesh_or_layout is None:
        return None
    if isinstance(mesh_or_layout, Mesh):
        return _HeuristicLayout(mesh_or_layout)
    return mesh_or_layout
