"""Multi-host distributed init — the replacement for the reference's
ps-lite scheduler/tracker (SURVEY.md N16/N25, tools/launch.py).

The reference cluster: a scheduler node + N workers + M servers wired by
env vars (DMLC_ROLE, DMLC_PS_ROOT_URI...). TPU-native: every host runs the
SAME SPMD program; `jax.distributed.initialize` (coordinator address +
process id) replaces the scheduler; the global device mesh spans hosts over
DCN and collectives replace push/pull. `dist_async` (server applies updates
as they arrive) is deliberately not a collective: it runs as a host-side
parameter server instead (parallel/ps_async.py; the server role in
kvstore_server.py serves it when MXNET_KVSTORE_TYPE=dist_async).

Env compat shims: DMLC_* vars map onto the JAX coordinator so reference
launch scripts keep working.
"""
from __future__ import annotations

import os

import jax

__all__ = ["init", "rank", "size", "is_initialized", "default_mesh"]

_initialized = False


def init(coordinator_address=None, num_processes=None, process_id=None):
    """Initialize multi-host JAX from args or DMLC_*/JAX env vars."""
    global _initialized
    if _initialized:
        return
    if coordinator_address is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9000")
        if uri:
            coordinator_address = "%s:%s" % (uri, port)
    if num_processes is None:
        nw = os.environ.get("DMLC_NUM_WORKER")
        num_processes = int(nw) if nw else None
    if process_id is None:
        pid = os.environ.get("DMLC_WORKER_ID")
        process_id = int(pid) if pid else None
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    _initialized = True


def is_initialized():
    return _initialized


def default_mesh(axis_sizes=None):
    """The sensible pod-scale ``data × fsdp`` mesh for the GSPMD
    one-jit path (docs/parallelism.md): ``fsdp`` spans the devices of
    one host/slice (parameter all-gathers ride ICI, the fast fabric),
    ``data`` spans hosts (only grad reduce-scatters cross DCN) —
    the topology split arXiv 2004.13336's weight-update sharding
    assumes. Single-process runs get ``data=1, fsdp=all``.

    axis_sizes: optional override dict forwarded to
    ``sharding.make_mesh`` (e.g. add ``{"tp": 2}``); validated against
    the visible device count with an actionable ValueError.
    """
    from .sharding import make_mesh
    if axis_sizes is not None:
        return make_mesh(axis_sizes)
    # jax.devices() id order is NOT guaranteed to group by host; the
    # (hosts, n//hosts) reshape below only puts one host's devices in
    # one fsdp group if we sort them that way first
    devs = sorted(jax.devices(),
                  key=lambda d: (d.process_index, d.id))
    n = len(devs)
    hosts = jax.process_count()
    if n % hosts != 0:
        # heterogeneous host/device split: fall back to pure fsdp
        return make_mesh({"data": 1, "fsdp": n}, devices=devs)
    return make_mesh({"data": hosts, "fsdp": n // hosts}, devices=devs)


def rank():
    return jax.process_index()


def size():
    return jax.process_count()
