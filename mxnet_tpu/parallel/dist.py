"""Multi-host distributed init — the replacement for the reference's
ps-lite scheduler/tracker (SURVEY.md N16/N25, tools/launch.py).

The reference cluster: a scheduler node + N workers + M servers wired by
env vars (DMLC_ROLE, DMLC_PS_ROOT_URI...). TPU-native: every host runs the
SAME SPMD program; `jax.distributed.initialize` (coordinator address +
process id) replaces the scheduler; the global device mesh spans hosts over
DCN and collectives replace push/pull. `dist_async` (server applies updates
as they arrive) is deliberately not a collective: it runs as a host-side
parameter server instead (parallel/ps_async.py; the server role in
kvstore_server.py serves it when MXNET_KVSTORE_TYPE=dist_async).

Env compat shims: DMLC_* vars map onto the JAX coordinator so reference
launch scripts keep working.
"""
from __future__ import annotations

import os

import jax

__all__ = ["init", "rank", "size", "is_initialized"]

_initialized = False


def init(coordinator_address=None, num_processes=None, process_id=None):
    """Initialize multi-host JAX from args or DMLC_*/JAX env vars."""
    global _initialized
    if _initialized:
        return
    if coordinator_address is None:
        uri = os.environ.get("DMLC_PS_ROOT_URI")
        port = os.environ.get("DMLC_PS_ROOT_PORT", "9000")
        if uri:
            coordinator_address = "%s:%s" % (uri, port)
    if num_processes is None:
        nw = os.environ.get("DMLC_NUM_WORKER")
        num_processes = int(nw) if nw else None
    if process_id is None:
        pid = os.environ.get("DMLC_WORKER_ID")
        process_id = int(pid) if pid else None
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    _initialized = True


def is_initialized():
    return _initialized


def rank():
    return jax.process_index()


def size():
    return jax.process_count()
