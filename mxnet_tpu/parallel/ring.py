"""Ring attention — sequence/context parallelism over the device mesh.

New TPU-native capability (SURVEY §2.3: the reference has NO sequence
parallelism; its long-sequence story was bucketing + BPTT truncation).
This is the standard ring schedule (Liu et al., Ring Attention, 2023):
queries stay put, key/value blocks rotate around the mesh axis via
``lax.ppermute`` (riding ICI neighbour links), and the flash-style
online softmax merges each visiting block — every device holds only
T/n of the sequence at any moment, so max context scales linearly with
the mesh axis while compute stays MXU-dense per block.

Compose with data/tensor parallel axes freely: q/k/v enter sharded
(B, H, T, D) with T split over ``axis_name``; output keeps that
sharding.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._compat import pvary as _pvary, shard_map as _shard_map

__all__ = ["ring_attention"]

_NEG_INF = -1e30


def _ring_local(q, k, v, *, axis_name, causal, scale):
    """Per-device body: q (B,H,Tq,D) local; k/v local blocks that will
    rotate n-1 times.

    Each visiting block runs the Pallas flash kernel (MXU-dense,
    O(Tq + Tk) memory — no (Tq, Tk) score materialization, so local
    shards can be tens of thousands of tokens) returning normalized
    (o, lse); blocks combine by logsumexp merge. The causal mask over
    GLOBAL positions reduces, for equal shards, to three whole-block
    cases on the visiting block id: src < me fully visible, src == me
    the standard diagonal, src > me skipped."""
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    from ..ops.attention import flash_attention_with_lse
    q3 = q.reshape(B * H, Tq, D)

    def block_attend(k_cur, v_cur, src):
        k3 = k_cur.reshape(B * H, Tk, D)
        v3 = v_cur.reshape(B * H, Tk, D)

        def full(_):
            return flash_attention_with_lse(q3, k3, v3, scale=scale,
                                            causal=False)

        def diag(_):
            return flash_attention_with_lse(q3, k3, v3, scale=scale,
                                            causal=True)

        def skip(_):
            # fresh constants are replicated-typed; match the kernel
            # branches' device-varying outputs for lax.switch
            return tuple(_pvary(x, (axis_name,)) for x in (
                jnp.zeros(q3.shape, q3.dtype),
                jnp.full((B * H, Tq), _NEG_INF, jnp.float32)))

        if not causal:
            return full(None)
        if Tq != Tk:
            raise ValueError("causal ring attention needs equal "
                             "sequence shards (Tq=%d, Tk=%d)"
                             % (Tq, Tk))
        idx = jnp.where(src == me, 1, jnp.where(src < me, 0, 2))
        return lax.switch(idx, [full, diag, skip], None)

    def merge(o_acc, lse_acc, o_b, lse_b):
        lse = jnp.logaddexp(lse_acc, lse_b)
        w_a = jnp.exp(lse_acc - lse)[..., None]
        w_b = jnp.exp(lse_b - lse)[..., None]
        return (o_acc * w_a + o_b.astype(jnp.float32) * w_b, lse)

    o0 = jnp.zeros((B * H, Tq, D), jnp.float32)
    lse0 = jnp.full((B * H, Tq), _NEG_INF, jnp.float32)
    # constants enter the loop carry device-varying (their updates vary
    # over the ring axis; shard_map type-checks this)
    o0, lse0 = (_pvary(x, (axis_name,)) for x in (o0, lse0))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(t, carry):
        k_cur, v_cur, o_acc, lse_acc = carry
        o_b, lse_b = block_attend(k_cur, v_cur, (me - t) % n)
        o_acc, lse_acc = merge(o_acc, lse_acc, o_b, lse_b)
        # rotate KV to the next neighbour (ICI hop), overlapping with
        # the next block's compute under XLA's async collectives
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, o_acc, lse_acc

    # n-1 rotations visit every remote block; the final visiting block is
    # consumed without a wasted last rotation (a collective in the loop
    # tail cannot be DCE'd by XLA)
    k_last, v_last, o_acc, lse_acc = lax.fori_loop(
        0, n - 1, step, (k, v, o0, lse0))
    o_b, lse_b = block_attend(k_last, v_last, (me - (n - 1)) % n)
    o_acc, _ = merge(o_acc, lse_acc, o_b, lse_b)
    return o_acc.reshape(B, H, Tq, D).astype(q.dtype)


def _ring_local_windowed(q, k, v, *, axis_name, scale, window, n):
    """Windowed (banded causal) ring body, UNROLLED over visiting-block
    distance t — n is static, so each step's band offset t*Tb is a
    static kernel parameter and, crucially, the loop runs only
    r = ceil((window-1)/Tb) rotations instead of n-1: a window reaches
    at most r predecessor blocks, so the ring only has to carry K/V
    that far (communication O(window), not O(T))."""
    me = lax.axis_index(axis_name)
    B, H, Tq, D = q.shape
    Tb = k.shape[2]
    if Tq != Tb:
        raise ValueError("windowed ring attention needs equal "
                         "sequence shards (Tq=%d, Tk=%d)" % (Tq, Tb))
    from ..ops.attention import flash_attention_with_lse
    q3 = q.reshape(B * H, Tq, D)
    r = 0 if window <= 1 else min(n - 1, (window - 2) // Tb + 1)

    def merge(o_acc, lse_acc, o_b, lse_b):
        lse = jnp.logaddexp(lse_acc, lse_b)
        w_a = jnp.exp(lse_acc - lse)[..., None]
        w_b = jnp.exp(lse_b - lse)[..., None]
        return (o_acc * w_a + o_b.astype(jnp.float32) * w_b, lse)

    o_acc = _pvary(jnp.zeros((B * H, Tq, D), jnp.float32),
                   (axis_name,))
    lse_acc = _pvary(jnp.full((B * H, Tq), _NEG_INF, jnp.float32),
                     (axis_name,))
    perm = [(j, (j + 1) % n) for j in range(n)]
    k_cur, v_cur = k, v
    for t in range(r + 1):
        k3 = k_cur.reshape(B * H, Tb, D)
        v3 = v_cur.reshape(B * H, Tb, D)

        def compute(_, k3=k3, v3=v3, t=t):
            return flash_attention_with_lse(
                q3, k3, v3, scale=scale, causal=True, window=window,
                band_offset=t * Tb)

        def skip(_):
            return tuple(_pvary(x, (axis_name,)) for x in (
                jnp.zeros(q3.shape, q3.dtype),
                jnp.full((B * H, Tq), _NEG_INF, jnp.float32)))

        if t == 0:
            o_b, lse_b = compute(None)
        else:
            # devices whose t-th predecessor wraps past position 0
            # have no such block (causal): skip at run time
            o_b, lse_b = lax.cond(me >= t, compute, skip, None)
        o_acc, lse_acc = merge(o_acc, lse_acc, o_b, lse_b)
        if t < r:
            k_cur = lax.ppermute(k_cur, axis_name, perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm)
    return o_acc.reshape(B, H, Tq, D).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False,
                   scale=None, window=0):
    """Sequence-parallel attention: (B, H, T, D) inputs with T sharded
    over ``mesh`` axis ``axis_name``; output sharded the same way.

    window > 0 (causal only) runs the BANDED ring: each device visits
    only the predecessor blocks its window reaches, so both compute
    and ring communication scale with the window, not the context."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if window and not causal:
        raise ValueError("window attention requires causal=True")
    spec = P(None, None, axis_name, None)
    if window:
        body = functools.partial(
            _ring_local_windowed, axis_name=axis_name,
            scale=float(scale), window=int(window),
            n=int(mesh.shape[axis_name]))
    else:
        body = functools.partial(_ring_local, axis_name=axis_name,
                                 causal=causal, scale=float(scale))
    fn = _shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec)
    return fn(q, k, v)
