"""Ring attention — sequence/context parallelism over the device mesh.

New TPU-native capability (SURVEY §2.3: the reference has NO sequence
parallelism; its long-sequence story was bucketing + BPTT truncation).
This is the standard ring schedule (Liu et al., Ring Attention, 2023):
queries stay put, key/value blocks rotate around the mesh axis via
``lax.ppermute`` (riding ICI neighbour links), and the flash-style
online softmax merges each visiting block — every device holds only
T/n of the sequence at any moment, so max context scales linearly with
the mesh axis while compute stays MXU-dense per block.

Compose with data/tensor parallel axes freely: q/k/v enter sharded
(B, H, T, D) with T split over ``axis_name``; output keeps that
sharding.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._compat import pvary as _pvary, shard_map as _shard_map

__all__ = ["ring_attention"]

_NEG_INF = -1e30


def _ring_local(q, k, v, *, axis_name, causal, scale):
    """Per-device body: q (B,H,Tq,D) local; k/v local blocks that will
    rotate n-1 times."""
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    B, H, Tq, D = q.shape
    Tk = k.shape[2]

    m0 = jnp.full((B, H, Tq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, H, Tq, 1), jnp.float32)
    acc0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    # constants enter the loop carry device-varying (their updates vary
    # over the ring axis; shard_map type-checks this)
    m0, l0, acc0 = (_pvary(x, (axis_name,)) for x in (m0, l0, acc0))
    perm = [(j, (j + 1) % n) for j in range(n)]

    def attend(t, k_cur, v_cur, m, l, acc):
        src = (me - t) % n               # global block id of k_cur
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_cur,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            rows = me * Tq + jnp.arange(Tq)[:, None]
            cols = src * Tk + jnp.arange(Tk)[None, :]
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(v_cur.dtype), v_cur,
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    def step(t, carry):
        k_cur, v_cur, m, l, acc = carry
        m, l, acc = attend(t, k_cur, v_cur, m, l, acc)
        # rotate KV to the next neighbour (ICI hop), overlapping with
        # the next block's compute under XLA's async collectives
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, m, l, acc

    # n-1 rotations visit every remote block; the final visiting block is
    # consumed without a wasted last rotation (a collective in the loop
    # tail cannot be DCE'd by XLA)
    k_last, v_last, m, l, acc = lax.fori_loop(
        0, n - 1, step, (k, v, m0, l0, acc0))
    m, l, acc = attend(n - 1, k_last, v_last, m, l, acc)
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False,
                   scale=None):
    """Sequence-parallel attention: (B, H, T, D) inputs with T sharded
    over ``mesh`` axis ``axis_name``; output sharded the same way."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, None, axis_name, None)
    fn = _shard_map(
        functools.partial(_ring_local, axis_name=axis_name,
                          causal=causal, scale=float(scale)),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
