"""Pipeline parallelism — stages sharded over a mesh axis, GPipe
microbatch schedule.

New TPU-native capability (SURVEY §2.3: the reference's nearest feature
is `PartialForward` staged execution + the model-parallel LSTM example;
it has no pipeline schedule). Each device on the ``pipe`` axis holds ONE
stage's parameters; microbatches stream through, activations hop to the
next stage over ``lax.ppermute`` (neighbour ICI links). The bubble is
the standard (S-1)/(M+S-1) GPipe fraction.

The schedule runs inside ``shard_map`` and is itself jittable/
differentiable — wrap it in a loss and `jax.grad` works through the
collectives, so the same function serves train and inference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._compat import pvary as _pvary, shard_map as _shard_map

__all__ = ["pipeline_apply"]


def pipeline_apply(stage_fn, stage_params, microbatches, mesh,
                   axis_name="pipe"):
    """Run ``stage_fn`` composed over S pipeline stages.

    stage_fn(params_i, x) -> y: one stage's computation; every stage
        must map (mb, ...) -> (mb, ...) of the same shape/dtype (pad
        feature dims to a common width if stages differ).
    stage_params: pytree whose leaves have leading dim S (stage i's
        slice lives on device i of the axis).
    microbatches: (M, mb, ...) — M microbatches streamed through.
    Returns (M, mb, ...): stage S-1's outputs for every microbatch,
    replicated across the axis.

    Equivalent to ``for p in stages: x = stage_fn(p, x)`` per
    microbatch (asserted in tests/test_pipeline_moe.py).
    """
    S = mesh.shape[axis_name]
    M = microbatches.shape[0]
    fwd_perm = [(j, (j + 1) % S) for j in range(S)]

    def local(params, stream):
        # params: leaves (1, ...) = my stage; stream: (M, mb, ...) the
        # full microbatch queue (replicated — activations, not params)
        my = jax.tree.map(lambda l: l[0], params)
        me = lax.axis_index(axis_name)
        mb_shape = stream.shape[1:]
        carry = jnp.zeros(mb_shape, stream.dtype)
        carry = _pvary(carry, (axis_name,))
        outs0 = jnp.zeros((M,) + mb_shape, stream.dtype)
        outs0 = _pvary(outs0, (axis_name,))

        def tick(t, state):
            carry, outs = state
            # stage 0 ingests microbatch t (zeros once the stream ends)
            feed = lax.dynamic_index_in_dim(
                stream, jnp.minimum(t, M - 1), 0, keepdims=False)
            feed = jnp.where(t < M, feed, jnp.zeros_like(feed))
            x = jnp.where(me == 0, feed, carry)
            y = stage_fn(my, x)
            # microbatch t reaches the last stage at tick t + S - 1
            out_slot = t - (S - 1)
            take = (me == S - 1) & (out_slot >= 0)
            outs = lax.cond(
                take,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_slot, 0), 0),
                lambda o: o, outs)
            carry = lax.ppermute(y, axis_name, fwd_perm)
            return carry, outs

        _, outs = lax.fori_loop(0, M + S - 1, tick, (carry, outs0))
        # replicate the last stage's collected outputs to every device
        return lax.psum(jnp.where(me == S - 1, outs, 0.0), axis_name)

    pspec = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = _shard_map(local, mesh=mesh,
                    in_specs=(pspec, P()),
                    out_specs=P())
    return fn(stage_params, microbatches)
