"""Pipeline parallelism — stages sharded over a mesh axis, GPipe
microbatch schedule.

New TPU-native capability (SURVEY §2.3: the reference's nearest feature
is `PartialForward` staged execution + the model-parallel LSTM example;
it has no pipeline schedule). Each device on the ``pipe`` axis holds ONE
stage's parameters; microbatches stream through, activations hop to the
next stage over ``lax.ppermute`` (neighbour ICI links). The bubble is
the standard (S-1)/(M+S-1) GPipe fraction.

The schedule runs inside ``shard_map`` and is itself jittable/
differentiable — wrap it in a loss and `jax.grad` works through the
collectives, so the same function serves train and inference.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._compat import pvary as _pvary, shard_map as _shard_map

__all__ = ["pipeline_apply", "pipeline_from_symbol"]


def pipeline_apply(stage_fn, stage_params, microbatches, mesh,
                   axis_name="pipe"):
    """Run ``stage_fn`` composed over S pipeline stages.

    stage_fn(params_i, x) -> y: one stage's computation; every stage
        must map (mb, ...) -> (mb, ...) of the same shape/dtype (pad
        feature dims to a common width if stages differ). A 3-argument
        stage_fn additionally receives the schedule tick t (traced
        int32) — combine it with ``lax.axis_index(axis_name)`` for
        per-(stage, microbatch) randomness (dropout keys).
    stage_params: pytree whose leaves have leading dim S (stage i's
        slice lives on device i of the axis).
    microbatches: (M, mb, ...) — M microbatches streamed through.
    Returns (M, mb, ...): stage S-1's outputs for every microbatch,
    replicated across the axis.

    Equivalent to ``for p in stages: x = stage_fn(p, x)`` per
    microbatch (asserted in tests/test_pipeline_moe.py).
    """
    import inspect

    S = mesh.shape[axis_name]
    M = microbatches.shape[0]
    fwd_perm = [(j, (j + 1) % S) for j in range(S)]
    # tick is passed only to a stage_fn whose THIRD parameter is a
    # plain positional without a default — a defaulted/keyword-only
    # third param (eps=1e-6, *, cfg=None) must not receive it
    _pos = [p for p in inspect.signature(stage_fn).parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    takes_tick = len(_pos) >= 3 and _pos[2].default is _pos[2].empty

    def local(params, stream):
        # params: leaves (1, ...) = my stage; stream: (M, mb, ...) the
        # full microbatch queue (replicated — activations, not params)
        my = jax.tree.map(lambda l: l[0], params)
        me = lax.axis_index(axis_name)
        mb_shape = stream.shape[1:]
        carry = jnp.zeros(mb_shape, stream.dtype)
        carry = _pvary(carry, (axis_name,))
        outs0 = jnp.zeros((M,) + mb_shape, stream.dtype)
        outs0 = _pvary(outs0, (axis_name,))

        def tick(t, state):
            carry, outs = state
            # stage 0 ingests microbatch t (zeros once the stream ends)
            feed = lax.dynamic_index_in_dim(
                stream, jnp.minimum(t, M - 1), 0, keepdims=False)
            feed = jnp.where(t < M, feed, jnp.zeros_like(feed))
            x = jnp.where(me == 0, feed, carry)
            y = stage_fn(my, x, t) if takes_tick else stage_fn(my, x)
            # microbatch t reaches the last stage at tick t + S - 1
            out_slot = t - (S - 1)
            take = (me == S - 1) & (out_slot >= 0)
            outs = lax.cond(
                take,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_slot, 0), 0),
                lambda o: o, outs)
            carry = lax.ppermute(y, axis_name, fwd_perm)
            return carry, outs

        _, outs = lax.fori_loop(0, M + S - 1, tick, (carry, outs0))
        # replicate the last stage's collected outputs to every device
        return lax.psum(jnp.where(me == S - 1, outs, 0.0), axis_name)

    pspec = jax.tree.map(lambda _: P(axis_name), stage_params)
    fn = _shard_map(local, mesh=mesh,
                    in_specs=(pspec, P()),
                    out_specs=P())
    return fn(stage_params, microbatches)


def pipeline_from_symbol(layer_sym, stage_params, microbatches, mesh,
                         axis_name="pipe", data_name="data",
                         is_train=False, rng=None):
    """GPipe over a SYMBOL-defined stage — pipeline parallelism for the
    symbolic API (dp/tp: TrainStep mesh; sp: seq_axis; ep: expert_axis;
    this is the pp leg).

    layer_sym: a Symbol mapping input ``data_name`` of shape
        (mb, ...) to a single same-shape/dtype output — e.g.
        ``models.transformer.get_stage_symbol``. Must carry no
        auxiliary states (BN moving stats can't live inside the
        rotating schedule; use LayerNorm-style stages).
    stage_params: dict name -> (S, ...) stacked per-stage values for
        every non-data argument of ``layer_sym`` (stage i's slice is
        row i).
    microbatches: (M, mb, ...) streamed through all S stages.
    Returns (M, mb, ...), differentiable; same contract as
    ``pipeline_apply``.
    """
    from ..executor import _graph_eval_fn

    if layer_sym.list_auxiliary_states():
        raise ValueError(
            "pipeline stages cannot carry auxiliary states %r — the "
            "GPipe schedule has no slot for cross-microbatch mutable "
            "state" % layer_sym.list_auxiliary_states())
    if data_name not in layer_sym.list_arguments():
        raise ValueError(
            "data_name %r is not an argument of the stage symbol "
            "(has %r) — the microbatch stream would be ignored"
            % (data_name, layer_sym.list_arguments()))
    arg_names = [n for n in layer_sym.list_arguments() if n != data_name]
    missing = set(arg_names) - set(stage_params)
    if missing:
        raise ValueError("stage_params missing %r" % sorted(missing))
    if len(layer_sym.list_outputs()) != 1:
        raise ValueError("a pipeline stage must have exactly 1 output, "
                         "got %r" % layer_sym.list_outputs())

    eval_fn = _graph_eval_fn(layer_sym)
    key = rng if rng is not None else jax.random.PRNGKey(0)

    def stage_fn(params, x, t):
        # distinct randomness per (stage, tick): dropout masks must not
        # repeat across stages or microbatches
        k = jax.random.fold_in(
            jax.random.fold_in(key, lax.axis_index(axis_name)), t)
        outs, _aux = eval_fn({**params, data_name: x}, {}, k, is_train)
        return outs[0]

    return pipeline_apply(stage_fn,
                          {n: stage_params[n] for n in arg_names},
                          microbatches, mesh, axis_name=axis_name)
