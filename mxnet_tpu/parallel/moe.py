"""Mixture-of-experts FFN with expert parallelism (Switch-style top-1
routing over ``lax.all_to_all``).

New TPU-native capability (SURVEY §2.3: the reference has no MoE/expert
parallelism). Experts shard over a mesh axis; each device routes its
local tokens, packs them into per-expert capacity buffers, exchanges
buffers with one all_to_all (ICI), runs its resident experts' FFN, and
all_to_alls results back — the canonical TPU MoE dataflow (Shazeer et
al. 2017; Fedus et al., Switch Transformer, 2021).

Top-1 routing with capacity dropping: tokens beyond an expert's
capacity contribute zeros (add the usual residual connection around the
layer so dropped tokens pass through).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ._compat import shard_map as _shard_map

__all__ = ["moe_ffn", "dense_moe"]


def _route(x, gate_w, num_experts, capacity):
    """Top-1 routing of local tokens: returns (expert_id, slot, keep,
    gate_prob) per token — slot is the token's position in its expert's
    capacity buffer, assigned in token order (first come first served,
    the Switch discipline)."""
    probs = jax.nn.softmax(
        (x.astype(jnp.float32) @ gate_w.astype(jnp.float32)), axis=-1)
    gate = jnp.max(probs, axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    onehot = jax.nn.one_hot(expert, num_experts, dtype=jnp.int32)
    slot = jnp.sum((jnp.cumsum(onehot, axis=0) - 1) * onehot, axis=-1)
    keep = slot < capacity
    return expert, jnp.clip(slot, 0, capacity - 1), keep, gate


def _dispatch(x, expert, slot, keep, num_buckets, cap):
    """Scatter kept tokens into (num_buckets, cap, D) capacity
    buffers."""
    disp = jnp.zeros((num_buckets, cap, x.shape[-1]), x.dtype)
    return disp.at[expert, slot].add(jnp.where(keep[:, None], x, 0))


def _combine(y, expert, slot, keep, gate, dtype):
    """Gather each token's expert output back, gated; dropped tokens
    zero."""
    out = y[expert, slot] * gate[:, None].astype(dtype)
    return jnp.where(keep[:, None], out, 0.0).astype(dtype)


def dense_moe(x, gate_w, w1, w2, capacity_factor=1.25):
    """Single-program Switch MoE: route local tokens into capacity
    buffers, run every expert's FFN, combine. Shares _route/_dispatch/
    _combine with the expert-parallel moe_ffn below (which inserts the
    all_to_all exchanges between the same stages).

    x (N, D); gate_w (D, E); w1 (E, D, H); w2 (E, H, D) -> (N, D),
    capacity-dropped tokens zero."""
    N = x.shape[0]
    E = gate_w.shape[1]
    cap = max(1, int(math.ceil(N * float(capacity_factor) / E)))
    expert, slot, keep, gate = _route(x, gate_w, E, cap)
    disp = _dispatch(x, expert, slot, keep, E, cap)
    h = jax.nn.relu(jnp.einsum("ecd,edh->ech", disp, w1))
    y = jnp.einsum("ech,ehd->ecd", h, w2)
    return _combine(y, expert, slot, keep, gate, x.dtype)


def moe_ffn(x, gate_w, w1, w2, mesh, axis_name="expert",
            capacity_factor=1.25):
    """Expert-parallel MoE FFN.

    x: (T, D) tokens, T sharded over ``axis_name``.
    gate_w: (D, E) router weights (replicated).
    w1: (E, D, H), w2: (E, H, D) expert weights, E sharded over the axis.
    Returns (T, D) with x's sharding; dropped-capacity tokens yield 0.
    """
    n = mesh.shape[axis_name]
    E = gate_w.shape[1]
    if E % n:
        raise ValueError("num_experts %d must divide over %d devices"
                         % (E, n))

    def local(xl, gw, w1l, w2l):
        # xl (Tl, D); w1l (El, D, H); w2l (El, H, D)
        Tl, D = xl.shape
        El = E // n
        cap = max(1, int(math.ceil(Tl * capacity_factor / E)))
        expert, slot, keep, gate = _route(xl, gw, E, cap)
        disp = _dispatch(xl, expert, slot, keep, E, cap)
        # exchange: device d keeps buffers for its El resident experts
        # from every sender -> (n senders, El, cap, D)
        recv = lax.all_to_all(disp.reshape(n, El, cap, D), axis_name,
                              split_axis=0, concat_axis=0, tiled=False)
        # recv: (n senders, El, cap, D) -> expert-major token queues
        tokens = recv.transpose(1, 0, 2, 3).reshape(El, n * cap, D)

        h = jax.nn.relu(jnp.einsum("ecd,edh->ech", tokens, w1l))
        y = jnp.einsum("ech,ehd->ecd", h, w2l)          # (El, n*cap, D)

        # back to sender-major and return to the owning devices
        y = y.reshape(El, n, cap, D).transpose(1, 0, 2, 3)
        back = lax.all_to_all(y, axis_name,
                              split_axis=0, concat_axis=0, tiled=False)
        # back: (n expert-groups, El, cap, D); group-major flatten IS
        # global expert order -> my tokens' buffers (E, cap, D)
        mine = back.reshape(E, cap, D)
        return _combine(mine, expert, slot, keep, gate, xl.dtype)

    fn = _shard_map(local, mesh=mesh,
                    in_specs=(P(axis_name), P(), P(axis_name), P(axis_name)),
                    out_specs=P(axis_name))
    return fn(x, gate_w, w1, w2)
