"""jax API compatibility shims shared by the parallel modules."""
from __future__ import annotations

from jax import lax

try:                                     # jax>=0.6 moved shard_map up
    from jax import shard_map as shard_map
except ImportError:                      # pragma: no cover
    from jax.experimental.shard_map import shard_map  # noqa: F401


def pvary(x, axes):
    """Mark a value device-varying over mesh axes (jax 0.9 deprecates
    lax.pvary in favour of lax.pcast(x, axes, to='varying'))."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    return lax.pvary(x, axes)            # pragma: no cover - jax<0.9
