"""jax API compatibility shims shared by the parallel modules."""
from __future__ import annotations

from jax import lax

try:                                     # jax>=0.6 moved shard_map up
    from jax import shard_map as shard_map
except ImportError:                      # pragma: no cover
    from jax.experimental.shard_map import shard_map  # noqa: F401

_HAS_VARY_MARKER = hasattr(lax, "pcast") or hasattr(lax, "pvary")

if not _HAS_VARY_MARKER:                 # jax 0.4
    # jax 0.4 has no varying-marker op at all, but its shard_map still
    # runs the static replication checker (check_rep=True default) —
    # which then rejects exactly the mixed-replication patterns pvary
    # exists to bless (ppermute carries in a scan, cond branches).
    # With no marker to teach it, the faithful shim is to turn the
    # checker off; the collectives themselves are unaffected.
    _shard_map_raw = shard_map

    def shard_map(f, *args, **kwargs):   # noqa: F811
        kwargs.setdefault("check_rep", False)
        return _shard_map_raw(f, *args, **kwargs)


def pvary(x, axes):
    """Mark a value device-varying over mesh axes (jax 0.9 deprecates
    lax.pvary in favour of lax.pcast(x, axes, to='varying')). jax 0.4
    has NEITHER — its shard_map does not track varying-over-mesh-axes
    types (the compat shard_map above disables its replication
    checker), so the identity is the correct shim, not an
    approximation."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axes)        # pragma: no cover - jax 0.5-0.8
    return x
