"""Fault tolerance for the distributed KVStore path (docs/robustness.md).

The reference delegated resilience to ps-lite: a connect/retry loop at
worker start and the scheduler-tracked FINALIZE protocol at job end.
Everything in between — a dropped TCP connection mid-push, a hung
server, a worker that died holding a barrier — was fatal or a hang.
This module supplies the missing middle for the host-side async PS
(`parallel/ps_async.py`):

* :class:`RetryPolicy` — exponential backoff with *deterministic*
  jitter (seeded, so two runs of the same job retry on the same
  schedule), per-op deadlines, and transient-vs-fatal error
  classification. Transport faults (reset, refused, timeout, EOF) are
  transient and worth a reconnect; application errors the server
  *replied* with (bad key, :class:`DeadWorkerError`) are fatal — the
  transport demonstrably works, retrying cannot help.

* :class:`FaultInjector` — deterministic fault injection wrapped
  around the socket send/recv plumbing, driven by the
  ``MXNET_FAULT_SPEC`` env var (or installed programmatically in
  tests). Injects drops, delays, and mid-message disconnects at exact
  call counts, so failure-path tests need no real process kills, no
  sleeps-and-hope, and reproduce bit-identically.

* :class:`DeadWorkerError` — raised to barrier waiters when the
  server's heartbeat monitor declares a cohort member dead (the
  alternative, which this replaces, was every surviving worker
  spinning in the barrier until job end).
"""
from __future__ import annotations

import errno
import os
import re
import socket
import threading
import time
import zlib

__all__ = ["DeadWorkerError", "FaultInjected", "FaultInjector",
           "RetryPolicy", "active_injector", "install_fault_injector"]


class DeadWorkerError(RuntimeError):
    """A worker in the cohort was declared dead (heartbeat lapse).

    Raised server-side to every barrier waiter — the cohort can never
    complete, so surviving workers fail loudly instead of hanging.
    Under ``MXNET_PS_ELASTIC=1`` the server shrinks the cohort instead
    and this error is not raised."""


class FaultInjected(ConnectionError):
    """The error a :class:`FaultInjector` rule raises — a subclass of
    ConnectionError so retry classification treats it exactly like the
    real transport fault it simulates."""


# errno values that indicate a transport-level (retryable) failure
_TRANSIENT_ERRNOS = frozenset(
    getattr(errno, name) for name in
    ("ECONNREFUSED", "ECONNRESET", "ECONNABORTED", "EPIPE", "ETIMEDOUT",
     "EHOSTUNREACH", "ENETUNREACH", "ENETRESET", "EAGAIN")
    if hasattr(errno, name))


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return float(default)


class RetryPolicy:
    """Exponential backoff with deterministic jitter and a per-op
    deadline.

    delay(attempt) = min(base * multiplier^(attempt-1), max_delay)
                     * (0.5 + 0.5 * jitter_frac(seed, attempt))

    The jitter fraction is a crc32 of ``(seed, attempt)`` — spread
    across workers (each seeds with its worker id) yet bit-reproducible
    run to run, so a fault-injection test replays the exact schedule.

    Env defaults: ``MXNET_PS_RETRY_MAX`` (8 retries),
    ``MXNET_PS_RETRY_BASE`` (0.05s), ``MXNET_PS_RETRY_MAX_DELAY`` (2s),
    ``MXNET_PS_OP_DEADLINE`` (120s; 0 = unlimited) — the total budget
    for one op *including* its retries and backoff sleeps."""

    def __init__(self, max_retries=None, base_delay=None, max_delay=None,
                 multiplier=2.0, deadline=None, seed=0):
        self.max_retries = int(max_retries if max_retries is not None
                               else _env_float("MXNET_PS_RETRY_MAX", 8))
        self.base_delay = float(base_delay if base_delay is not None
                                else _env_float("MXNET_PS_RETRY_BASE",
                                                0.05))
        self.max_delay = float(max_delay if max_delay is not None
                               else _env_float("MXNET_PS_RETRY_MAX_DELAY",
                                               2.0))
        self.multiplier = float(multiplier)
        self.deadline = float(deadline if deadline is not None
                              else _env_float("MXNET_PS_OP_DEADLINE",
                                              120.0))
        self.seed = seed

    # -- classification -----------------------------------------------------
    @staticmethod
    def is_transient(exc):
        """True when retrying can plausibly succeed: the TRANSPORT
        failed. False when the server answered (application error) or
        the cohort is dead — a retry would re-fail identically or,
        worse, re-apply a non-idempotent op."""
        if isinstance(exc, DeadWorkerError):
            return False
        if isinstance(exc, (ConnectionError, BrokenPipeError,
                            socket.timeout, TimeoutError, EOFError)):
            return True
        if isinstance(exc, OSError):
            return exc.errno in _TRANSIENT_ERRNOS
        return False

    # -- schedule -----------------------------------------------------------
    def delay(self, attempt):
        """Backoff before retry #attempt (1-based). Deterministic."""
        d = self.base_delay * (self.multiplier ** (max(1, attempt) - 1))
        d = min(d, self.max_delay)
        frac = (zlib.crc32(("%s:%d" % (self.seed, attempt))
                           .encode("utf-8")) % 1024) / 1024.0
        return d * (0.5 + 0.5 * frac)

    def run(self, fn, describe="op", on_retry=None, on_fatal=None):
        """Call ``fn()`` until it succeeds, a fatal error occurs, the
        retry count is exhausted, or the deadline would be overrun by
        the next backoff sleep. ``on_retry(exc, attempt, delay)`` fires
        before each sleep (the client uses it to drop the broken
        connection and to log).

        ``on_fatal(exc)`` is the per-call reroute hook: consulted for
        errors :meth:`is_transient` classifies FATAL, and only for
        those — and only when a retry is actually available (budget
        and deadline permitting), so the hook's bookkeeping never
        records a retry that cannot happen. Returning True retries
        anyway (same budget, same backoff schedule); False/None
        preserves the fast-fail raise. The fatal classification
        itself never changes — the hook exists for callers whose
        ``fn`` re-targets each attempt, e.g. the serve router
        retrying an ``Overloaded`` on the next-least-loaded replica
        (a single-replica client keeps its fast-fail contract by
        simply not passing one: retrying an Overloaded against the
        same full queue is a retry storm)."""
        start = time.monotonic()
        attempt = 0
        while True:
            try:
                return fn()
            except Exception as exc:  # noqa: BLE001 — classified below
                fatal = not self.is_transient(exc)
                if fatal and on_fatal is None:
                    raise
                if attempt + 1 > self.max_retries:
                    raise
                d = self.delay(attempt + 1)
                if self.deadline > 0 and \
                        time.monotonic() - start + d > self.deadline:
                    raise
                if fatal and not on_fatal(exc):
                    raise
                attempt += 1
                if on_retry is not None:
                    on_retry(exc, attempt, d)
                # the backoff sleep as a trace span (no-op when tracing
                # is off): in a trace of a retried op the wait between
                # attempts is visible, not an unexplained gap
                from .. import trace as _trace
                bsp = _trace.start_span("retry.backoff", op=describe,
                                        attempt=attempt)
                time.sleep(d)
                _trace.end_span(bsp)


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

_RULE_RE = re.compile(
    r"^(?P<point>\w+):(?P<action>drop|disconnect|delay)"
    r"@(?P<nth>\d+)(?:x(?P<count>\d+|\*))?(?::(?P<arg>[0-9.]+))?$")

# step-indexed guardrail rules (mxnet_tpu/guardrail.py): the "call" being
# counted is one training step of a fit loop, and the point name IS the
# action — `nan@5` poisons the 5th step's gradients (exercising the real
# on-device detection/masking path), `sigterm@3` raises a real SIGTERM
# through the chaining GracefulShutdown handler at the 3rd step boundary.
# The `kill<I>` family is the chaos-harness extension of the same form
# (tools/chaos_fleet.py): the "call" counted is one completed fleet
# request, and a firing rule tells the harness to SIGKILL child replica
# index I — `kill1@40` kills replica 1 when the 40th request completes.
_STEP_RULE_RE = re.compile(
    r"^(?P<point>nan|sigterm|kill\d*)@(?P<nth>\d+)(?:x(?P<count>\d+|\*))?$")

# every wire point name the documented hooks can ever fire (the grammar
# above accepts any \w+ for `point`, so without this check a typo'd
# point — `serve_snd:drop@1` — would silently never fire and a fault
# test would pass vacuously). Fixed names plus the per-replica router
# family; step-rule points validate through _STEP_RULE_RE itself.
_WIRE_POINTS = frozenset((
    "send", "recv", "ping", "srv_send", "srv_recv",
    "serve_send", "serve_recv", "serve_srv_send", "serve_srv_recv",
    "prefill_send", "prefill_recv",
))
_WIRE_POINT_PATTERNS = (
    re.compile(r"^router\d+_(?:ctl_)?(?:send|recv)$"),
)


def _check_wire_point(point, raw):
    if point in _WIRE_POINTS or \
            any(p.match(point) for p in _WIRE_POINT_PATTERNS):
        return
    raise ValueError(
        "MXNET_FAULT_SPEC rule %r names unknown injection point %r — "
        "documented wire points are %s, plus the per-replica router "
        "family router<I>_send / router<I>_recv / router<I>_ctl_send / "
        "router<I>_ctl_recv; step-indexed rules are nan@N / sigterm@N "
        "/ kill<I>@N (docs/robustness.md). A mistyped point never "
        "fires, so the fault test it belongs to passes vacuously."
        % (raw, point, ", ".join(sorted(_WIRE_POINTS))))


class _Rule:
    __slots__ = ("point", "action", "nth", "count", "arg")

    def __init__(self, point, action, nth, count, arg):
        self.point = point
        self.action = action
        self.nth = nth          # first matching call (1-based)
        self.count = count      # how many consecutive calls (None = ∞)
        self.arg = arg          # delay seconds

    def matches(self, n):
        if n < self.nth:
            return False
        if self.count is None:
            return True
        return n < self.nth + self.count


class FaultInjector:
    """Deterministic fault injection on the PS socket plumbing.

    Spec grammar (``MXNET_FAULT_SPEC``, rules joined by ``;``)::

        point:action@nth[xcount][:arg]

    * ``point`` — where the hook fires: ``send`` / ``recv`` (worker
      client request/reply plumbing), ``ping`` (worker heartbeat
      sends), ``srv_send`` / ``srv_recv`` (server-side plumbing, for a
      server process running with the env set). The serving front end
      (``mxnet_tpu/serve/net.py``) exposes the same grammar under its
      own points — ``serve_send`` / ``serve_recv`` (client) and
      ``serve_srv_send`` / ``serve_srv_recv`` (server) — so serving
      fault tests never perturb PS injection counts. The serve router
      (``mxnet_tpu/serve/router.py``) gives each replica its own
      point family so one replica's transport can be killed without
      touching the others: ``router<I>_send`` / ``router<I>_recv``
      (data path to replica index I) and ``router<I>_ctl_send`` /
      ``router<I>_ctl_recv`` (its stats/warm control connection).
    * ``action`` — ``drop`` (close the socket and fail before any
      bytes move), ``disconnect`` (transmit *half* the frame, then
      close — the peer sees a torn message; on recv points identical
      to drop), ``delay`` (sleep ``arg`` seconds, then proceed).
    * ``@nth`` — fire on the nth call of that point (1-based), counted
      per point from injector installation.
    * ``xcount`` — fire for that many consecutive calls (``x*`` =
      every call from nth on).

    Step-indexed guardrail rules use the short form ``nan@nth[xcount]``
    / ``sigterm@nth[xcount]`` — the "call" counted is one training step
    of a fit loop (``on_train_step``): ``nan@5`` poisons the 5th step's
    gradients, ``sigterm@3`` raises a real SIGTERM at the 3rd step
    boundary (mxnet_tpu/guardrail.py). ``kill<I>@nth[xcount]`` is the
    chaos-harness member of the same family (``on_chaos_tick``): the
    call counted is one completed fleet request, and a firing rule
    tells ``tools/chaos_fleet.py`` to SIGKILL child replica index I.

    Wire point names are validated at parse time against the families
    above — an unknown point raises ``ValueError`` naming the valid
    ones, because a typo'd point never fires and the fault test it
    belongs to would pass vacuously.

    Example: ``send:disconnect@4;recv:drop@6`` tears the 4th request
    frame mid-message and severs the connection before the 6th reply
    read. Counting is process-wide per point, under a lock, so a
    single-client test replays identically every run.

    ``fired`` records every injection as ``(point, n, action)`` for
    test assertions."""

    def __init__(self, spec):
        self.spec = spec or ""
        self._rules = []
        def add_rule(m, action, arg):
            count = m.group("count")
            self._rules.append(_Rule(
                m.group("point"), action, int(m.group("nth")),
                None if count == "*" else int(count or 1), arg))

        for raw in filter(None,
                          (s.strip() for s in self.spec.split(";"))):
            m = _RULE_RE.match(raw)
            if m is not None:
                _check_wire_point(m.group("point"), raw)
                add_rule(m, m.group("action"),
                         float(m.group("arg") or 0.0))
                continue
            m = _STEP_RULE_RE.match(raw)
            if m is None:
                raise ValueError(
                    "bad MXNET_FAULT_SPEC rule %r (want "
                    "point:action@nth[xcount][:seconds] or "
                    "nan@nth[xcount] / sigterm@nth[xcount] / "
                    "kill<I>@nth[xcount])" % raw)
            add_rule(m, m.group("point"), 0.0)
        self._counts = {}
        self._lock = threading.Lock()
        self.fired = []

    def _step(self, point):
        """Advance the point's call counter; return the rule to apply
        (or None)."""
        with self._lock:
            n = self._counts.get(point, 0) + 1
            self._counts[point] = n
            for rule in self._rules:
                if rule.point == point and rule.matches(n):
                    self.fired.append((point, n, rule.action))
                    return rule
        return None

    @staticmethod
    def _sever(sock):
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass  # already dead — severing twice is the point, not a bug
        sock.close()

    # -- hooks (called from ps_async._send_msg/_recv_msg) -------------------
    def on_send(self, point, sock, frame):
        """Before a frame is written. May sleep, or sever the
        connection (optionally after leaking half the frame) and raise
        FaultInjected — the caller must not then write."""
        rule = self._step(point)
        if rule is None:
            return
        if rule.action == "delay":
            time.sleep(rule.arg)
            return
        if rule.action == "disconnect":
            # mid-message disconnect: the peer receives a torn frame
            try:
                sock.sendall(frame[:max(1, len(frame) // 2)])
            except OSError:
                pass  # peer already gone; the sever below still holds
        self._sever(sock)
        raise FaultInjected("injected %s at %s #%d"
                            % (rule.action, point,
                               self._counts.get(point, 0)))

    def on_recv(self, point, sock):
        """Before a frame is read. drop/disconnect sever the socket and
        raise; delay sleeps."""
        rule = self._step(point)
        if rule is None:
            return
        if rule.action == "delay":
            time.sleep(rule.arg)
            return
        self._sever(sock)
        raise FaultInjected("injected %s at %s #%d"
                            % (rule.action, point,
                               self._counts.get(point, 0)))

    # -- hook (called once per fit-loop step, mxnet_tpu/guardrail.py) -------
    def on_train_step(self, point):
        """Step-indexed guardrail points (``nan`` / ``sigterm``):
        advance the per-point counter by one training step; True when a
        rule fires this step. The caller performs the fault (the
        injector has no socket to act on here)."""
        return self._step(point) is not None

    # -- hook (called once per completed fleet request,
    #    tools/chaos_fleet.py) --------------------------------------------
    def on_chaos_tick(self, point):
        """Chaos-schedule points (the ``kill<I>`` family): advance the
        named point's counter by one completed fleet request; True when
        a rule fires this tick. The harness performs the fault — a hard
        SIGKILL of child replica I — so the schedule is deterministic
        in request-completion order, never wall time."""
        return self._step(point) is not None


_installed = None          # explicitly installed injector (tests)
_env_injector = None       # injector built from MXNET_FAULT_SPEC
_env_spec = None           # the spec string _env_injector was built from
_env_lock = threading.Lock()


def install_fault_injector(injector):
    """Install (or, with None, remove) the process-wide injector.
    Explicit installation overrides ``MXNET_FAULT_SPEC``."""
    global _installed
    _installed = injector
    return injector


def active_injector():
    """The injector in effect: the explicitly installed one, else one
    lazily built from ``MXNET_FAULT_SPEC`` (rebuilt if the env value
    changes), else None."""
    global _env_injector, _env_spec
    if _installed is not None:
        return _installed
    spec = os.environ.get("MXNET_FAULT_SPEC") or None
    if spec != _env_spec:
        with _env_lock:
            if spec != _env_spec:
                _env_injector = FaultInjector(spec) if spec else None
                _env_spec = spec
    return _env_injector
