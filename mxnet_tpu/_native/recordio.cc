// Native RecordIO chunk reader — the C++ component of the data pipeline.
//
// Reference counterpart: dmlc-core's recordio.cc + the chunk readers in
// src/io/iter_image_recordio_2.cc (OMP-parallel record parsing). Here the
// file is mmap'd once and scanned into an ordinal index of logical
// records (continuation-split parts are tracked and reassembled on
// read), so Python-side iteration is one memcpy per record instead of
// per-record struct unpacking — the host-side half of keeping the TPU
// input-bound pipeline off the interpreter.
//
// Record layout (recordio spec):
//   [magic u32le = 0xced7230a][lrec u32le: cflag<<29 | len]
//   [payload][pad to 4B]
// cflag: 0 whole, 1 first, 2 middle, 3 last — split parts rejoin with
// the magic word between them.

#include <cstdint>
#include <cstring>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

struct Part {
  uint64_t off;
  uint32_t len;
};

struct RioFile {
  const uint8_t* base = nullptr;
  uint64_t size = 0;
  int fd = -1;
  // flattened parts; record i spans parts [starts[i], starts[i+1])
  std::vector<Part> parts;
  std::vector<uint64_t> starts;
  std::vector<uint64_t> offsets;  // byte offset of record i's header
};

inline uint32_t read_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

}  // namespace

extern "C" {

void* rio_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (mem == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  RioFile* f = new RioFile();
  f->base = static_cast<const uint8_t*>(mem);
  f->size = static_cast<uint64_t>(st.st_size);
  f->fd = fd;

  uint64_t pos = 0;
  bool in_split = false;
  while (pos + 8 <= f->size) {
    if (read_u32(f->base + pos) != kMagic) break;  // torn tail: stop
    uint32_t lrec = read_u32(f->base + pos + 4);
    uint32_t cflag = lrec >> 29u;
    uint32_t len = lrec & ((1u << 29) - 1u);
    if (pos + 8 + len > f->size) break;
    if (cflag == 0 || cflag == 1) {
      f->starts.push_back(f->parts.size());
      f->offsets.push_back(pos);
      in_split = (cflag == 1);
    } else if (!in_split) {
      break;  // corrupt: continuation without a first part
    }
    f->parts.push_back(Part{pos + 8, len});
    if (cflag == 0 || cflag == 3) in_split = false;
    pos += 8 + len + ((4 - (len & 3u)) & 3u);
  }
  if (pos != f->size || in_split) {
    // torn or non-recordio content: refuse, so the caller falls back to
    // the strict Python reader (which raises at the corrupt offset
    // instead of silently truncating the epoch)
    munmap(const_cast<uint8_t*>(f->base), f->size);
    ::close(fd);
    delete f;
    return nullptr;
  }
  f->starts.push_back(f->parts.size());
  return f;
}

long rio_count(void* h) {
  RioFile* f = static_cast<RioFile*>(h);
  return static_cast<long>(f->starts.size()) - 1;
}

long rio_num_parts(void* h) {
  RioFile* f = static_cast<RioFile*>(h);
  return static_cast<long>(f->parts.size());
}

// one-shot index export so the Python side can slice its own mmap with
// zero per-record FFI calls: rec_starts (count+1), part offsets/lengths
// (num_parts), header offsets (count)
void rio_export(void* h, int64_t* rec_starts, int64_t* part_offs,
                int64_t* part_lens, int64_t* hdr_offs) {
  RioFile* f = static_cast<RioFile*>(h);
  for (size_t i = 0; i < f->starts.size(); ++i)
    rec_starts[i] = static_cast<int64_t>(f->starts[i]);
  for (size_t i = 0; i < f->parts.size(); ++i) {
    part_offs[i] = static_cast<int64_t>(f->parts[i].off);
    part_lens[i] = static_cast<int64_t>(f->parts[i].len);
  }
  for (size_t i = 0; i < f->offsets.size(); ++i)
    hdr_offs[i] = static_cast<int64_t>(f->offsets[i]);
}

void rio_close(void* h) {
  RioFile* f = static_cast<RioFile*>(h);
  if (f == nullptr) return;
  munmap(const_cast<uint8_t*>(f->base), f->size);
  ::close(f->fd);
  delete f;
}

}  // extern "C"
