"""Native (C++) runtime components, built on demand with g++.

The reference implements its data plane in C++ (dmlc-core recordio,
src/io iterators); this package holds the TPU framework's native
equivalents, compiled lazily into shared objects next to the sources
(ctypes bindings — no pybind11 dependency). Every native path has a
pure-Python fallback: absence of a toolchain degrades performance, not
functionality.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIBS = {}


def _link_flags(src):
    """Extra linker flags from a leading '// LINK: -lfoo -lbar' comment."""
    try:
        with open(src) as f:
            for line in f.read(4096).splitlines():
                if line.startswith("// LINK:"):
                    return line.split(":", 1)[1].split()
    except OSError:
        pass
    return []


def _build(name):
    """Compile <name>.cc -> lib<name>.so if missing/stale; None on any
    failure (callers fall back to Python)."""
    src = os.path.join(_DIR, name + ".cc")
    so = os.path.join(_DIR, "lib%s.so" % name)
    try:
        if (not os.path.exists(so) or
                os.path.getmtime(so) < os.path.getmtime(src)):
            # per-process temp name: concurrent first-use builds (e.g.
            # multiprocessing loader workers) must not interleave writes
            tmp = "%s.tmp.%d" % (so, os.getpid())
            subprocess.run(
                ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", src,
                 "-o", tmp] + _link_flags(src),
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        return so
    except Exception:
        return None


def load(name):
    """ctypes handle for lib<name>.so (cached); None if unavailable."""
    with _LOCK:
        if name in _LIBS:
            return _LIBS[name]
        so = _build(name)
        lib = None
        if so is not None:
            try:
                lib = ctypes.CDLL(so)
            except OSError:
                lib = None
        _LIBS[name] = lib
        return lib


def build_predict_shim():
    """Compile the C predict ABI (predict_shim.cc) -> libpredict_shim.so.

    Separate from _build because it embeds CPython: include/lib flags
    come from sysconfig rather than a LINK comment. Returns the .so
    path, or None when the toolchain/headers are missing (the Python
    Predictor/CompiledPredictor surface is unaffected)."""
    import sysconfig

    src = os.path.join(_DIR, "predict_shim.cc")
    so = os.path.join(_DIR, "libpredict_shim.so")
    try:
        if (os.path.exists(so) and
                os.path.getmtime(so) >= os.path.getmtime(src)):
            return so
        inc = sysconfig.get_paths()["include"]
        libdir = sysconfig.get_config_var("LIBDIR")
        pyver = sysconfig.get_config_var("VERSION")
        tmp = "%s.tmp.%d" % (so, os.getpid())
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
             "-I%s" % inc, src, "-o", tmp,
             "-L%s" % libdir, "-Wl,-rpath,%s" % libdir,
             "-lpython%s" % pyver],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, so)
        return so
    except Exception:
        return None


_MAGIC_BYTES = b"\x0a\x23\xd7\xce"


class NativeRecordFile:
    """mmap-backed access to a .rec file: the C++ scanner builds the
    record index once (recordio.cc); the exported offset table lets
    reads slice a Python mmap directly — zero per-record FFI, one
    memcpy per record. Raises ImportError when the native library is
    unavailable — callers catch and fall back."""

    def __init__(self, path):
        import mmap as _mmap

        import numpy as np

        lib = load("recordio")
        if lib is None:
            raise ImportError("native recordio library unavailable")
        lib.rio_open.restype = ctypes.c_void_p
        lib.rio_open.argtypes = [ctypes.c_char_p]
        lib.rio_count.restype = ctypes.c_long
        lib.rio_count.argtypes = [ctypes.c_void_p]
        lib.rio_num_parts.restype = ctypes.c_long
        lib.rio_num_parts.argtypes = [ctypes.c_void_p]
        lib.rio_export.argtypes = [ctypes.c_void_p] + \
            [np.ctypeslib.ndpointer(np.int64)] * 4
        lib.rio_close.argtypes = [ctypes.c_void_p]

        handle = lib.rio_open(path.encode())
        if not handle:
            raise IOError("cannot open/scan %r" % path)
        try:
            count = lib.rio_count(handle)
            n_parts = lib.rio_num_parts(handle)
            rec_starts = np.empty(count + 1, np.int64)
            part_offs = np.empty(max(n_parts, 1), np.int64)
            part_lens = np.empty(max(n_parts, 1), np.int64)
            hdr_offs = np.empty(max(count, 1), np.int64)
            lib.rio_export(handle, rec_starts, part_offs, part_lens,
                           hdr_offs)
        finally:
            lib.rio_close(handle)
        # plain lists: scalar indexing in the per-record hot loop is
        # ~3x faster than numpy item access
        self._rec_starts = rec_starts.tolist()
        self._part_ends = (part_offs + part_lens).tolist()
        self._part_offs = part_offs.tolist()
        self._hdr_offs = hdr_offs[:count]

        self._count = count
        self._file = open(path, "rb")
        self._mm = _mmap.mmap(self._file.fileno(), 0,
                              access=_mmap.ACCESS_READ)
        self.size = self._mm.size()
        self.path = path

    def __len__(self):
        return self._count

    def read(self, i):
        """Assembled payload bytes of record ``i``."""
        if not 0 <= i < self._count:
            raise IndexError(i)
        lo, hi = self._rec_starts[i], self._rec_starts[i + 1]
        if hi == lo + 1:                       # common case: one part
            return self._mm[self._part_offs[lo]:self._part_ends[lo]]
        parts = [self._mm[self._part_offs[p]:self._part_ends[p]]
                 for p in range(lo, hi)]
        return _MAGIC_BYTES.join(parts)

    def find_offset(self, offset):
        """Record ordinal whose header lives at byte ``offset`` (the
        .idx sidecar stores these), or -1."""
        import numpy as np
        i = int(np.searchsorted(self._hdr_offs, offset))
        if i < self._count and self._hdr_offs[i] == offset:
            return i
        return -1

    def offset(self, i):
        return int(self._hdr_offs[i]) if 0 <= i < self._count else -1

    def close(self):
        if getattr(self, "_mm", None) is not None:
            self._mm.close()
            self._file.close()
            self._mm = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
