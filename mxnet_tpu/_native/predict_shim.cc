// C-callable predict surface over the AOT StableHLO deployment path.
//
// Reference parity target: src/c_api/c_predict_api.cc:363 — the
// standalone MXPredCreate/SetInput/Forward/GetOutput ABI that powered
// the amalgamation build, mobile targets and the non-Python bindings.
// The TPU-native artifact is a serialized XLA program
// (Predictor.export -> prefix.stablehlo + prefix.meta.json); this shim
// lets a C host load and run it through an EMBEDDED CPython interpreter
// hosting CompiledPredictor. The heavy lifting (deserialization,
// device placement, execution) is XLA's; the interpreter is a thin
// control plane, so this is the deployment analogue of the reference's
// "predict-only, no training framework" build — the host app needs no
// Python source, no symbol JSON, no parameter files.
//
// ABI (all functions thread-safe via the GIL; floats only, matching
// MXPredSetInput/MXPredGetOutput's float* contract):
//   MXTpuPredCreate(prefix)                 -> handle | NULL (error)
//   MXTpuPredSetInput(h, key, data, size)   -> 0 | -1
//   MXTpuPredForward(h)                     -> 0 | -1
//   MXTpuPredGetOutputShape(h, i, shape[], ndim*) -> 0 | -1
//   MXTpuPredGetOutput(h, i, data, size)    -> 0 | -1
//   MXTpuPredFree(h)
//   MXTpuGetLastError()                     -> const char*
//
// Training ABI (round 5; artifact from TrainStep.export — the whole
// forward+backward+optimizer step as one compiled program):
//   MXTpuTrainCreate(prefix)                -> handle | NULL
//   MXTpuTrainSetBatch(h, key, data, size)  -> 0 | -1
//   MXTpuTrainStep(h, lr)                   -> 0 | -1  (one update)
//   MXTpuTrainGetOutputShape/GetOutput      last step's loss heads
//   MXTpuTrainGetParamShape/GetParam        trained weights by name
//   MXTpuTrainSaveState(h, prefix)          -> 0 | -1
//   MXTpuTrainFree(h)
//
// Build: _native.build_predict_shim() (g++ + sysconfig flags); the
// Python side is optional — this file has no Python-package build-time
// dependency beyond Python.h.
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>

namespace {

std::mutex g_err_mu;
std::string g_last_error;

void set_error(const std::string& msg) {
  std::lock_guard<std::mutex> lock(g_err_mu);
  g_last_error = msg;
}

// Capture the pending Python exception into g_last_error.
void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "unknown python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  set_error(msg);
}

// Python glue executed once into a private namespace: the shim calls
// these four functions instead of fingering package internals from C.
const char* kGlue = R"PY(
import numpy as np

def _load_predictor(prefix):
    # amalgamated deployments ship mxtpu_predict_min.py NEXT TO the
    # model (tools/amalgamate.py) so no framework source is needed at
    # run time; a full install falls back to the framework class. The
    # bundled module loads BY FILE PATH under a per-directory name —
    # never via sys.path, which would let files beside one model shadow
    # later imports process-wide (and would pin the first bundle's
    # loader for every subsequent bundle)
    import hashlib, importlib.util, os, sys
    d = os.path.dirname(os.path.abspath(prefix))
    cand = os.path.join(d, "mxtpu_predict_min.py")
    if os.path.exists(cand):
        name = "mxtpu_predict_min_" + hashlib.md5(
            d.encode()).hexdigest()[:10]
        mod = sys.modules.get(name)
        if mod is None:
            spec = importlib.util.spec_from_file_location(name, cand)
            mod = importlib.util.module_from_spec(spec)
            sys.modules[name] = mod
            spec.loader.exec_module(mod)
        return mod.CompiledPredictor.load(prefix)
    from mxnet_tpu.predictor import CompiledPredictor
    return CompiledPredictor.load(prefix)

def _create(prefix):
    p = _load_predictor(prefix)
    return {"p": p, "inputs": {}, "outputs": None, "meta": p._meta}

def _set_input(h, key, buf):
    shapes = h["meta"]["data_shapes"]
    if key not in shapes:
        raise KeyError("unknown input %r; model inputs: %s"
                       % (key, sorted(shapes)))
    shape = shapes[key]
    arr = np.frombuffer(buf, dtype=np.float32)
    need = int(np.prod(shape))
    if arr.size != need:
        raise ValueError("input %r: got %d floats, shape %s needs %d"
                         % (key, arr.size, shape, need))
    h["inputs"][key] = arr.reshape(shape).copy()

def _forward(h):
    missing = [n for n in h["meta"]["data_names"]
               if n not in h["inputs"]]
    if missing:
        raise ValueError("inputs not set: %s" % missing)
    outs = h["p"].forward(**h["inputs"])
    h["outputs"] = [np.asarray(o.asnumpy(), dtype=np.float32)
                    for o in outs]

def _output(h, i):
    if h["outputs"] is None:
        raise RuntimeError("run forward first")
    return h["outputs"][int(i)]

# ---- training surface (MXTpuTrain*): drives CompiledTrainStep, the
# exported whole-train-step StableHLO program (forward + backward +
# optimizer baked in). Same deployment discipline as predict: an
# mxtpu_train_min.py next to the model wins (no framework source),
# else the installed framework class.
def _load_trainstep(prefix):
    import hashlib, importlib.util, os, sys
    d = os.path.dirname(os.path.abspath(prefix))
    cand = os.path.join(d, "mxtpu_train_min.py")
    if os.path.exists(cand):
        name = "mxtpu_train_min_" + hashlib.md5(
            d.encode()).hexdigest()[:10]
        mod = sys.modules.get(name)
        if mod is None:
            spec = importlib.util.spec_from_file_location(name, cand)
            mod = importlib.util.module_from_spec(spec)
            sys.modules[name] = mod
            spec.loader.exec_module(mod)
        return mod.CompiledTrainStep.load(prefix)
    from mxnet_tpu.parallel.trainer import CompiledTrainStep
    return CompiledTrainStep.load(prefix)

def _train_create(prefix):
    t = _load_trainstep(prefix)
    return {"t": t, "batch": {}, "outputs": None, "meta": t._meta}

def _train_set_batch(h, key, buf):
    meta = h["meta"]
    if key not in meta["batch_shapes"]:
        raise KeyError("unknown batch input %r; exported inputs: %s"
                       % (key, sorted(meta["batch_shapes"])))
    shape = meta["batch_shapes"][key]
    arr = np.frombuffer(buf, dtype=np.float32)
    need = int(np.prod(shape)) if shape else 1
    if arr.size != need:
        raise ValueError("input %r: got %d floats, shape %s needs %d"
                         % (key, arr.size, shape, need))
    h["batch"][key] = arr.reshape(shape).copy()

def _train_step(h, lr):
    missing = [n for n in h["meta"]["batch_names"]
               if n not in h["batch"]]
    if missing:
        raise ValueError("batch inputs not set: %s" % missing)
    outs = h["t"].step(h["batch"], float(lr))
    h["outputs"] = [np.asarray(o, dtype=np.float32) for o in outs]

def _train_output(h, i):
    if h["outputs"] is None:
        raise RuntimeError("run a step first")
    return h["outputs"][int(i)]

def _train_param_shape(h, name):
    # shape without materializing/casting the array (a large embedding
    # would otherwise be copied just to learn its dimensions); the
    # zero-strided broadcast view only carries .shape for the C side
    return np.broadcast_to(np.float32(0), h["t"].get_param_shape(name))

def _train_param(h, name):
    # float32 conversions cached per training step: the shape+data
    # call pattern must not copy every parameter twice
    cached = h.get("param_cache")
    if cached is None or cached[0] != h["t"]._step_count:
        cached = (h["t"]._step_count, {})
        h["param_cache"] = cached
    if name not in cached[1]:
        params = h["t"].get_params()
        if name not in params:
            raise KeyError("unknown param %r; params: %s"
                           % (name, sorted(params)))
        cached[1][name] = np.asarray(params[name], dtype=np.float32)
    return cached[1][name]

def _train_save(h, prefix):
    h["t"].save_state(prefix)
)PY";

PyObject* g_ns = nullptr;  // glue namespace dict

bool ensure_python() {
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);  // no signal handlers: we are a guest
  }
  PyGILState_STATE st = PyGILState_Ensure();
  bool ok = true;
  if (!g_ns) {
    g_ns = PyDict_New();
    PyDict_SetItemString(g_ns, "__builtins__", PyEval_GetBuiltins());
    PyObject* r = PyRun_String(kGlue, Py_file_input, g_ns, g_ns);
    if (!r) {
      set_error_from_python();
      Py_CLEAR(g_ns);
      ok = false;
    } else {
      Py_DECREF(r);
    }
  }
  PyGILState_Release(st);
  return ok;
}

PyObject* glue_call(const char* fn, PyObject* args) {
  // caller holds the GIL; steals nothing, returns new ref or NULL
  PyObject* f = PyDict_GetItemString(g_ns, fn);  // borrowed
  if (!f) {
    set_error(std::string("glue function missing: ") + fn);
    return nullptr;
  }
  PyObject* out = PyObject_CallObject(f, args);
  if (!out) set_error_from_python();
  return out;
}

// Copy a numpy array's shape / float32 payload out to C buffers.
// Caller holds the GIL and owns `arr`.
int arr_shape_out(PyObject* arr, uint32_t* shape, uint32_t* ndim) {
  PyObject* shp = PyObject_GetAttrString(arr, "shape");
  if (!shp) { set_error_from_python(); return -1; }
  int rc = -1;
  Py_ssize_t n = PyTuple_Size(shp);
  if (*ndim < n) {
    set_error("shape buffer too small");
  } else {
    for (Py_ssize_t i = 0; i < n; ++i)
      shape[i] = static_cast<uint32_t>(
          PyLong_AsLong(PyTuple_GetItem(shp, i)));
    *ndim = static_cast<uint32_t>(n);
    rc = 0;
  }
  Py_DECREF(shp);
  return rc;
}

int arr_copy_out(PyObject* arr, float* data, uint64_t size) {
  PyObject* bytes = PyObject_CallMethod(arr, "tobytes", nullptr);
  if (!bytes) { set_error_from_python(); return -1; }
  int rc = -1;
  char* raw = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(bytes, &raw, &len) == 0) {
    if (static_cast<uint64_t>(len) != size * sizeof(float)) {
      set_error("output size mismatch: have " + std::to_string(len) +
                " bytes, caller buffer holds " +
                std::to_string(size * sizeof(float)));
    } else {
      std::memcpy(data, raw, len);
      rc = 0;
    }
  } else {
    set_error_from_python();
  }
  Py_DECREF(bytes);
  return rc;
}

}  // namespace

extern "C" {

const char* MXTpuGetLastError() {
  std::lock_guard<std::mutex> lock(g_err_mu);
  return g_last_error.c_str();
}

void* MXTpuPredCreate(const char* model_prefix) {
  if (!ensure_python()) return nullptr;
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(s)", model_prefix);
  PyObject* h = glue_call("_create", args);
  Py_DECREF(args);
  PyGILState_Release(st);
  return h;  // new ref owned by the caller's handle
}

int MXTpuPredSetInput(void* handle, const char* key, const float* data,
                      uint64_t size) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size * sizeof(float)));
  PyObject* args = Py_BuildValue("(OsO)", static_cast<PyObject*>(handle),
                                 key, buf);
  Py_DECREF(buf);
  PyObject* r = glue_call("_set_input", args);
  Py_DECREF(args);
  int rc = r ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(st);
  return rc;
}

int MXTpuPredForward(void* handle) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = glue_call("_forward", args);
  Py_DECREF(args);
  int rc = r ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(st);
  return rc;
}

int MXTpuPredGetOutputShape(void* handle, uint32_t index,
                            uint32_t* shape, uint32_t* ndim) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(OI)", static_cast<PyObject*>(handle),
                                 index);
  PyObject* arr = glue_call("_output", args);
  Py_DECREF(args);
  int rc = arr ? arr_shape_out(arr, shape, ndim) : -1;
  Py_XDECREF(arr);
  PyGILState_Release(st);
  return rc;
}

int MXTpuPredGetOutput(void* handle, uint32_t index, float* data,
                       uint64_t size) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(OI)", static_cast<PyObject*>(handle),
                                 index);
  PyObject* arr = glue_call("_output", args);
  Py_DECREF(args);
  int rc = arr ? arr_copy_out(arr, data, size) : -1;
  Py_XDECREF(arr);
  PyGILState_Release(st);
  return rc;
}

void MXTpuPredFree(void* handle) {
  if (!handle) return;
  PyGILState_STATE st = PyGILState_Ensure();
  Py_DECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(st);
}

// ---- training ABI: one compiled-train-step artifact, driven from C.
// The step program (forward+backward+optimizer) and state layout come
// from TrainStep.export; see docs/c_abi.md for why the C training
// boundary is the compiled program rather than the reference's 146
// per-op entry points (include/mxnet/c_api.h).

void* MXTpuTrainCreate(const char* model_prefix) {
  if (!ensure_python()) return nullptr;
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(s)", model_prefix);
  PyObject* h = glue_call("_train_create", args);
  Py_DECREF(args);
  PyGILState_Release(st);
  return h;
}

int MXTpuTrainSetBatch(void* handle, const char* key, const float* data,
                       uint64_t size) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data),
      static_cast<Py_ssize_t>(size * sizeof(float)));
  PyObject* args = Py_BuildValue("(OsO)", static_cast<PyObject*>(handle),
                                 key, buf);
  Py_DECREF(buf);
  PyObject* r = glue_call("_train_set_batch", args);
  Py_DECREF(args);
  int rc = r ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(st);
  return rc;
}

int MXTpuTrainStep(void* handle, float lr) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(Of)", static_cast<PyObject*>(handle),
                                 lr);
  PyObject* r = glue_call("_train_step", args);
  Py_DECREF(args);
  int rc = r ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(st);
  return rc;
}

int MXTpuTrainGetOutputShape(void* handle, uint32_t index,
                             uint32_t* shape, uint32_t* ndim) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(OI)", static_cast<PyObject*>(handle),
                                 index);
  PyObject* arr = glue_call("_train_output", args);
  Py_DECREF(args);
  int rc = arr ? arr_shape_out(arr, shape, ndim) : -1;
  Py_XDECREF(arr);
  PyGILState_Release(st);
  return rc;
}

int MXTpuTrainGetOutput(void* handle, uint32_t index, float* data,
                        uint64_t size) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(OI)", static_cast<PyObject*>(handle),
                                 index);
  PyObject* arr = glue_call("_train_output", args);
  Py_DECREF(args);
  int rc = arr ? arr_copy_out(arr, data, size) : -1;
  Py_XDECREF(arr);
  PyGILState_Release(st);
  return rc;
}

int MXTpuTrainGetParamShape(void* handle, const char* name,
                            uint32_t* shape, uint32_t* ndim) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(handle),
                                 name);
  PyObject* arr = glue_call("_train_param_shape", args);
  Py_DECREF(args);
  int rc = arr ? arr_shape_out(arr, shape, ndim) : -1;
  Py_XDECREF(arr);
  PyGILState_Release(st);
  return rc;
}

int MXTpuTrainGetParam(void* handle, const char* name, float* data,
                       uint64_t size) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(handle),
                                 name);
  PyObject* arr = glue_call("_train_param", args);
  Py_DECREF(args);
  int rc = arr ? arr_copy_out(arr, data, size) : -1;
  Py_XDECREF(arr);
  PyGILState_Release(st);
  return rc;
}

int MXTpuTrainSaveState(void* handle, const char* prefix) {
  if (!handle) { set_error("null handle"); return -1; }
  PyGILState_STATE st = PyGILState_Ensure();
  PyObject* args = Py_BuildValue("(Os)", static_cast<PyObject*>(handle),
                                 prefix);
  PyObject* r = glue_call("_train_save", args);
  Py_DECREF(args);
  int rc = r ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(st);
  return rc;
}

void MXTpuTrainFree(void* handle) {
  if (!handle) return;
  PyGILState_STATE st = PyGILState_Ensure();
  Py_DECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(st);
}

}  // extern "C"
