// Native batched image decode + crop + resize — the C++ half of the
// image pipeline.
//
// Reference counterpart: ImageRecordIOParser2's OMP decode loop
// (src/io/iter_image_recordio_2.cc:121-319) + the default augmenter's
// crop/resize (src/io/image_aug_default.cc), which run per record on
// worker threads with OpenCV. Here: libjpeg/libpng decode, bilinear
// crop-resize, optional mirror, a std::thread pool — fully off the
// Python GIL, one FFI call per batch.
//
// LINK: -ljpeg -lpng

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>
#include <png.h>

#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct JErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jerr_exit(j_common_ptr cinfo) {
  longjmp(reinterpret_cast<JErr*>(cinfo->err)->jb, 1);
}

bool is_jpeg(const uint8_t* p, int64_t n) {
  return n >= 2 && p[0] == 0xFF && p[1] == 0xD8;
}

bool is_png(const uint8_t* p, int64_t n) {
  static const uint8_t sig[4] = {0x89, 'P', 'N', 'G'};
  return n >= 4 && std::memcmp(p, sig, 4) == 0;
}

bool decode_jpeg(const uint8_t* buf, int64_t len, std::vector<uint8_t>* rgb,
                 int* h, int* w) {
  jpeg_decompress_struct cinfo;
  JErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jerr_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, buf, len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;  // grayscale sources expand to RGB
  jpeg_start_decompress(&cinfo);
  *w = cinfo.output_width;
  *h = cinfo.output_height;
  rgb->resize(static_cast<size_t>(*h) * *w * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = rgb->data() +
        static_cast<size_t>(cinfo.output_scanline) * *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return true;
}

bool decode_png(const uint8_t* buf, int64_t len, std::vector<uint8_t>* rgb,
                int* h, int* w) {
  png_image img;
  std::memset(&img, 0, sizeof(img));
  img.version = PNG_IMAGE_VERSION;
  if (!png_image_begin_read_from_memory(&img, buf, len)) return false;
  img.format = PNG_FORMAT_RGB;
  *w = img.width;
  *h = img.height;
  rgb->resize(PNG_IMAGE_SIZE(img));
  if (!png_image_finish_read(&img, nullptr, rgb->data(), 0, nullptr)) {
    png_image_free(&img);
    return false;
  }
  return true;
}

bool decode_any(const uint8_t* buf, int64_t len, std::vector<uint8_t>* rgb,
                int* h, int* w) {
  if (is_jpeg(buf, len)) return decode_jpeg(buf, len, rgb, h, w);
  if (is_png(buf, len)) return decode_png(buf, len, rgb, h, w);
  return false;
}

// bilinear sample of the rect (x0,y0,cw,ch) of src into (oh,ow) at dst
void crop_resize(const uint8_t* src, int sh, int sw, float x0, float y0,
                 float cw, float ch, uint8_t* dst, int oh, int ow,
                 bool flip) {
  const float sx = cw / ow;
  const float sy = ch / oh;
  for (int y = 0; y < oh; ++y) {
    float fy = y0 + (y + 0.5f) * sy - 0.5f;
    if (fy < 0) fy = 0;
    if (fy > sh - 1) fy = sh - 1;
    const int iy = static_cast<int>(fy);
    const int iy1 = iy + 1 < sh ? iy + 1 : iy;
    const float wy = fy - iy;
    for (int x = 0; x < ow; ++x) {
      float fx = x0 + (x + 0.5f) * sx - 0.5f;
      if (fx < 0) fx = 0;
      if (fx > sw - 1) fx = sw - 1;
      const int ix = static_cast<int>(fx);
      const int ix1 = ix + 1 < sw ? ix + 1 : ix;
      const float wx = fx - ix;
      const uint8_t* p00 = src + (static_cast<size_t>(iy) * sw + ix) * 3;
      const uint8_t* p01 = src + (static_cast<size_t>(iy) * sw + ix1) * 3;
      const uint8_t* p10 = src + (static_cast<size_t>(iy1) * sw + ix) * 3;
      const uint8_t* p11 = src + (static_cast<size_t>(iy1) * sw + ix1) * 3;
      const int ox = flip ? ow - 1 - x : x;
      uint8_t* q = dst + (static_cast<size_t>(y) * ow + ox) * 3;
      for (int c = 0; c < 3; ++c) {
        const float top = p00[c] + (p01[c] - p00[c]) * wx;
        const float bot = p10[c] + (p11[c] - p10[c]) * wx;
        q[c] = static_cast<uint8_t>(top + (bot - top) * wy + 0.5f);
      }
    }
  }
}

}  // namespace

extern "C" {

// dimensions without full decode (header parse): hw <- {h, w}; 0 on ok
int imgd_probe(const uint8_t* buf, int64_t len, int32_t* hw) {
  if (is_jpeg(buf, len)) {
    jpeg_decompress_struct cinfo;
    JErr jerr;
    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = jerr_exit;
    if (setjmp(jerr.jb)) {
      jpeg_destroy_decompress(&cinfo);
      return 1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, buf, len);
    jpeg_read_header(&cinfo, TRUE);
    hw[0] = cinfo.image_height;
    hw[1] = cinfo.image_width;
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  if (is_png(buf, len)) {
    png_image img;
    std::memset(&img, 0, sizeof(img));
    img.version = PNG_IMAGE_VERSION;
    if (!png_image_begin_read_from_memory(&img, buf, len)) return 1;
    hw[0] = img.height;
    hw[1] = img.width;
    png_image_free(&img);
    return 0;
  }
  return 1;
}

// Decode n images, crop rects[i] = {x0,y0,cw,ch} (scaled by 1/16 fixed
// point via float array), bilinear-resize each to (oh, ow), optional
// mirror, into out (n * oh * ow * 3, HWC uint8). Returns 0 on success,
// else 1-based index of the first failed image.
int imgd_batch(const uint8_t** bufs, const int64_t* lens, int n,
               const float* rects, const uint8_t* flips, int oh, int ow,
               uint8_t* out, int n_threads) {
  std::atomic<int> next(0), failed(0);
  auto worker = [&]() {
    std::vector<uint8_t> rgb;
    for (;;) {
      const int i = next.fetch_add(1);
      if (i >= n || failed.load()) return;  // batch is doomed: stop early
      int h = 0, w = 0;
      bool ok = false;
      try {
        ok = decode_any(bufs[i], lens[i], &rgb, &h, &w) &&
            static_cast<int64_t>(h) * w <= (1ll << 26);  // 64MPix cap
      } catch (...) {
        ok = false;  // bad_alloc from absurd claimed dims etc.
      }
      if (!ok) {
        int expect = 0;
        failed.compare_exchange_strong(expect, i + 1);
        continue;
      }
      const float* r = rects + static_cast<size_t>(i) * 4;
      float x0 = r[0], y0 = r[1], cw = r[2], ch = r[3];
      if (cw <= 0 || ch <= 0) {  // sentinel: whole image
        x0 = 0; y0 = 0; cw = w; ch = h;
      }
      crop_resize(rgb.data(), h, w, x0, y0, cw, ch,
                  out + static_cast<size_t>(i) * oh * ow * 3, oh, ow,
                  flips[i] != 0);
    }
  };
  int nt = n_threads > 0 ? n_threads : 1;
  if (nt > n) nt = n;
  std::vector<std::thread> pool;
  for (int t = 1; t < nt; ++t) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return failed.load();
}

}  // extern "C"
