"""Executor — runs a bound Symbol graph.

Reference: src/executor/graph_executor.cc + python/mxnet/executor.py.
The reference's bind pipeline (gradient pass, device placement, shape
inference, memory planning, op fusion into engine segments) collapses here
into: lower the Symbol to ONE pure JAX function, `jax.jit` it (XLA does
placement/planning/fusion), and get the backward pass from `jax.vjp` of that
same function — the whole-graph analogue of the reference's symbolic
Gradient pass.

Training forwards run a FUSED fwd+vjp program: one XLA executable computes
outputs, updated aux state and parameter gradients together, so the
Module.fit hot path pays forward FLOPs once (the reference reused forward
activations from its executor memory plan; XLA shares them inside the one
program).

Device placement (the reference's PlaceDevice pass over `ctx_group`
attributes, graph_executor.cc:309-410) maps to GSPMD sharding constraints:
nodes annotated `__shard__="data,model"` (or `__ctx_group__=g` with a
group2ctx entry naming a spec) get `with_sharding_constraint` applied to
their outputs when the executor runs over a mesh.

Aux states (BatchNorm moving stats) are threaded functionally through the
compiled fn and written back to their NDArrays after each forward — the
reference mutated them in-place from inside kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .base import MXNetError
from .context import Context, current_context
from .ndarray import ndarray as _nd
from .ndarray.ndarray import NDArray, _wrap

__all__ = ["Executor"]


def _parse_pspec(spec):
    """'data,model' / '(data, None)' / 'model' / 'data+fsdp,None' ->
    tuple for PartitionSpec. None/'None'/'' entries mean unsharded
    dims; '+' joins multiple axes on one dim (and tuple entries pass
    through) — shared grammar with parallel.sharding.parse_spec."""
    from .parallel.sharding import parse_spec
    return parse_spec(spec)


def _shard_constraint(mesh, spec, val, strict=True):
    """Apply a sharding constraint to one node output.

    strict (the __shard__ attr): a spec naming an axis the mesh lacks,
    or an indivisible dim, is an error. strict=False (the
    __shard_hint__ attr): such specs are silently skipped — the lenient
    form for annotations baked into reusable model builders (e.g. the
    transformer's seq_axis residual-stream hint), where the same symbol
    must still bind on meshes without that axis."""
    parts = _parse_pspec(spec)
    if len(parts) > np.ndim(val):
        return val  # annotation written for a different-rank tensor
    for dim, axis in enumerate(parts):
        if axis is None:
            continue
        axes = axis if isinstance(axis, tuple) else (axis,)
        missing = [a for a in axes if a not in mesh.axis_names]
        if missing:
            if not strict:
                return val
            raise MXNetError(
                "__shard__ axis %r not in mesh axes %r"
                % (missing[0], mesh.axis_names))
        n_shards = int(np.prod([mesh.shape[a] for a in axes]))
        if val.shape[dim] % n_shards != 0:
            if not strict:
                return val
            raise MXNetError(
                "__shard__=%r: dim %d of shape %r not divisible by mesh "
                "axes %r (total shards %d)"
                % (spec, dim, tuple(val.shape), axes, n_shards))
    return jax.lax.with_sharding_constraint(
        val, NamedSharding(mesh, P(*parts)))


def _node_shard_spec(node, group2spec):
    """The sharding annotation of a node, if any: explicit __shard__ wins,
    else its ctx_group's entry in group2spec."""
    attrs = node.misc_attrs
    spec = attrs.get("__shard__")
    if spec is not None:
        return spec
    group = attrs.get("__ctx_group__") or attrs.get("ctx_group")
    if group is not None and group2spec:
        return group2spec.get(group)
    return None


def _graph_eval_fn(symbol, mesh=None, group2spec=None, capture=None,
                   layout=None):
    """Build the pure function evaluating `symbol`'s graph.

    Returns fn(arg_vals: dict name->array, aux_vals: dict, rng, is_train)
      -> (tuple outputs, dict new_aux).

    mesh/group2spec: lower ctx_group/__shard__ annotations to sharding
    constraints (the PlaceDevice analogue). layout (a
    parallel.sharding.SpecLayout): additionally pins activation batch
    dims at module boundaries (sharding.BOUNDARY_OPS) with LENIENT
    constraints — explicit __shard__/__shard_hint__ annotations win.
    capture: debugging hook called with (node_name, [outputs]) for
    every op node — only useful un-jitted (Monitor path)."""
    from .symbol.symbol import _topo_order

    boundary_ops = None
    if mesh is not None and layout is not None:
        from .parallel import sharding as _shd
        if getattr(layout, "act_parts", None) is not None and \
                layout.act_parts(2) is not None:
            boundary_ops = _shd.BOUNDARY_OPS

    entries = symbol._entries
    order = _topo_order(entries)
    node_uid = {id(n): i for i, n in enumerate(order)}

    def eval_fn(arg_vals, aux_vals, rng, is_train):
        from .ops._mesh_ctx import use_mesh
        with use_mesh(mesh):
            return _eval_body(arg_vals, aux_vals, rng, is_train)

    def _eval_body(arg_vals, aux_vals, rng, is_train):
        env = {}
        aux_out = dict(aux_vals)
        for node in order:
            if node.op is None:
                if node.is_aux:
                    env[id(node)] = [aux_out[node.name]]
                else:
                    env[id(node)] = [arg_vals[node.name]]
                if capture is not None:
                    capture(node.name, env[id(node)])
                continue
            xs = [env[id(m)][i] for (m, i) in node.inputs]
            attrs = dict(node.attrs)
            if node.op.takes_is_train:
                attrs["is_train"] = is_train
            kw = {}
            if node.op.needs_rng:
                kw["rng"] = jax.random.fold_in(rng, node_uid[id(node)])
            raw = node.op.fn(*xs, **kw, **attrs)
            outs = list(raw) if isinstance(raw, (tuple, list)) else [raw]
            n_state = node.op.num_state
            if n_state:
                state_outs = outs[-n_state:]
                outs = outs[:-n_state]
                # state_inputs index the FULL signature; node.inputs holds
                # only the active (arg_select-filtered) args — map by name
                active = node.op.active_args(node.attrs)
                for slot, val in zip(node.op.state_inputs, state_outs):
                    sname = node.op.arg_names[slot]
                    if sname not in active:
                        continue
                    m, _i = node.inputs[active.index(sname)]
                    if m.op is None and m.is_aux:
                        aux_out[m.name] = val
            if mesh is not None:
                spec = _node_shard_spec(node, group2spec)
                if spec is not None:
                    outs = [_shard_constraint(mesh, spec, o) for o in outs]
                else:
                    hint = node.misc_attrs.get("__shard_hint__")
                    if hint is not None:
                        outs = [_shard_constraint(mesh, hint, o,
                                                  strict=False)
                                for o in outs]
                    elif boundary_ops is not None and \
                            node.op.name in boundary_ops:
                        # module boundary: pin the batch dim to the
                        # layout's data axes (lenient — indivisible or
                        # batchless tensors pass through untouched)
                        outs = [o if layout.act_parts(np.ndim(o)) is None
                                else _shard_constraint(
                                    mesh, layout.act_parts(np.ndim(o)),
                                    o, strict=False)
                                for o in outs]
            if capture is not None:
                capture(node.name, outs)
            env[id(node)] = outs
        outputs = tuple(env[id(n)][i] for (n, i) in entries)
        return outputs, aux_out

    return eval_fn


class Executor:
    """Executor over a lowered symbol graph (reference graph_executor.h:57)."""

    def __init__(self, symbol, ctx=None, args=None, args_grad=None,
                 grad_req="write", aux_states=None, group2ctx=None,
                 mesh=None, layout=None):
        self._symbol = symbol
        self._ctx = ctx if ctx is not None else current_context()
        self._group2ctx = group2ctx or {}
        self._mesh = mesh
        self._layout = layout
        self._monitor_callback = None
        self._monitor_all = False
        # host-python ops (CustomOp -> jax pure_callback) cannot run on
        # remote/tunneled accelerators; recorded structurally here so
        # the runtime-failure rewrite below does not depend on the
        # backend's error WORDING surviving upgrades
        try:
            import json as _json
            self._has_host_callback_ops = any(
                n.get("op") == "Custom"
                for n in _json.loads(symbol.tojson())["nodes"])
        except Exception:  # noqa: BLE001
            self._has_host_callback_ops = False

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        self._arg_names = arg_names
        self._aux_names = aux_names

        self.arg_arrays = self._align("args", args, arg_names)
        self.aux_arrays = self._align("aux_states", aux_states, aux_names,
                                      allow_missing=not aux_names)

        if isinstance(grad_req, str):
            self._grad_req = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            self._grad_req = dict(zip(arg_names, grad_req))
        else:
            self._grad_req = {n: grad_req.get(n, "null") for n in arg_names}

        if args_grad is None:
            self.grad_arrays = [
                _nd.zeros_like(a) if self._grad_req[n] != "null" else None
                for n, a in zip(arg_names, self.arg_arrays)]
        else:
            self.grad_arrays = self._align("args_grad", args_grad, arg_names,
                                           allow_missing=True)
            for i, n in enumerate(arg_names):
                if self.grad_arrays[i] is None and \
                        self._grad_req[n] != "null":
                    self._grad_req[n] = "null"

        # group2ctx: entries whose value is a partition-spec string (or
        # P tuple) become sharding constraints; Context values (reference
        # device placement) have no single-program analogue and replicate
        self._group2spec = {g: v for g, v in self._group2ctx.items()
                            if not isinstance(v, Context)}
        self._eval_fn = _graph_eval_fn(symbol, mesh=mesh,
                                       group2spec=self._group2spec,
                                       layout=layout)
        self._jit_fwd = jax.jit(self._eval_fn, static_argnums=(3,))
        self._grad_names = [n for n in arg_names
                            if self._grad_req[n] != "null"]
        self._jit_fwd_bwd = jax.jit(self._fwd_bwd_impl)
        self._jit_bwd = jax.jit(self._bwd_impl)
        self._compile_logged = set()   # telemetry compile events, per fn
        self.outputs = []
        self._fwd_inputs = None
        self._cached_grads = None
        # adaptive: fused fwd+grads is only worth it when backward() takes
        # the default ones-cotangent path; a backward with explicit
        # out_grads (e.g. SequentialModule interior stages) flips this off
        # so later forwards don't compute grads that get thrown away
        self._prefer_fused = True

    # -- construction helpers ----------------------------------------------
    def _align(self, what, values, names, allow_missing=False):
        if values is None:
            if allow_missing:
                return [None] * len(names)
            raise MXNetError("%s must be provided for %r" % (what, names))
        if isinstance(values, dict):
            out = []
            for n in names:
                if n in values:
                    v = values[n]
                    out.append(v if isinstance(v, NDArray) or v is None
                               else _nd.array(v))
                elif allow_missing:
                    out.append(None)
                else:
                    raise MXNetError("%s: missing entry for %r" % (what, n))
            return out
        values = list(values)
        if len(values) != len(names):
            raise MXNetError("%s: length %d != expected %d"
                             % (what, len(values), len(names)))
        return [v if isinstance(v, NDArray) or v is None else _nd.array(v)
                for v in values]

    @staticmethod
    def _simple_bind(symbol, ctx=None, grad_req="write", type_dict=None,
                     group2ctx=None, **kwargs):
        arg_shapes, _, aux_shapes = symbol.infer_shape(**kwargs)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        type_dict = type_dict or {}
        arg_types, _, aux_types = symbol.infer_type(**type_dict)
        args = [_nd.zeros(s, dtype=t) for s, t in zip(arg_shapes, arg_types)]
        aux = [_nd.zeros(s, dtype=t) for s, t in zip(aux_shapes, aux_types)]
        return Executor(symbol, ctx, args=args, grad_req=grad_req,
                        aux_states=aux, group2ctx=group2ctx)

    # -- dict views ----------------------------------------------------------
    @property
    def arg_dict(self):
        return dict(zip(self._arg_names, self.arg_arrays))

    @property
    def grad_dict(self):
        return dict(zip(self._arg_names, self.grad_arrays))

    @property
    def aux_dict(self):
        return dict(zip(self._aux_names, self.aux_arrays))

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in (arg_params or {}).items():
            if name in self.arg_dict:
                self.arg_dict[name]._set_data(
                    jnp.asarray(arr.asnumpy() if isinstance(arr, NDArray)
                                else arr,
                                self.arg_dict[name]._data.dtype))
            elif not allow_extra_params:
                raise MXNetError("Found name %r not in arguments" % name)
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                self.aux_dict[name]._set_data(
                    jnp.asarray(arr.asnumpy() if isinstance(arr, NDArray)
                                else arr,
                                self.aux_dict[name]._data.dtype))
            elif not allow_extra_params:
                raise MXNetError("Found name %r not in aux states" % name)

    def set_monitor_callback(self, callback, monitor_all=False):
        """Install a per-op value callback (reference ExecuteMonCallback,
        graph_executor.h:200). Fires for every graph node's outputs;
        monitor_all additionally fires for variable (arg/aux) nodes."""
        self._monitor_callback = callback
        self._monitor_all = monitor_all

    # -- execution -----------------------------------------------------------
    def _current_rng(self):
        from . import random as mx_random
        return mx_random.next_key()

    def _monitor_active(self):
        if self._monitor_callback is None:
            return False
        mon = getattr(self._monitor_callback, "mon", None)
        return bool(getattr(mon, "activated", True))

    def _run_monitored(self, arg_vals, aux_vals, rng, is_train):
        """Un-jitted graph evaluation with a per-node capture hook — the
        Monitor debugging path (intermediate tensors are materialized,
        which jit+fusion would never do)."""
        cb = self._monitor_callback
        want_vars = self._monitor_all
        var_names = set(self._arg_names) | set(self._aux_names)

        def capture(name, outs):
            if not want_vars and name in var_names:
                return
            for i, o in enumerate(outs):
                label = name if len(outs) == 1 else "%s_out%d" % (name, i)
                cb(label, _wrap(jnp.asarray(o)))

        fn = _graph_eval_fn(self._symbol, mesh=self._mesh,
                            group2spec=self._group2spec, capture=capture,
                            layout=self._layout)
        return fn(arg_vals, aux_vals, rng, is_train)

    def forward(self, is_train=False, **kwargs):
        """Run forward (reference MXExecutorForward →
        GraphExecutor::Forward). kwargs update named input arrays.

        Training forwards with gradients requested run the fused
        fwd+vjp executable and cache the gradients for backward()."""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown forward argument %r" % k)
            dst = self.arg_dict[k]
            src = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            dst._set_data(src.astype(dst._data.dtype)
                          if src.dtype != dst._data.dtype else src)

        arg_vals = {n: a._data for n, a in zip(self._arg_names,
                                               self.arg_arrays)}
        aux_vals = {n: a._data for n, a in zip(self._aux_names,
                                               self.aux_arrays)}
        rng = self._current_rng()

        from . import profiler
        from . import telemetry as _telemetry

        # telemetry compile events: the FIRST call of each jitted
        # variant blocks through XLA trace+compile, so its wall time IS
        # the compile cost. Monitored (un-jitted) runs are excluded.
        jr = _telemetry.journal()
        if self._monitor_active():
            variant = None
        elif is_train and self._grad_names and self._prefer_fused:
            variant = "fwd_bwd"
        else:
            variant = "train_fwd" if is_train else "infer_fwd"
        log_compile = jr is not None and variant is not None \
            and variant not in self._compile_logged
        t_compile = _telemetry.now_ms() if log_compile else 0.0

        self._cached_grads = None
        try:
            with profiler.scope("executor_forward%s" %
                                ("_train" if is_train else ""),
                                "executor"):
                if self._monitor_active():
                    outs, new_aux = self._run_monitored(
                        arg_vals, aux_vals, rng, bool(is_train))
                elif is_train and self._grad_names and \
                        self._prefer_fused:
                    outs, new_aux, grads = self._jit_fwd_bwd(
                        arg_vals, aux_vals, rng)
                    self._cached_grads = grads
                else:
                    outs, new_aux = self._jit_fwd(arg_vals, aux_vals,
                                                  rng, bool(is_train))
            if log_compile:
                self._compile_logged.add(variant)
                # per-variant model FLOPs from XLA cost analysis: a
                # one-off re-trace + lower at the compile event (the
                # executable itself is already cached — no second XLA
                # compile, no execution, no host sync). Feeds the MFU
                # line in tools/telemetry_report.py (MXNET_PEAK_FLOPS).
                flops = self._variant_flops(variant, arg_vals,
                                            aux_vals, rng)
                # only the FUSED step variant feeds the MFU gauge: it
                # is the one whole-step program. train_fwd alone would
                # undercount a split fwd+bwd step ~3x and infer_fwd
                # isn't a training step at all (both still record
                # their flops on the compile event below).
                if flops and variant == "fwd_bwd":
                    _telemetry.gauge("step.model_flops").set(flops)
                _telemetry.journal_event(
                    "compile", site="Executor.forward", variant=variant,
                    wall_ms=round(_telemetry.now_ms() - t_compile, 3),
                    flops=flops)
        except Exception as e:  # noqa: BLE001
            if "host send/recv callbacks" in str(e) or (
                    self._has_host_callback_ops
                    and "UNIMPLEMENTED" in str(e)
                    and "callback" in str(e).lower()):
                # remote/tunneled accelerator backends (axon) cannot
                # run jax host callbacks, which is how CustomOp /
                # _contrib_* python ops execute their host python.
                # Surface what the user can act on instead of a bare
                # UNIMPLEMENTED from deep inside the runtime. (The
                # structural _has_host_callback_ops arm keeps this
                # working if the backend rewords its message.)
                raise RuntimeError(
                    "this graph contains a host-python op (CustomOp / "
                    "pure_callback) but the active backend %r cannot "
                    "run host callbacks (remote/tunneled accelerator). "
                    "Run custom-op graphs on a host-attached backend — "
                    "e.g. JAX_PLATFORMS=cpu for development, or a "
                    "co-located TPU host in production." %
                    jax.default_backend()) from e
            raise
        if self._has_host_callback_ops:
            # Custom-op graphs run host-python callbacks on the runtime's
            # execution threads, and that host code dispatches jax ops of
            # its own. Letting the program run async while the caller
            # keeps dispatching eagerly can deadlock the CPU client (the
            # callback's dispatch waits on the pool the still-running
            # program occupies). Custom ops are a host round trip by
            # design ("escape hatch, not a fast path") — serialize them.
            jax.block_until_ready((outs, new_aux, self._cached_grads))
        if is_train:
            for n, a in zip(self._aux_names, self.aux_arrays):
                a._set_data(new_aux[n])
            self._fwd_inputs = (arg_vals, aux_vals, rng)
        else:
            # a non-train forward invalidates the training residuals so a
            # later backward() cannot silently use stale inputs
            self._fwd_inputs = None
        self.outputs = [_wrap(o) for o in outs]
        return self.outputs

    def _variant_flops(self, variant, arg_vals, aux_vals, rng):
        """XLA ``cost_analysis()`` FLOPs of one jit variant (trace +
        lower only; see TrainStep.cost_analysis for the same trick).
        None when the backend reports nothing."""
        try:
            if variant == "fwd_bwd":
                lowered = self._jit_fwd_bwd.lower(arg_vals, aux_vals,
                                                  rng)
            else:
                lowered = self._jit_fwd.lower(arg_vals, aux_vals, rng,
                                              variant == "train_fwd")
            ca = lowered.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else {}
            flops = float((ca or {}).get("flops", 0.0))
            return flops or None
        except Exception:    # noqa: BLE001 — cost analysis is advisory
            return None

    def _fwd_bwd_impl(self, arg_vals, aux_vals, rng):
        """One XLA program: outputs + new aux + grads (ones cotangent —
        the reference's head-grad convention, where loss heads ignore the
        incoming cotangent)."""
        from .base import env_flag
        wrt = {n: arg_vals[n] for n in self._grad_names}

        def f(wrt_vals):
            merged = dict(arg_vals)
            merged.update(wrt_vals)
            outs, new_aux = self._eval_fn(merged, aux_vals, rng, True)
            return outs, new_aux

        if env_flag("MXNET_BACKWARD_DO_MIRROR"):
            # gradient mirroring (reference graph_executor.cc:276-287,
            # env_var.md memonger): trade forward recompute for
            # activation memory — on TPU this is jax rematerialization
            f = jax.checkpoint(f)
        outs, vjp, new_aux = jax.vjp(f, wrt, has_aux=True)
        cots = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
        grads = vjp(cots)[0]
        return outs, new_aux, grads

    def _bwd_impl(self, arg_vals, aux_vals, rng, head_grads):
        """Re-derivation path for explicit head gradients."""
        from .base import env_flag
        wrt = tuple(arg_vals[n] for n in self._grad_names)

        def f(wrt_vals):
            merged = dict(arg_vals)
            merged.update(dict(zip(self._grad_names, wrt_vals)))
            outs, _ = self._eval_fn(merged, aux_vals, rng, True)
            return outs

        if env_flag("MXNET_BACKWARD_DO_MIRROR"):
            f = jax.checkpoint(f)
        outs, vjp = jax.vjp(f, wrt)
        grads = vjp(tuple(head_grads))[0]
        return dict(zip(self._grad_names, grads))

    def backward(self, out_grads=None, is_train=True):
        """Backprop through the bound graph (reference MXExecutorBackwardEx).

        With no `out_grads`, each head receives an all-ones cotangent —
        the reference's head-grad convention for loss-layer ops
        (SoftmaxOutput, MakeLoss). Heads propagate the incoming
        cotangent as a scale (identity under the ones default; the
        hook dynamic loss scaling rides on, ops/loss.py). In the
        default case the gradients were already produced by the fused
        forward program and this only writes them out."""
        if self._fwd_inputs is None:
            raise MXNetError("backward() requires a prior "
                             "forward(is_train=True)")
        arg_vals, aux_vals, rng = self._fwd_inputs
        if out_grads is None:
            self._prefer_fused = True
            if self._cached_grads is not None:
                grads = self._cached_grads
            else:
                head_grads = [jnp.ones(o.shape, o._data.dtype)
                              for o in self.outputs]
                grads = self._jit_bwd(arg_vals, aux_vals, rng,
                                      tuple(head_grads))
        else:
            self._prefer_fused = False
            if isinstance(out_grads, (NDArray, jax.Array, np.ndarray)):
                out_grads = [out_grads]
            head_grads = [g._data if isinstance(g, NDArray)
                          else jnp.asarray(g) for g in out_grads]
            grads = self._jit_bwd(arg_vals, aux_vals, rng,
                                  tuple(head_grads))
        if self._has_host_callback_ops:
            # see forward(): host-callback programs are serialized so
            # their callbacks can't deadlock against eager dispatch
            jax.block_until_ready(grads)
        for n, gbuf in zip(self._arg_names, self.grad_arrays):
            if gbuf is None or self._grad_req[n] == "null":
                continue
            if self._grad_req[n] == "add":
                gbuf._set_data(gbuf._data + grads[n])
            else:
                gbuf._set_data(grads[n])
        return [self.grad_dict[n] for n in self._grad_names]

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor with new input shapes (reference
        executor.py:reshape). jit recompiles per shape automatically, so this
        just reallocates the data arrays."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = []
        for n, a, s in zip(self._arg_names, self.arg_arrays, arg_shapes):
            if tuple(a.shape) == tuple(s):
                new_args.append(a)
            else:
                new_args.append(_nd.zeros(s, dtype=a.dtype))
        new_aux = []
        for n, a, s in zip(self._aux_names, self.aux_arrays, aux_shapes):
            new_aux.append(a if tuple(a.shape) == tuple(s)
                           else _nd.zeros(s, dtype=a.dtype))
        return Executor(self._symbol, self._ctx, args=new_args,
                        grad_req={n: r for n, r in self._grad_req.items()},
                        aux_states=new_aux, group2ctx=self._group2ctx,
                        mesh=self._mesh, layout=self._layout)

    def debug_str(self):
        return self._symbol.debug_str()
