"""Optimizers (reference: python/mxnet/optimizer.py, 1085 LoC).

API-faithful: registry + per-param lr/wd multipliers, `create_state`,
`update`, `Updater` for KVStore, `get_updater`. TPU-native: each update is
one fused registry op (ops/optimizer_ops.py — the analogue of the
reference's src/operator/optimizer_op.cc fused kernels); XLA fuses the whole
elementwise chain, and when an update runs inside a jitted step function it
fuses into the step itself. Multi-precision (mp_*) holds a float32 master
copy next to bf16/f16 weights — the TPU mixed-precision recipe.
"""
from __future__ import annotations

import logging
import math
import pickle
import threading
import warnings

import numpy as np

from .base import numeric_types, string_types
from .ndarray import NDArray, zeros, op as _op
from .ndarray.ndarray import array as _array

__all__ = ["Optimizer", "SGD", "Signum", "NAG", "SGLD", "DCASGD", "ccSGD",
           "Adam", "AdaGrad", "RMSProp", "AdaDelta", "Ftrl", "Adamax",
           "Nadam", "LAMB", "Test", "Updater", "get_updater", "create",
           "register", "opt_registry"]


class Optimizer:
    """Base optimizer (reference optimizer.py:Optimizer)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        """Register an optimizer class by (lowercased) name."""
        if not isinstance(klass, type):
            raise TypeError("can only register classes")
        name = klass.__name__.lower()
        prev = Optimizer.opt_registry.get(name)
        if prev is not None:
            warnings.warn("optimizer name %r: %s replaces %s"
                          % (name, klass, prev))
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        """Instantiate by registered name (reference
        optimizer.py:create_optimizer)."""
        try:
            klass = Optimizer.opt_registry[name.lower()]
        except KeyError:
            raise ValueError("no optimizer registered under %r" % name)
        return klass(**kwargs)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate

        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self._count_lock = threading.Lock()
        self.clip_gradient = clip_gradient
        self.multi_precision = False

        if param_idx2name is None:
            param_idx2name = {}
        assert isinstance(param_idx2name, dict), \
            "param_idx2name should be a dict of param indexes to names."
        self.idx2name = param_idx2name.copy()
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) \
            if sym is not None else ()
        self.param_dict = param_dict if param_dict else {}

        self.set_lr_mult({})
        self.set_wd_mult({})

    def create_state(self, index, weight):
        """Create optimizer state (momentum etc.) for one weight."""
        return None

    def create_state_multi_precision(self, index, weight):
        """State incl. the float32 master weight when multi-precision is on
        (reference optimizer.py:create_state_multi_precision)."""
        weight_master_copy = None
        if self.multi_precision and weight.dtype == np.float16:
            weight_master_copy = weight.astype(np.float32)
            return (weight_master_copy, self.create_state(index,
                                                          weight_master_copy))
        if weight.dtype == np.float16 and not self.multi_precision:
            warnings.warn("float16 optimizer state accumulates rounding "
                          "error (poor accuracy / slow convergence); pass "
                          "multi_precision=True to keep float32 master "
                          "weights")
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        """Apply one update. Subclasses override."""
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == np.float16:
            weight_master_copy, original_state = state
            grad32 = grad.astype(np.float32)
            self.update(index, weight_master_copy, grad32, original_state)
            weight[:] = weight_master_copy.astype(weight.dtype)
        else:
            self.update(index, weight, grad, state)

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning(
                "this optimizer's learning rate is driven by an "
                "LRScheduler; set_learning_rate would be overridden on "
                "the next update. Adjust the scheduler instead (or "
                "create the optimizer without one).")
        self.lr = lr

    def set_lr_scale(self, args_lrscale):  # pragma: no cover - deprecated
        raise DeprecationWarning("Use set_lr_mult instead.")

    def _sym_attr_mults(self, attr_key):
        """Collect __lr_mult__/__wd_mult__ symbol attrs into a dict."""
        if not self.sym_info:
            return {}
        attr, arg_names = self.sym_info
        return {n: float(attr[n][attr_key]) for n in arg_names
                if attr_key in attr.get(n, {})}

    def __getstate__(self):
        # optimizers travel by pickle (dist_async set_optimizer ships
        # them to the server); locks don't pickle — recreated on load
        d = self.__dict__.copy()
        d.pop("_count_lock", None)
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self._count_lock = threading.Lock()

    def set_lr_mult(self, args_lr_mult):
        """Per-param lr multipliers; also pulls ``__lr_mult__`` symbol attrs
        (reference optimizer.py:set_lr_mult)."""
        self.lr_mult = {**self._sym_attr_mults("__lr_mult__"),
                        **args_lr_mult}

    def set_wd_mult(self, args_wd_mult):
        """Per-param wd multipliers. As in the reference, params whose name
        does not end in _weight or _gamma default to wd_mult=0 (no decay
        on biases/betas)."""
        no_decay = {n: 0.0 for n in self.idx2name.values()
                    if not n.endswith(("_weight", "_gamma"))}
        self.wd_mult = {**no_decay, **self._sym_attr_mults("__wd_mult__"),
                        **args_wd_mult}

    def _update_count(self, index):
        # lock: the async PS applies distinct-key updates from
        # concurrent handler threads (parallel/ps_async.py per-key lock
        # table); per-index state is disjoint there, but num_update is
        # a SHARED scalar whose read-modify-write must not interleave
        # (a stale max would rewind lr schedules / bias correction)
        with self._count_lock:
            count = self._index_update_count.get(
                index, self.begin_num_update) + 1
            self._index_update_count[index] = count
            self.num_update = max(count, self.num_update)

    def _mult_for(self, index, mults, attr):
        """Resolve the per-param multiplier: param_dict beats explicit
        index entries beats name-keyed entries."""
        if index in self.param_dict:
            return getattr(self.param_dict[index], attr)
        if index in mults:
            return mults[index]
        return mults.get(self.idx2name.get(index), 1.0)

    def _get_lr(self, index):
        base = self.lr_scheduler(self.num_update) \
            if self.lr_scheduler is not None else self.lr
        return base * self._mult_for(index, self.lr_mult, "lr_mult")

    def _get_wd(self, index):
        return self.wd * self._mult_for(index, self.wd_mult, "wd_mult")

    # -- shared per-update preamble (the reference repeats these four
    #    lines in every optimizer's update body; factored here) ----------
    def _hypers(self, index):
        """Count this update and return (lr, wd) for the param."""
        self._update_count(index)
        return self._get_lr(index), self._get_wd(index)

    def _scaled(self, grad):
        """Rescale + clip a gradient for non-fused update math. Fused
        registry ops take rescale_grad/clip_gradient as attrs instead."""
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = _op.clip(g, -self.clip_gradient, self.clip_gradient)
        return g

register = Optimizer.register
create = Optimizer.create_optimizer
opt_registry = Optimizer.opt_registry


def _clip_attr(clip_gradient):
    return -1.0 if clip_gradient is None else clip_gradient


@register
class SGD(Optimizer):
    """SGD with momentum + optional multi-precision
    (reference optimizer.py:SGD; fused kernels sgd_update/sgd_mom_update/
    mp_sgd_* from src/operator/optimizer_op.cc)."""

    def __init__(self, momentum=0.0, multi_precision=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.multi_precision = multi_precision

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, dtype=weight.dtype)

    def create_state_multi_precision(self, index, weight):
        weight_master_copy = None
        if self.multi_precision and weight.dtype == np.float16:
            weight_master_copy = weight.astype(np.float32)
            return (self.create_state(index, weight_master_copy),
                    weight_master_copy)
        if weight.dtype == np.float16 and not self.multi_precision:
            warnings.warn("float16 optimizer state accumulates rounding "
                          "error (poor accuracy / slow convergence); pass "
                          "multi_precision=True to the SGD optimizer to "
                          "keep float32 master weights")
        return self.create_state(index, weight)

    def _update_impl(self, index, weight, grad, state, multi_precision=False):
        lr, wd = self._hypers(index)

        kwargs = dict(lr=lr, wd=wd, rescale_grad=self.rescale_grad,
                      clip_gradient=_clip_attr(self.clip_gradient))
        if not multi_precision:
            if state is not None:
                _op.sgd_mom_update(weight, grad, state, out=weight,
                                   momentum=self.momentum, **kwargs)
            else:
                _op.sgd_update(weight, grad, out=weight, **kwargs)
        else:
            if state[0] is not None:
                _op.mp_sgd_mom_update(weight, grad, state[0], state[1],
                                      out=weight, momentum=self.momentum,
                                      **kwargs)
            else:
                _op.mp_sgd_update(weight, grad, state[1], out=weight,
                                  **kwargs)

    def update(self, index, weight, grad, state):
        self._update_impl(index, weight, grad, state, multi_precision=False)

    def update_multi_precision(self, index, weight, grad, state):
        use_mp = self.multi_precision and weight.dtype == np.float16
        self._update_impl(index, weight, grad, state,
                          multi_precision=use_mp)


@register
class Signum(Optimizer):
    """SignSGD / Signum (fused signsgd_update; later-reference optimizer
    kept because the fused kernel exists here)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return zeros(weight.shape, dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        if state is not None:
            g = grad * self.rescale_grad
            if self.clip_gradient is not None:
                g = _op.clip(g, -self.clip_gradient, self.clip_gradient)
            state[:] = self.momentum * state - (1 - self.momentum) * \
                (g + wd * weight)
            weight[:] = weight + lr * _op.sign(state) - \
                lr * self.wd_lh * weight
        else:
            _op.signsgd_update(weight, grad, out=weight, lr=lr, wd=wd,
                               rescale_grad=self.rescale_grad,
                               clip_gradient=_clip_attr(self.clip_gradient))


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py:DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, dtype=weight.dtype), weight.copy())

    def update(self, index, weight, grad, state):
        lr, wd = self._hypers(index)
        grad = self._scaled(grad)

        mom, previous_weight = state
        if mom is not None:
            mom[:] *= self.momentum
            mom[:] += -lr * (grad + wd * weight + self.lamda *
                             grad * grad * (weight - previous_weight))
        else:
            assert self.momentum == 0.0
            mom = -lr * (grad + wd * weight + self.lamda *
                         grad * grad * (weight - previous_weight))
            state = (None, previous_weight)
        previous_weight[:] = weight
        weight[:] += mom


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference optimizer.py:NAG)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def update(self, index, weight, grad, state):
        lr, wd = self._hypers(index)
        grad = self._scaled(grad)

        if state is not None:
            mom = state
            mom[:] *= self.momentum
            grad += wd * weight
            mom[:] += grad
            grad[:] += self.momentum * mom
            weight[:] += -lr * grad
        else:
            assert self.momentum == 0.0
            weight[:] += -lr * (grad + wd * weight)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (reference
    optimizer.py:SGLD)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        lr, wd = self._hypers(index)
        grad = self._scaled(grad)
        from . import random as _rnd
        import jax
        noise = _array(np.asarray(
            jax.random.normal(_rnd.next_key(), weight.shape)) *
            math.sqrt(lr))
        weight[:] += -lr / 2 * (grad + wd * weight) + noise


@register
class ccSGD(SGD):  # pylint: disable=invalid-name
    """Deprecated alias of SGD (reference optimizer.py:ccSGD)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)


@register
class Adam(Optimizer):
    """Adam (reference optimizer.py:Adam; fused adam_update)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype),   # mean
                zeros(weight.shape, dtype=weight.dtype))   # variance

    def update(self, index, weight, grad, state):
        lr, wd = self._hypers(index)

        t = self._index_update_count[index]
        coef1 = 1. - self.beta1 ** t
        coef2 = 1. - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1

        mean, var = state
        _op.adam_update(weight, grad, mean, var, out=weight, lr=lr, wd=wd,
                        beta1=self.beta1, beta2=self.beta2,
                        epsilon=self.epsilon,
                        rescale_grad=self.rescale_grad,
                        clip_gradient=_clip_attr(self.clip_gradient))


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference optimizer.py:AdaGrad)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=weight.dtype)  # history

    def update(self, index, weight, grad, state):
        lr, wd = self._hypers(index)
        grad = self._scaled(grad)
        history = state
        history[:] += grad * grad
        weight[:] += -lr * (grad / _op.sqrt(history + self.float_stable_eps)
                            + wd * weight)


@register
class RMSProp(Optimizer):
    """RMSProp, Tieleman (centered=False) / Graves (centered=True) variants
    (reference optimizer.py:RMSProp; fused rmsprop/rmspropalex kernels)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, dtype=weight.dtype),  # n
                    zeros(weight.shape, dtype=weight.dtype),  # g
                    zeros(weight.shape, dtype=weight.dtype))  # delta
        return (zeros(weight.shape, dtype=weight.dtype),)     # n

    def update(self, index, weight, grad, state):
        lr, wd = self._hypers(index)

        kwargs = dict(lr=lr, wd=wd, gamma1=self.gamma1,
                      epsilon=self.epsilon,
                      rescale_grad=self.rescale_grad,
                      clip_gradient=_clip_attr(self.clip_gradient),
                      clip_weights=(self.clip_weights
                                    if self.clip_weights else -1.0))
        if not self.centered:
            (n,) = state
            _op.rmsprop_update(weight, grad, n, out=weight, **kwargs)
        else:
            n, g, delta = state
            _op.rmspropalex_update(weight, grad, n, g, delta, out=weight,
                                   gamma2=self.gamma2, **kwargs)


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference optimizer.py:AdaDelta)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype),  # E[g^2]
                zeros(weight.shape, dtype=weight.dtype))  # E[dx^2]

    def update(self, index, weight, grad, state):
        _, wd = self._hypers(index)
        grad = self._scaled(grad)

        acc_g, acc_delta = state
        acc_g[:] = self.rho * acc_g + (1. - self.rho) * grad * grad
        current_delta = (_op.sqrt(acc_delta + self.epsilon) /
                         _op.sqrt(acc_g + self.epsilon)) * grad
        acc_delta[:] = self.rho * acc_delta + \
            (1. - self.rho) * current_delta * current_delta
        weight[:] -= current_delta + wd * weight


@register
class Ftrl(Optimizer):
    """FTRL-proximal (reference optimizer.py:Ftrl; fused ftrl_update)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(**kwargs)
        self.lamda1 = lamda1
        self.beta = beta
        self.lr = learning_rate

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype),  # z
                zeros(weight.shape, dtype=weight.dtype))  # n

    def update(self, index, weight, grad, state):
        assert isinstance(weight, NDArray)
        assert isinstance(grad, NDArray)
        self._update_count(index)
        wd = self._get_wd(index)
        lr = self._get_lr(index)

        z, n = state
        _op.ftrl_update(weight, grad, z, n, out=weight, lr=lr, wd=wd,
                        lamda1=self.lamda1, beta=self.beta,
                        rescale_grad=self.rescale_grad,
                        clip_gradient=_clip_attr(self.clip_gradient))


@register
class Adamax(Optimizer):
    """AdaMax, infinity-norm Adam variant (reference
    optimizer.py:Adamax)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype),  # mean
                zeros(weight.shape, dtype=weight.dtype))  # variance

    def update(self, index, weight, grad, state):
        lr, wd = self._hypers(index)

        t = self._index_update_count[index]
        lr /= (1. - self.beta1 ** t)

        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = _op.clip(grad, -self.clip_gradient, self.clip_gradient)

        m_t, u_t = state
        m_t[:] = self.beta1 * m_t + (1. - self.beta1) * grad
        u_t[:] = _op.maximum(self.beta2 * u_t, _op.abs(grad))
        weight[:] -= lr * m_t / u_t


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference optimizer.py:Nadam)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype),  # mean
                zeros(weight.shape, dtype=weight.dtype))  # variance

    def update(self, index, weight, grad, state):
        lr, wd = self._hypers(index)

        t = self._index_update_count[index]

        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = _op.clip(grad, -self.clip_gradient, self.clip_gradient)

        momentum_t = self.beta1 * (1. - 0.5 * 0.96 ** (
            t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1. - 0.5 * 0.96 ** (
            (t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1

        m_t, v_t = state
        m_t[:] = self.beta1 * m_t + (1. - self.beta1) * grad
        v_t[:] = self.beta2 * v_t + (1. - self.beta2) * grad * grad

        grad_prime = grad / (1. - self.m_schedule)
        m_t_prime = m_t / (1. - m_schedule_next)
        v_t_prime = v_t / (1. - self.beta2 ** t)
        m_t_bar = (1. - momentum_t) * grad_prime + \
            momentum_t_1 * m_t_prime

        weight[:] -= lr * m_t_bar / (_op.sqrt(v_t_prime) + self.epsilon)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive Adam for large-batch TPU training (extension:
    the reference predates LAMB; included because large-batch data parallel
    is the TPU scaling mode — You et al. 2019)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=1e-3, upper_bound=10.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound

    def create_state(self, index, weight):
        return (zeros(weight.shape, dtype=weight.dtype),
                zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]

        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = _op.clip(grad, -self.clip_gradient, self.clip_gradient)

        m, v = state
        m[:] = self.beta1 * m + (1. - self.beta1) * grad
        v[:] = self.beta2 * v + (1. - self.beta2) * grad * grad
        m_hat = m / (1. - self.beta1 ** t)
        v_hat = v / (1. - self.beta2 ** t)
        update = m_hat / (_op.sqrt(v_hat) + self.epsilon) + wd * weight
        # trust ratio computed on-device: no host sync in the update path
        w_norm = _op.norm(weight)
        u_norm = _op.norm(update)
        ratio = _op.where(w_norm * u_norm > 0,
                          _op.clip(w_norm / (u_norm + 1e-30),
                                   self.lower_bound, self.upper_bound),
                          _op.ones_like(w_norm))
        weight[:] -= lr * ratio * update


@register
class Test(Optimizer):
    """Mock optimizer for update-path tests (reference
    optimizer.py:1002)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        weight[:] += grad * self.rescale_grad
        state[:] = weight


class Updater:
    """KVStore updater closure over an Optimizer (reference
    optimizer.py:1019 get_updater/Updater): lazily creates per-key state on
    first update; states picklable via get_states/set_states."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = \
                self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        elif not self.states_synced[index]:
            self.states[index] = \
                self.sync_state_context(self.states[index], weight.context)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad,
                                              self.states[index])

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, np.ndarray):  # revived from get_states pickle
            return _array(state, ctx=context)
        if isinstance(state, (tuple, list)):
            return type(state)(
                self.sync_state_context(i, context) for i in state)
        return state

    def set_states(self, states):
        """Load pickled states (reference Updater.set_states)."""
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        """Pickle states (+ optionally the optimizer itself)."""
        def to_np(s):
            if isinstance(s, NDArray):
                return s.asnumpy()
            if isinstance(s, (tuple, list)):
                return type(s)(to_np(i) for i in s)
            return s
        states = {k: to_np(v) for k, v in self.states.items()}
        return pickle.dumps((states, self.optimizer) if dump_optimizer
                            else states)


def get_updater(optimizer):
    """Wrap an optimizer as a kvstore updater fn (reference
    optimizer.py:get_updater)."""
    return Updater(optimizer)
