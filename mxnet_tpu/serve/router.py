"""Fleet router: one serving endpoint over N ServeServer replicas
(docs/serving.md §fleet).

A single :class:`~mxnet_tpu.serve.ServeEngine` is one process — one
batcher, one queue, one chip's worth of decode slots. Millions of
users need N replicas behind one endpoint, which is exactly the
paper's KVStore identity replayed on the inference side: many workers,
one logical service, load balanced and failure-masked. The
:class:`ServeRouter` supplies the missing layer:

* **Least-loaded dispatch** — every request goes to the replica with
  the lowest load score. The score is
  ``router-tracked in-flight + last-polled queue depth``: the
  in-flight count is exact and instantaneous (the router increments it
  at dispatch, decrements at response), the polled queue depth folds
  in load from OTHER frontends sharing the replica. Requests whose row
  count fits a bucket some subset has WARMED prefer that subset — a
  cold replica never costs a live request an XLA compile when a warm
  one is free.
* **Decode session affinity** — a request carrying ``session=`` pins
  to the replica holding that session's KV slot; the first request of
  a session places it on the replica with the most free decode slots
  (falling back to least-loaded when no replica reports
  ``decode_free_slots``). A pinned session never reroutes on
  ``Overloaded`` (its decode state is ON that replica — shedding is a
  backpressure signal to the caller, not a reason to orphan a KV
  slot); a pin to a draining/removed replica is dropped and the
  session re-places like a new one (state loss, the caller re-prefills).
* **Shed-and-retry** — an ``Overloaded`` (or drain-window
  ``EngineClosed``) from one replica retries on the
  next-least-loaded, via :meth:`RetryPolicy.run`'s ``on_fatal``
  reroute hook; ``Overloaded`` reaches the caller only when EVERY
  live replica shed this request. Transport faults mark the replica
  *suspect* (deprioritized, revived by the next successful stats
  poll or dispatch) and reroute — every failure path is
  deterministically injectable because all bytes still move through
  ``serve/net.py``'s FaultInjector'd plumbing, under per-replica
  point families (``router<I>_send``/``router<I>_recv`` data,
  ``router<I>_ctl_*`` control).
* **Zero-drop rolling restarts** — :meth:`recycle` stops routing to
  the replica, waits for its drain (the router's own in-flight
  condition PLUS the stats-observed engine in-flight, so work from
  other frontends counts too), runs the caller's ``restart`` hook
  (typically SIGTERM → the PR 3 GracefulShutdown drain → fresh
  process), re-warms the declared buckets over the wire, and
  readmits. A client sweep running throughout observes exactly one
  response per request.
* **Replica death survival** (docs/robustness.md, fleet failure
  semantics) — the router is the durable owner of every generate's
  recovery state. When the replica pinned to an in-flight generate
  dies mid-call (transport fault + failed control probe), the
  request REPLAYS on a survivor from its retained recovery record
  (prompt, sampling opts, seed, handoff blob) — token-for-token
  identical, because prefill is pure and per-request PRNG streams
  split once per emitted token; every generate carries an admit id,
  so a replay onto a replica that actually survived rides the
  original admission (decode-side dedup — exactly-once admit). A
  recycle of a decode-role replica EVACUATES instead of draining:
  active sessions export mid-decode (``evacuate`` frame) and resume
  on survivors bit-exactly, so the restart is bounded by
  export+import cost, not the longest sequence in flight.
  ``MXNET_ROUTER_FAILOVER`` / ``MXNET_ROUTER_MIGRATION_LIMIT``
  govern both paths.

The router IS an engine to the front end: ``ServeServer(router)``
serves the same wire (infer/ping/stats/hello/warm frames) — clients
cannot tell a router from a replica. All router transport rides
:class:`~mxnet_tpu.serve.ServeClient`; this module never touches a
socket (lint-enforced, tools/perf_gate.sh).
"""
from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from .. import config as _config
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..generation import kv_blob_nbytes
from ..parallel.resilience import RetryPolicy
from .decode import drain_timeout as _decode_drain_timeout
from .engine import EngineClosed, Overloaded, ServeError
from .net import ServeClient

__all__ = ["ServeRouter", "ReplicaState"]


class ReplicaState:
    """The three dispatchability states of a fleet member."""
    LIVE = "live"            # routable
    SUSPECT = "suspect"      # transport fault seen; last-resort only
    DRAINING = "draining"    # recycling / externally draining; never
    #                          routed, readmitted by recycle()


class _DoneFuture:
    """An already-resolved response with the ServeFuture surface —
    router dispatch is synchronous in the calling thread (concurrency
    comes from concurrent front-end connections, exactly like the
    engine's contract), so the future the front end waits on is
    always complete."""

    __slots__ = ("_value", "_exc")

    def __init__(self, value=None, exc=None):
        self._value = value
        self._exc = exc

    def done(self):
        return True

    def result(self, timeout=None):
        del timeout
        if self._exc is not None:
            raise self._exc
        return self._value


class _Replica:
    """Router-side record of one fleet member: its control client,
    pooled data clients, dispatch accounting, and the last-polled
    load signals."""

    __slots__ = ("name", "host", "port", "index", "state", "control",
                 "idle", "inflight", "dispatched", "rerouted_from",
                 "faults", "stats", "declared", "role", "recycles",
                 "model_id", "window")

    def __init__(self, name, host, port, index):
        self.name = name
        self.host = host
        self.port = int(port)
        self.index = index               # fault-point family id; stable
        self.state = ReplicaState.LIVE
        self.control = None              # ServeClient (stats/warm/hello)
        self.idle = deque()              # pooled data ServeClients
        self.inflight = 0                # router-dispatched, unresolved
        self.dispatched = 0
        self.rerouted_from = 0           # sheds/faults that left here
        self.faults = 0
        self.stats = {}                  # last successful poll extract
        self.declared = {}               # hello() engine state
        self.role = None                 # hello-declared replica role
        self.recycles = 0
        self.model_id = None             # hello-declared artifact stamp
        self.window = {}                 # prev cumulative counters, for
        #                                  the per-poll-window rates

    def describe(self):
        return {"host": self.host, "port": self.port,
                "state": self.state, "role": self.role,
                "model_id": self.model_id,
                "in_flight": self.inflight,
                "dispatched": self.dispatched,
                "rerouted_from": self.rerouted_from,
                "faults": self.faults, "recycles": self.recycles,
                "stats": dict(self.stats)}


def _not_prefill(rep):
    """The default dispatchability predicate: every role but dedicated
    prefill (legacy fleets declare no role at all and stay fully
    dispatchable — today's colocated behavior, bit for bit)."""
    return rep.role != "prefill"


def _parse_addr(addr):
    if isinstance(addr, (tuple, list)):
        host, port = addr
        return str(host), int(port)
    host, _, port = str(addr).rpartition(":")
    if not host:
        raise ValueError("replica address wants HOST:PORT or "
                         "(host, port), got %r" % (addr,))
    return host, int(port)


class ServeRouter:
    """Least-loaded fan-out over a pool of serving replicas.

    Parameters
    ----------
    replicas : iterable, optional
        Initial fleet: ``"host:port"`` strings or ``(host, port)``
        tuples (more via :meth:`add_replica`).
    retry : RetryPolicy, optional
        The DISPATCH policy (reroutes + transport retries share its
        budget/backoff). Default: fleet-sized — ``max(8, replicas+2)``
        retries at 5 ms base backoff, so every live replica gets its
        chance to shed before Overloaded reaches the caller.
    poll_ms / conns_per_replica / session_cap / drain_timeout
        Override ``MXNET_ROUTER_POLL_MS`` / ``MXNET_ROUTER_CONNS`` /
        ``MXNET_ROUTER_SESSION_CAP`` / ``MXNET_ROUTER_DRAIN_TIMEOUT``.
        ``poll_ms=0`` disables the background poller (tests drive
        :meth:`poll_now` explicitly — every router code path is then
        deterministic).
    io_timeout : float, optional
        Socket timeout for the per-replica clients (default
        ``MXNET_ROUTER_IO_TIMEOUT``, 30 s; 0 = unbounded — a hung
        replica then wedges its dispatch thread instead of failing
        over).
    """

    role = "router"                      # the hello frame's identity

    def __init__(self, replicas=None, retry=None, poll_ms=None,
                 conns_per_replica=None, session_cap=None,
                 drain_timeout=None, io_timeout=None, logger=None):
        self._log = logger or logging.getLogger(__name__)
        self._user_retry = retry          # None = fleet-sized default
        #                                   built per dispatch
        self._poll_ms = float(poll_ms if poll_ms is not None
                              else _config.get("MXNET_ROUTER_POLL_MS"))
        self._conns = int(conns_per_replica
                          if conns_per_replica is not None
                          else _config.get("MXNET_ROUTER_CONNS"))
        self._session_cap = int(session_cap if session_cap is not None
                                else _config.get(
                                    "MXNET_ROUTER_SESSION_CAP"))
        self._drain_timeout = float(
            drain_timeout if drain_timeout is not None
            else _config.get("MXNET_ROUTER_DRAIN_TIMEOUT"))
        if io_timeout is None:
            io_timeout = float(_config.get("MXNET_ROUTER_IO_TIMEOUT"))
        # bounded by default: a replica that accepts but never answers
        # must surface as a transport fault (suspect + reroute), not
        # wedge the dispatching thread and the poller forever
        self._io_timeout = io_timeout or None

        self._replicas = OrderedDict()   # name -> _Replica
        self._sessions = OrderedDict()   # session id -> replica name
        self._next_index = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False

        self._g_replicas = _telemetry.gauge("serve.router.replicas")
        self._g_live = _telemetry.gauge("serve.router.replicas_live")
        self._g_inflight = _telemetry.gauge("serve.router.inflight")
        self._g_sessions = _telemetry.gauge("serve.router.sessions")
        self._c_dispatched = _telemetry.counter(
            "serve.router.dispatched")
        self._c_rerouted = _telemetry.counter("serve.router.rerouted")
        self._c_shed = _telemetry.counter("serve.router.shed")
        self._c_suspected = _telemetry.counter("serve.router.suspected")
        self._c_revived = _telemetry.counter("serve.router.revived")
        self._c_recycles = _telemetry.counter("serve.router.recycles")
        self._c_sessions_placed = _telemetry.counter(
            "serve.router.sessions_placed")
        self._c_sessions_replaced = _telemetry.counter(
            "serve.router.sessions_replaced")
        self._h_dispatch = _telemetry.histogram(
            "serve.router.dispatch_ms")
        # disaggregation accounting (docs/serving.md §disaggregated
        # prefill): prefills this router fanned to prefill replicas,
        # generate requests it completed, and the handoff blob bytes
        # it shipped decode-ward (byte-scale buckets, 1 KiB..64 MiB)
        self._c_generates = _telemetry.counter("serve.router.generates")
        self._c_streams = _telemetry.counter("serve.router.streams")
        self._c_prefills = _telemetry.counter("serve.prefill.dispatched")
        self._h_handoff = _telemetry.histogram(
            "serve.router.handoff_bytes",
            buckets=tuple(float(1 << s) for s in range(10, 27, 2)))
        # replica-death survival accounting (docs/robustness.md):
        # replays = generate attempts re-sent after a transport fault
        # (same replica when the probe says it lives, a survivor when
        # it is dead); failovers = the dead-replica subset of those;
        # migrations = evacuated sessions resumed on a survivor;
        # evacuations = evacuate frames a migrating recycle sent
        self._c_failovers = _telemetry.counter("serve.router.failovers")
        self._c_replays = _telemetry.counter("serve.router.replays")
        self._c_migrations = _telemetry.counter(
            "serve.router.migrations")
        self._c_evacuations = _telemetry.counter(
            "serve.router.evacuations")
        self._failover = bool(_config.get("MXNET_ROUTER_FAILOVER"))
        self._migration_limit = int(
            _config.get("MXNET_ROUTER_MIGRATION_LIMIT"))
        # admit-id source (PR 1's (cid, seq) pattern on the serving
        # side): unique per router instance ACROSS processes, so two
        # routers sharing a fleet can never collide in a replica's
        # dedup table
        self._admit_cid = "g%d.%x" % (os.getpid(), id(self) & 0xFFFFFF)
        self._admit_seq = itertools.count(1)

        _telemetry.journal_event("serve.router.start",
                                 poll_ms=self._poll_ms)
        try:
            for addr in (replicas or ()):
                host, port = _parse_addr(addr)
                self.add_replica(host, port)
        except BaseException:
            # a later replica failing registration must not leak the
            # already-connected control clients — the caller gets an
            # exception, never a router object to close()
            self.close()
            raise

        self._poll_thread = None
        self._poll_stop = threading.Event()
        if self._poll_ms > 0:
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="mxnet-router-poll",
                daemon=True)
            self._poll_thread.start()

    # -- fleet membership ---------------------------------------------------
    def add_replica(self, host, port, name=None, warm=False):
        """Register a replica, hello it (learning its declared buckets
        and engine identity), take a first stats poll, and admit it to
        dispatch. ``warm=True`` pre-compiles the declared buckets over
        the wire BEFORE the replica becomes routable (it registers
        draining, warms, then flips live) — a freshly spawned replica
        never pays a cold XLA compile on a live request (the fleet
        controller's scale-out path). Returns the replica's name."""
        with self._lock:
            if self._closed:
                raise EngineClosed("router is closed")
            index = self._next_index
            self._next_index += 1
            name = name or ("replica%d" % index)
            if name in self._replicas:
                raise ValueError("duplicate replica name %r" % name)
            rep = _Replica(name, host, port, index)
            if warm:
                # warm-before-admit: not routable until the buckets
                # are compiled (dispatch skips DRAINING)
                rep.state = ReplicaState.DRAINING
            rep.control = self._make_client(rep, control=True)
            self._replicas[name] = rep

        def unwind():
            with self._lock:
                self._replicas.pop(name, None)
            rep.control.close()
        try:
            rep.declared = rep.control.hello()
        except ServeError:
            # a replica that answers but errors is misconfigured —
            # surface it, and do NOT leave the half-registered entry
            # routable (or its control socket open)
            unwind()
            raise
        except Exception as exc:         # noqa: BLE001 — classified:
            # transport-unreachable at registration is the operator's
            # problem to know about NOW, not at first dispatch
            unwind()
            raise ConnectionError(
                "replica %s at %s:%d unreachable at registration: %s"
                % (name, host, port, exc)) from exc
        rep.role = (rep.declared or {}).get("role")
        rep.model_id = (rep.declared or {}).get("model_id")
        if warm:
            try:
                self._warm_replica(rep)   # ServeError declines logged
            except Exception as exc:      # noqa: BLE001 — transport
                # mid-warm: same contract as an unreachable hello —
                # the caller never gets a half-admitted replica
                unwind()
                raise ConnectionError(
                    "replica %s at %s:%d died during pre-admission "
                    "warm: %s" % (name, host, int(port), exc)) from exc
            with self._lock:
                rep.state = ReplicaState.LIVE
        self._poll_replica(rep)
        self._update_gauges()
        _telemetry.journal_event(
            "serve.router.add_replica", name=name,
            addr="%s:%d" % (host, int(port)), role=rep.role,
            warmed=bool(warm))
        return name

    def remove_replica(self, name):
        """Drop a replica from dispatch immediately and close its
        clients (in-flight requests to it fail over through the normal
        fault path). Pinned sessions re-place on next use."""
        with self._lock:
            rep = self._replicas.pop(name, None)
            if rep is None:
                raise KeyError("no replica %r" % name)
            for sid in [s for s, n in self._sessions.items()
                        if n == name]:
                self._sessions.pop(sid, None)
            idle = list(rep.idle)
            rep.idle.clear()
        for cl in idle + [rep.control]:
            if cl is not None:
                cl.close()
        self._update_gauges()
        _telemetry.journal_event("serve.router.remove_replica",
                                 name=name)

    def replicas(self):
        """{name: replica description} — live router-side accounting
        plus the last-polled load signals per replica."""
        with self._lock:
            return {n: r.describe() for n, r in self._replicas.items()}

    # -- clients ------------------------------------------------------------
    def _make_client(self, rep, control=False):
        pts = "router%d_ctl" % rep.index if control \
            else "router%d" % rep.index
        # data clients carry NO transport retry budget of their own:
        # a fault must surface to the dispatch loop immediately so the
        # request reroutes to another replica instead of hammering a
        # dead one. The control client keeps a small budget (polls and
        # warms tolerate a blip; nothing reroutes them).
        retry = RetryPolicy(max_retries=2, base_delay=0.01,
                            seed="router:%s:ctl" % rep.name) if control \
            else RetryPolicy(max_retries=0, seed="router:%s" % rep.name)
        return ServeClient(rep.host, rep.port, retry=retry,
                           timeout=self._io_timeout, fault_points=pts,
                           logger=self._log)

    def _acquire(self, rep):
        with self._lock:
            if rep.idle:
                return rep.idle.popleft()
        return self._make_client(rep)

    def _release(self, rep, client):
        with self._lock:
            if self._replicas.get(rep.name) is rep and \
                    rep.state != ReplicaState.DRAINING and \
                    len(rep.idle) < self._conns and not self._closed:
                # (the identity check matters: a replica removed while
                # this request was in flight must not collect live
                # sockets into its orphaned pool — nothing would ever
                # close them)
                rep.idle.append(client)
                return
        client.close()

    # -- load signals -------------------------------------------------------
    @staticmethod
    def _extract(stats_reply):
        eng = (stats_reply or {}).get("engine") or {}
        out = {"queue_depth": int(eng.get("queue_depth") or 0),
               "in_flight": int(eng.get("in_flight") or 0),
               "warmed": list(eng.get("warmed") or []),
               "buckets": list(eng.get("buckets") or []),
               "draining": bool(eng.get("draining"))}
        if eng.get("decode_free_slots") is not None:
            out["decode_free_slots"] = int(eng["decode_free_slots"])
        if eng.get("shed") is not None:
            out["shed"] = int(eng["shed"])
        if eng.get("admitted") is not None:
            out["admitted"] = int(eng["admitted"])
        return out

    # the windowed-rate signals: which cumulative counter feeds which
    # per-poll-window rate (delta since the previous successful poll —
    # the fleet controller's scale signals, rendered by
    # tools/telemetry_report.py --stats for humans)
    _RATES = (("shed", "shed_rate"), ("admitted", "req_rate"))

    def _poll_replica(self, rep):
        """One stats round trip; success refreshes the cached load
        signals and revives a suspect, failure marks suspect. Besides
        the raw extract, each poll derives the per-window rates
        (``shed_rate``/``req_rate``): the delta of the replica's
        cumulative counter since the previous successful poll. A
        counter that went BACKWARDS means the replica restarted — the
        window restarts with it (rate = counts since the restart),
        never a negative rate. The first poll of a replica's life
        reports 0 (no window exists yet)."""
        try:
            reply = rep.control.stats()
        except Exception as exc:          # noqa: BLE001 — any failure
            # to observe the replica is a health signal, not a crash
            self._mark_suspect(rep, exc)
            return False
        with self._lock:
            st = self._extract(reply)
            for cum, rate in self._RATES:
                new = st.get(cum)
                if new is None:
                    continue
                prev = rep.window.get(cum)
                if prev is None:
                    st[rate] = 0
                else:
                    st[rate] = new - prev if new >= prev else new
                rep.window[cum] = new
            rep.stats = st
        if rep.state == ReplicaState.SUSPECT:
            self._revive(rep)
        return True

    def poll_now(self):
        """Synchronously refresh every replica's cached stats (the
        background poller's body; deterministic tests call this
        instead of running the poller)."""
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            self._poll_replica(rep)
        self._update_gauges()

    def _poll_loop(self):
        # dedicated event, NOT self._cond: dispatch completions
        # notify_all() that condition constantly, which would wake the
        # poller after nearly every request and turn the configured
        # poll period into a continuous stats hammer under load
        failing = False
        while not self._poll_stop.wait(self._poll_ms / 1000.0):
            try:
                self.poll_now()
                failing = False
            except Exception:  # noqa: BLE001 — the poller must outlive
                # any one bad stats frame: an uncaught error here used
                # to kill the thread silently, freezing load scores
                # and suspect revival for the router's lifetime. Log
                # the FIRST failure of a streak loudly, the rest at
                # debug (a flapping replica must not flood the log).
                if not failing:
                    self._log.exception(
                        "router: poll_now failed — poller keeps "
                        "running (repeats logged at debug)")
                else:
                    self._log.debug("router: poll_now failed again",
                                    exc_info=True)
                failing = True

    def _probe(self, rep):
        """Is the replica's process demonstrably alive? One control
        ping — the failover discriminator between a transport blip on
        a surviving replica (replay to the SAME replica; its admit-id
        dedup makes that exactly-once) and a dead one (replay on a
        survivor). Any failure to answer means dead for failover
        purposes; the poller keeps probing afterwards and revives it
        when it answers stats again."""
        try:
            return bool(rep.control.ping())
        except Exception:  # noqa: BLE001 — unreachable = not alive
            return False

    def probe_replica(self, name):
        """The liveness probe by name — the failover discriminator
        (:meth:`_probe`), exposed for the fleet controller's heal
        decision: True iff the replica's process answers a control
        ping right now."""
        with self._lock:
            rep = self._replicas.get(name)
        if rep is None:
            raise KeyError("no replica %r" % name)
        return self._probe(rep)

    def canary(self, name, inputs, timeout=None):
        """One infer pinned to the NAMED replica — no load balancing,
        no reroute, no retry: the fleet controller's rollout health
        gate (a freshly promoted replica must answer this within its
        deadline or the rollout rolls back). Uses a dedicated one-shot
        client so ``timeout`` bounds the whole round trip; typed
        replica errors and transport faults both propagate to the
        caller — every failure mode IS the gate's signal. Not counted
        as a dispatch (it is control-plane traffic, like warm)."""
        with self._lock:
            rep = self._replicas.get(name)
        if rep is None:
            raise KeyError("no replica %r" % name)
        arrays = [np.asarray(a) for a in inputs]
        client = ServeClient(
            rep.host, rep.port,
            retry=RetryPolicy(max_retries=0,
                              seed="router:%s:canary" % rep.name),
            timeout=float(timeout) if timeout else self._io_timeout,
            fault_points="router%d_ctl" % rep.index, logger=self._log)
        try:
            return client.request(arrays)
        finally:
            client.close()

    def _mark_suspect(self, rep, exc):
        with self._lock:
            rep.faults += 1
            was = rep.state
            if rep.state == ReplicaState.LIVE:
                rep.state = ReplicaState.SUSPECT
        if was == ReplicaState.LIVE:
            self._c_suspected.inc()
            _telemetry.journal_event("serve.router.suspect",
                                     name=rep.name,
                                     error=type(exc).__name__)
            self._log.warning("router: replica %s suspect after %s",
                              rep.name, exc)
            self._update_gauges()

    def _revive(self, rep):
        with self._lock:
            was = rep.state
            if rep.state == ReplicaState.SUSPECT:
                rep.state = ReplicaState.LIVE
        if was == ReplicaState.SUSPECT:
            self._c_revived.inc()
            _telemetry.journal_event("serve.router.revive",
                                     name=rep.name)
            self._update_gauges()

    def _update_gauges(self):
        with self._lock:
            reps = list(self._replicas.values())
            self._g_replicas.set(len(reps))
            self._g_live.set(sum(r.state == ReplicaState.LIVE
                                 for r in reps))
            self._g_inflight.set(sum(r.inflight for r in reps))
            self._g_sessions.set(len(self._sessions))

    # -- dispatch -----------------------------------------------------------
    @staticmethod
    def _score(rep):
        """Lower routes first. Router-tracked in-flight is exact and
        current; the polled queue depth folds in other frontends'
        load; the index breaks ties deterministically (registration
        order)."""
        return (rep.inflight + rep.stats.get("queue_depth", 0),
                rep.index)

    @staticmethod
    def _warm_for(rep, rows):
        """Is this replica compiled for a rows-sized request? Batch
        replicas warm PADDED buckets (any bucket >= rows serves);
        a prefill replica's 'warmed' entries are EXACT prompt lengths
        (the prefill graph specializes per (B, P)) — only an exact
        match avoids the cold compile the ranking exists to dodge."""
        warmed = rep.stats.get("warmed") or ()
        if rep.role == "prefill":
            return rows in warmed
        return any(b >= rows for b in warmed)

    def _candidates(self, rows, exclude, want=None):
        """Dispatchable replicas, best first: live before suspect
        (suspects are last-resort, so a one-replica fleet still rides
        out a transport blip), warmed-for-this-size before cold,
        least-loaded within each class. ``want``: optional role
        predicate — the disaggregated paths restrict a leg to its
        phase's replicas (prefill leg → role 'prefill', decode leg →
        role 'decode'); ``None`` = the infer/colocated default, every
        role except dedicated prefill (a prefill replica cannot
        answer anything but the prefill frame)."""
        if want is None:
            want = _not_prefill
        live, suspect = [], []
        for rep in self._replicas.values():
            if not want(rep) or rep.name in exclude or \
                    rep.state == ReplicaState.DRAINING or \
                    rep.stats.get("draining"):
                # the polled flag catches an EXTERNALLY draining
                # replica (its own SIGTERM) at poll time — no need to
                # pay a doomed round trip per request to notice; the
                # next poll clears it if the replica comes back
                continue
            (live if rep.state == ReplicaState.LIVE
             else suspect).append(rep)
        for pool in (live, suspect):
            pool.sort(key=lambda r: (not self._warm_for(r, rows),)
                      + self._score(r))
        return live + suspect

    def _pick(self, rows, session, exclude, fresh_pins, want=None):
        """Choose and charge the target replica (inflight++ under the
        lock, so concurrent dispatches see each other's load).
        Returns ``(replica, established)`` — established means the
        session pin predates this dispatch (KV state exists on that
        replica, so a shed there must NOT reroute); a pin placed by
        this very dispatch (``fresh_pins``) is speculative and free to
        move. ``want`` restricts the leg to a role (see
        :meth:`_candidates`); a pin to a replica outside the wanted
        role re-places like a pin to a drained one (the fleet's
        topology changed under the session)."""
        if want is None:
            want = _not_prefill
        with self._lock:
            if self._closed:
                raise EngineClosed("router is closed")
            if session is not None:
                pinned = self._replicas.get(self._sessions.get(session))
                if pinned is not None and want(pinned) and \
                        pinned.state != ReplicaState.DRAINING and \
                        not pinned.stats.get("draining") and \
                        pinned.name not in exclude:
                    self._sessions.move_to_end(session)   # LRU touch
                    pinned.inflight += 1
                    pinned.dispatched += 1
                    return pinned, pinned.name not in fresh_pins
                if self._sessions.pop(session, None) is not None:
                    # the pin's replica is draining/gone (or this
                    # dispatch's own speculative pin failed): the
                    # session re-places fresh
                    self._c_sessions_replaced.inc()
            cands = self._candidates(rows, exclude, want)
            if not cands:
                self._c_shed.inc()
                _telemetry.journal_event("serve.router.all_shed",
                                         tried=len(exclude))
                raise Overloaded(
                    "every live replica shed or is unavailable "
                    "(%d tried, %d draining/suspect-excluded)"
                    % (len(exclude),
                       len(self._replicas) - len(exclude)))
            if session is not None:
                # new session: most free decode slots wins (that's
                # where its KV slot will live); least-loaded when no
                # replica reports slot counts. Only among LIVE
                # replicas while any exist — a suspect's stale stats
                # must not win it a long-lived pin (_candidates
                # already sorts live first, so cands[0] is live iff
                # any live candidate exists)
                pool = [r for r in cands
                        if r.state == ReplicaState.LIVE] or cands
                rep = min(pool, key=lambda r: (
                    -r.stats.get("decode_free_slots", 0),)
                    + self._score(r))
                self._sessions[session] = rep.name
                fresh_pins.add(rep.name)
                self._c_sessions_placed.inc()
                while len(self._sessions) > self._session_cap:
                    self._sessions.popitem(last=False)
            else:
                rep = cands[0]
            rep.inflight += 1
            rep.dispatched += 1
            return rep, False

    def _has_other_candidate(self, rep, exclude, want=None):
        """Is any OTHER replica dispatchable right now? (the honesty
        test for the reroute counter)"""
        if want is None:
            want = _not_prefill
        with self._lock:
            return any(r is not rep and want(r)
                       and r.name not in exclude
                       and r.state != ReplicaState.DRAINING
                       and not r.stats.get("draining")
                       for r in self._replicas.values())

    def _finish_dispatch(self, rep):
        with self._cond:
            rep.inflight -= 1
            self._cond.notify_all()       # recycle() waits on this

    def submit(self, *inputs, deadline_ms=None, tc=None, session=None):
        """The engine-surface entry (ServeServer calls this): dispatch
        synchronously, return an already-resolved future. Typed errors
        raise here exactly like ServeEngine.submit's admission errors
        (Overloaded only when every live replica shed)."""
        return _DoneFuture(self._dispatch(
            [np.asarray(a) for a in inputs], deadline_ms, session, tc))

    def request(self, inputs, deadline_ms=None, session=None):
        """Blocking convenience twin of ServeClient.request for
        in-process callers (the fleet bench drives this)."""
        return self._dispatch([np.asarray(a) for a in inputs],
                              deadline_ms, session, None)

    def infer(self, *inputs, deadline_ms=None, session=None,
              timeout=None):
        """submit + result in one call (engine-surface parity;
        ``timeout`` is accepted for signature parity — dispatch is
        synchronous, so the response is already here)."""
        return self.submit(*inputs, deadline_ms=deadline_ms,
                           session=session).result(timeout)

    # -- disaggregated generation -------------------------------------------
    def _disagg_active(self):
        """Disaggregation engages only when the fleet holds BOTH
        phases: at least one routable prefill-role replica AND one
        decode-role replica. Any other fleet — legacy no-role, decode
        replicas alone, prefill replicas mid-deploy — keeps the
        colocated path bit-for-bit (the replica that admits also
        prefills)."""
        with self._lock:
            have = {None: False, "prefill": False, "decode": False}
            for r in self._replicas.values():
                if r.state == ReplicaState.DRAINING or \
                        r.stats.get("draining"):
                    continue
                have[r.role if r.role in have else None] = True
            return have["prefill"] and have["decode"]

    def _has_role(self, role):
        with self._lock:
            return any(r.role == role
                       and r.state != ReplicaState.DRAINING
                       and not r.stats.get("draining")
                       for r in self._replicas.values())

    def generate(self, prompt, max_new_tokens, eos_id=None,
                 temperature=0.0, top_k=None, top_p=None, seed=0,
                 session=None, timeout=None, handoff=None, tc=None,
                 on_token=None, speculative=False):
        """Route one sequence generation through the fleet
        (docs/serving.md §disaggregated prefill).

        Disaggregated fleet (prefill + decode roles both present): the
        prefill fans to the least-loaded prefill replica (preferring
        one with this prompt length warmed), the session places on the
        decode replica with most free slots — established pins keep
        their PR-14 affinity semantics untouched — and the exported KV
        blob ships WITH the admit, so the decode replica runs zero
        prefill graph calls. Any other fleet: the generate frame goes
        to one colocated replica that prefills and decodes locally —
        decode-role replicas when any exist (a ``role: batch``
        neighbor cannot answer a generate frame), otherwise any
        non-prefill replica (legacy no-role fleets, bit for bit).
        Both paths emit exactly what a single-process
        ``Generator.generate`` would for this prompt + seed.

        ``handoff``: a prefill reply the CALLER already holds (the
        replica-surface contract — a client that paid its own remote
        prefill must not pay a second one through the router); the
        prefill leg is skipped and the blob ships as-is.

        ``timeout`` is a best-effort end-to-end budget: the decode
        leg receives what remains of it after the prefill leg.
        Transport-fault replays can stretch the total past it (each
        replayed attempt re-arms its read window — the price of
        exactly-one-response delivery); callers needing a hard wall
        enforce it on their own side of the wire.

        ``on_token``: streaming mode — the decode leg asks its
        replica to stream and each NEW token relays through
        ``on_token(tok)`` the moment its frame arrives, without
        buffering the row. The recovery record extends to the
        DELIVERED-TOKEN PREFIX: every leg attempt — failover replay
        on a survivor, migration resume — re-reads its replica's
        stream from emission index 0, and the router verifies the
        replayed tokens against what it already delivered (a mismatch
        fails loudly as a determinism violation), forwarding only the
        tail. No duplicated or missing frames, across any number of
        mid-stream replica deaths. Streamed legs drop the blanket
        whole-completion deadline for the per-frame
        ``MXNET_STREAM_IDLE_TIMEOUT`` idle bound.

        ``speculative``: forwarded on every decode leg — first
        dispatch, failover replay AND migration resume — as the pure
        performance hint it is: a draft-carrying replica decodes the
        request in draft/verify rounds, a draft-less one ignores it,
        and the emitted tokens are byte-identical either way, so the
        delivered-prefix verification and the fault-free oracle both
        hold across mixed fleets."""
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        P = int(prompt.size)
        if P < 1:
            raise ValueError("empty prompt")
        t_entry = _telemetry.now_ms()
        if tc is None:
            tc = _trace.current_context()
        disagg = handoff is None and self._disagg_active()
        gsp = _trace.start_span("serve.router.generate", parent=tc,
                                tokens=P, disagg=disagg)
        try:
            if disagg:
                handoff = self._route(
                    P, None, None,
                    lambda c: c.prefill(prompt,
                                        temperature=temperature,
                                        top_k=top_k, top_p=top_p,
                                        seed=seed),
                    want=lambda r: r.role == "prefill",
                    span="serve.router.prefill")
                nbytes = kv_blob_nbytes(handoff["kv_blob"])
                self._c_prefills.inc()
                self._h_handoff.observe(nbytes)
                _telemetry.journal_event("serve.router.handoff",
                                         bytes=nbytes, tokens=P)
            if disagg or handoff is not None or \
                    self._has_role("decode"):
                # a blob (routed or caller-supplied) needs a decode
                # admit; and in ANY fleet that has decode-role
                # replicas, the generate frame belongs on them — a
                # 'batch' neighbor has no handle_generate()
                want = lambda r: r.role == "decode"  # noqa: E731
            else:
                want = None              # legacy no-role fleet
            # the decode leg must stay BOUNDED even when the caller
            # passed no timeout: an unbounded wire read on a hung
            # replica would wedge this dispatch thread forever (the
            # exact failure MXNET_ROUTER_IO_TIMEOUT exists to catch
            # on the infer path). Scale the ceiling with the work —
            # a second per requested token plus queue slack is hung
            # on any hardware, not slow. A caller budget is
            # END-TO-END: the decode leg gets what the prefill leg
            # left of it (floored so an already-blown budget fails
            # fast with the decoder's typed RequestTimeout)
            if timeout is not None:
                leg_timeout = max(
                    0.001, float(timeout)
                    - (_telemetry.now_ms() - t_entry) / 1000.0)
            elif on_token is not None:
                # streamed leg: liveness is per FRAME, not per
                # completion — the client applies the
                # MXNET_STREAM_IDLE_TIMEOUT idle bound to every frame
                # read, so the old scale-with-the-work ceiling has
                # nothing left to catch (a hung replica misses one
                # inter-frame gap and fails over)
                leg_timeout = None
            else:
                leg_timeout = 120.0 + float(max_new_tokens)
            # the recovery record: every attempt of this generate —
            # first dispatch, failover replay, migration resume —
            # re-sends the same request under ONE admit-id lineage,
            # so a replay onto a replica that already admitted it
            # rides the original admission (exactly-once)
            admit_id = "%s:%d" % (self._admit_cid,
                                  next(self._admit_seq))
            # the delivered-token prefix — the streaming half of the
            # recovery record: tokens already relayed to the caller.
            # Each leg attempt re-reads its replica's stream from
            # emission index 0 (a deduped or resumed admission
            # replays the emitted prefix first), so a leg-local
            # cursor IS the global emission index: verify against
            # the prefix, relay only the tail
            delivered = []

            def leg_relay():
                cur = [0]

                def relay(tok):
                    k = cur[0]
                    cur[0] += 1
                    if k < len(delivered):
                        if delivered[k] != tok:
                            raise ServeError(
                                "stream replay diverged at token %d: "
                                "%d then %d — determinism violation"
                                % (k, delivered[k], tok))
                        return
                    if k > len(delivered):
                        raise ServeError(
                            "stream relay skipped to token %d past "
                            "the delivered prefix (%d)"
                            % (k, len(delivered)))
                    delivered.append(tok)
                    if k == 0 and _trace.enabled():
                        _trace.instant("serve.router.stream_relay")
                    on_token(tok)
                return relay

            def leg(c, resume=None, aid=admit_id):
                return c.generate(prompt, max_new_tokens,
                                  eos_id=eos_id,
                                  temperature=temperature,
                                  top_k=top_k, top_p=top_p,
                                  seed=seed, session=session,
                                  handoff=None if resume is not None
                                  else handoff,
                                  timeout=leg_timeout,
                                  admit_id=aid, resume=resume,
                                  on_token=None if on_token is None
                                  else leg_relay(),
                                  speculative=speculative)
            out = self._route(P, session, None, leg, want=want,
                              span="serve.router.decode",
                              recoverable=True)
            hops = 0
            while isinstance(out, dict) and "evacuated" in out:
                # the replica exported this in-flight session instead
                # of finishing it (migrating recycle / SIGTERM
                # evacuation): resume the portable state on a
                # survivor. The session re-pins where the resume
                # lands; the resumed stream re-derives its PRNG key
                # by advancing the same splits, so the remaining
                # tokens are bit-identical to an unmigrated run.
                mstate = out["evacuated"]
                hops += 1
                if hops > self._migration_limit:
                    raise EngineClosed(
                        "generate migrated %d times without "
                        "completing (MXNET_ROUTER_MIGRATION_LIMIT="
                        "%d) — the fleet is evacuating faster than "
                        "it decodes" % (hops - 1,
                                        self._migration_limit))
                self._c_migrations.inc()
                _telemetry.journal_event(
                    "serve.router.migrate", hop=hops,
                    session=str(session),
                    tokens=len(mstate.get("emitted") or ()))
                out = self._route(
                    P, session, None,
                    lambda c, s=mstate, h=hops: leg(
                        c, resume=s,
                        # a fresh id per hop: a resume that bounces
                        # back to a re-opened replica must never
                        # collide with a STALE dedup entry from an
                        # earlier life of this request
                        aid="%s:m%d" % (admit_id, h)),
                    want=want, span="serve.router.migrate",
                    recoverable=True)
            self._c_generates.inc()
            if on_token is not None:
                self._c_streams.inc()
            return out
        finally:
            _trace.end_span(gsp)

    def handle_generate(self, payload):
        """The ``generate`` wire frame when a ServeServer fronts the
        router — clients still cannot tell a router from a replica:
        the same frame a colocated replica admits, the router fans
        across the fleet."""
        return self.generate(
            payload["prompt"], payload["max_new_tokens"],
            eos_id=payload.get("eos_id"),
            temperature=payload.get("temperature") or 0.0,
            top_k=payload.get("top_k"), top_p=payload.get("top_p"),
            seed=payload.get("seed") or 0,
            session=payload.get("session"),
            timeout=payload.get("timeout"),
            handoff=payload.get("handoff"),
            speculative=bool(payload.get("speculative")))

    def handle_generate_stream(self, payload, emit):
        """The streamed ``generate`` frame through a router-fronting
        ServeServer: relay each leg frame straight out as a front-end
        frame — the router never buffers the row (``emit`` fires on
        this dispatch thread the moment a replica frame lands, while
        the replica is still decoding). ``offset`` restarts at the
        delivered count, never replays: the router's own prefix
        verification already absorbed the leg-side replays."""
        sent = [0]

        def on_token(tok):
            emit([int(tok)], sent[0])
            sent[0] += 1

        return self.generate(
            payload["prompt"], payload["max_new_tokens"],
            eos_id=payload.get("eos_id"),
            temperature=payload.get("temperature") or 0.0,
            top_k=payload.get("top_k"), top_p=payload.get("top_p"),
            seed=payload.get("seed") or 0,
            session=payload.get("session"),
            timeout=payload.get("timeout"),
            handoff=payload.get("handoff"),
            speculative=bool(payload.get("speculative")),
            on_token=on_token)

    def _dispatch(self, arrays, deadline_ms, session, tc):
        if not arrays:
            raise ValueError("dispatch needs at least one input array")
        rows = int(arrays[0].shape[0]) if arrays[0].ndim else 0
        if rows < 1:
            raise ValueError(
                "inputs need a leading batch axis (a single sample is "
                "shape (1, ...)), got %r" % (arrays[0].shape,))
        return self._route(
            rows, session, tc,
            lambda client: client.request(arrays,
                                          deadline_ms=deadline_ms,
                                          session=session))

    def _route(self, rows, session, tc, call, want=None,
               span="serve.router.dispatch", recoverable=False):
        """THE dispatch scaffolding every routed wire op shares —
        pick-and-charge, shed-and-retry via the RetryPolicy reroute
        hook, suspect marking, session-pin hygiene. ``call(client)``
        performs the actual round trip (infer / prefill / generate);
        ``want`` restricts candidates to a role (disaggregated legs);
        ``span`` names the dispatch span (the infer path keeps its
        established ``serve.router.dispatch`` vocabulary).

        ``recoverable``: the generate-failover contract — ``call`` is
        a full recovery record (the router re-sends prompt, sampling
        opts, seed and handoff on every attempt, under one admit id).
        A transport fault on an ESTABLISHED session then probes the
        pinned replica: alive → replay to it (the decode-side dedup
        admits exactly once); dead → drop the pin and replay on a
        survivor, token-for-token identical. Without it (infer legs,
        or ``MXNET_ROUTER_FAILOVER`` off) an established session's
        fault retries only its own replica, the pre-failover
        behavior."""
        t0 = _telemetry.now_ms()
        excluded = set()                 # replicas that shed THIS req
        fresh_pins = set()               # pins THIS dispatch placed
        state = {"rep": None, "established": False, "reroutes": 0}

        def attempt():
            state["rep"] = None
            rep, established = self._pick(rows, session, excluded,
                                          fresh_pins, want)
            state["rep"], state["established"] = rep, established
            client = self._acquire(rep)
            answered = False
            try:
                try:
                    out = call(client)
                    answered = True
                    return out
                except ServeError:
                    # a typed reply IS an answer: the transport (and
                    # the replica) demonstrably work — keep both
                    answered = True
                    raise
            finally:
                self._finish_dispatch(rep)
                if answered:
                    self._release(rep, client)
                    if rep.state == ReplicaState.SUSPECT:
                        self._revive(rep)   # it answered: healthy
                else:
                    client.close()        # never pool a faulted client

        def on_retry(exc, attempt_n, delay):
            # fires before EVERY retry sleep — both transient
            # transport faults and on_fatal-approved reroutes land
            # here. Typed replies (shed/drain) already did their
            # bookkeeping in on_fatal; only a TRANSPORT fault (real or
            # injected) makes the replica suspect
            del attempt_n, delay
            if isinstance(exc, ServeError):
                return
            rep = state["rep"]
            if rep is not None:
                self._mark_suspect(rep, exc)
                if state["established"]:
                    if not (recoverable and self._failover):
                        # the session's KV state lives on that
                        # replica: the retry goes back to it (a blip
                        # heals, a dead replica exhausts the budget —
                        # rerouting would silently orphan the decode
                        # state instead)
                        return
                    if self._probe(rep):
                        # the replica survived — the fault was the
                        # wire's. Replay to the pin: the dedup table
                        # returns the original admission, so the
                        # replay admits exactly once
                        self._c_replays.inc()
                        _trace.instant("serve.router.replay",
                                       replica=rep.name)
                        return
                    # the pinned replica is DEAD mid-generate: drop
                    # the pin and replay the full recovery record on
                    # a survivor — prefill is pure and the request's
                    # PRNG stream splits once per emitted token, so
                    # the replayed completion is token-for-token
                    # identical to what the dead replica would have
                    # finished
                    with self._lock:
                        if self._sessions.get(session) == rep.name:
                            self._sessions.pop(session, None)
                    self._c_failovers.inc()
                    self._c_replays.inc()
                    _telemetry.journal_event("serve.router.failover",
                                             name=rep.name,
                                             session=str(session))
                    _trace.instant("serve.router.failover",
                                   replica=rep.name)
                    self._log.warning(
                        "router: replica %s dead mid-generate (probe "
                        "failed) — replaying session %r on a "
                        "survivor", rep.name, session)
                    if self._has_other_candidate(rep, excluded, want):
                        rep.rerouted_from += 1
                        state["reroutes"] += 1
                        self._c_rerouted.inc()
                    return
                if session is not None:
                    # a SPECULATIVE pin (this dispatch placed it, no
                    # KV state exists) must not chain the retry back
                    # to the faulted replica through the pinned-branch
                    # fast path — drop it so the retry re-places
                    with self._lock:
                        if self._sessions.get(session) == rep.name:
                            self._sessions.pop(session, None)
                if not self._has_other_candidate(rep, excluded, want):
                    # single-replica fleet (or nothing else standing):
                    # the retry necessarily returns HERE — that is a
                    # plain transport retry, not a reroute; counting
                    # it would fake fleet motion in the metrics
                    return
                rep.rerouted_from += 1
                state["reroutes"] += 1    # span attr and counter agree
                self._c_rerouted.inc()
                _trace.instant("serve.router.reroute",
                               replica=rep.name, fault=True)

        def on_fatal(exc):
            # the RetryPolicy reroute hook: a replica-local shed (or a
            # drain-window EngineClosed) retries on the next candidate
            # — but only a REPLICA's answer qualifies (state["rep"] is
            # None when _pick itself raised the every-replica-shed
            # Overloaded, which must propagate), and an ESTABLISHED
            # session never leaves the replica holding its KV slot on
            # a shed (a pin this dispatch placed speculatively is
            # free to move — no state exists yet)
            rep = state["rep"]
            if rep is None or not isinstance(exc, (Overloaded,
                                                   EngineClosed)):
                return False
            if state["established"] and isinstance(exc, Overloaded):
                return False
            if isinstance(exc, EngineClosed):
                # the replica is draining under us (external SIGTERM,
                # a recycle racing this dispatch): cache the observed
                # fact into the SAME channel the poller writes —
                # _candidates skips it from now on, and the next
                # successful poll clears it if the replica comes back
                # (a state flip to DRAINING would be forever: only
                # recycle() readmits from that state)
                with self._lock:
                    rep.stats["draining"] = True
                _telemetry.journal_event("serve.router.observed_drain",
                                         name=rep.name)
            rep.rerouted_from += 1
            excluded.add(rep.name)
            state["reroutes"] += 1
            self._c_rerouted.inc()
            _trace.instant("serve.router.reroute", replica=rep.name,
                           shed=True)
            return True

        # the default budget scales with the fleet: every live replica
        # must get its chance to shed before Overloaded reaches the
        # caller (a fixed budget smaller than the fleet would raise by
        # exhaustion mid-sweep, skipping the all_shed accounting)
        policy = self._user_retry or RetryPolicy(
            max_retries=max(8, len(self._replicas) + 2),
            base_delay=0.005, seed="router")
        sp = _trace.start_span(span, parent=tc, rows=rows)
        try:
            out = policy.run(attempt, describe="router.dispatch",
                             on_retry=on_retry, on_fatal=on_fatal)
            self._c_dispatched.inc()
            self._h_dispatch.observe(_telemetry.now_ms() - t0)
            return out
        except BaseException:
            # a pin THIS dispatch placed must die with the dispatch —
            # left behind, the session's next request would treat it
            # as an established pin (with no KV state behind it) and
            # refuse to reroute off the failed replica
            if session is not None and fresh_pins:
                with self._lock:
                    if self._sessions.get(session) in fresh_pins:
                        self._sessions.pop(session, None)
            raise
        finally:
            rep = state["rep"]
            _trace.end_span(sp, replica=rep.name if rep else None,
                            reroutes=state["reroutes"])

    # -- sessions -----------------------------------------------------------
    def release_session(self, session):
        """Forget a session pin (its decode slot freed on the
        replica); the next request with this id places fresh."""
        with self._lock:
            dropped = self._sessions.pop(session, None) is not None
        if dropped:
            self._update_gauges()
        return dropped

    def sessions(self):
        """{session id: replica name} snapshot of the affinity table."""
        with self._lock:
            return dict(self._sessions)

    # -- rolling restart ----------------------------------------------------
    def recycle(self, name, restart=None, warm=True, timeout=None,
                admit=True):
        """Zero-drop rolling restart of one replica.

        1. stop routing new work to it (state -> draining; dispatch
           excludes it from the same instant, under the same lock);
        2. for a decode-role replica, EVACUATE first: the ``evacuate``
           frame exports every active session mid-decode, the blocked
           generate dispatches resume them on survivors (bit-exact —
           docs/robustness.md), and the drain below is bounded by
           export+import cost instead of the longest sequence in
           flight. Then wait for the router's own in-flight count to
           reach zero (condition-signaled, exact) and for the
           replica's stats-observed engine ``in_flight``/
           ``queue_depth`` to reach zero (covers other frontends);
        3. run ``restart()`` — the operator hook that actually
           restarts the replica (SIGTERM → GracefulShutdown drain →
           fresh process, a k8s pod delete, or an in-process
           engine+server rebuild). It may return a new ``(host,
           port)`` / ``"host:port"`` (None = same address). With
           ``restart=None`` the replica is only drained, re-warmed
           and readmitted (a config-reload recycle);
        4. re-warm the declared buckets over the wire (``warm``
           frame) so the readmitted replica never pays a cold
           compile on a live request;
        5. readmit (state -> live) and refresh its stats — unless
           ``admit=False``, which leaves the restarted replica
           QUARANTINED (state stays draining, dispatch never routes
           to it) until :meth:`admit_replica`. That is the rollout
           gate's seam: the fleet controller recycles a replica onto
           a candidate artifact, canaries it directly while zero
           live traffic can reach it, and only admits on a passed
           gate.

        Raises ValueError when no OTHER live replica exists (a
        one-replica fleet cannot recycle without dropping requests)
        and TimeoutError when the drain outlives the budget
        (``MXNET_ROUTER_DRAIN_TIMEOUT`` / ``timeout``; a replica
        whose hello declared role ``decode`` drains on
        ``MXNET_DECODE_DRAIN_TIMEOUT`` instead — the same clock its
        own ``ContinuousDecoder.close`` honors, validated loudly
        there, so a decode drain is never cut short by a router knob
        tuned for batch replicas). A drain timeout fails OPEN, never
        stranding the replica in DRAINING: decode-role replicas park
        SUSPECT (wedged sequences make the replica suspect by
        definition; the next successful poll revives it), other
        roles return LIVE."""
        with self._lock:
            # ONE lock section from lookup to the DRAINING flip — a
            # concurrent remove_replica must not slip between them and
            # leave this recycle operating on an orphaned record
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError("no replica %r" % name)
            if timeout is not None:
                budget = float(timeout)
            elif rep.role == "decode":
                budget = _decode_drain_timeout()
            else:
                budget = self._drain_timeout
            deadline = time.monotonic() + budget
            if not any(r.state == ReplicaState.LIVE
                       and r.name != name
                       for r in self._replicas.values()):
                raise ValueError(
                    "recycling %r would leave no live replica — add "
                    "capacity first (or close the router outright)"
                    % name)
            rep.state = ReplicaState.DRAINING
            for sid in [s for s, n in self._sessions.items()
                        if n == name]:
                self._sessions.pop(sid, None)   # pins re-place fresh
            idle = list(rep.idle)
            rep.idle.clear()
        for cl in idle:
            cl.close()
        self._update_gauges()
        t0 = _telemetry.now_ms()
        drained_ms = self._drain_replica(rep, deadline, budget,
                                         event="serve.router.recycle")
        try:
            if restart is not None:
                rep.control.close()
                addr = restart()
                if addr is not None:
                    rep.host, rep.port = _parse_addr(addr)
                rep.control = self._make_client(rep, control=True)
                # the bind window of a REAL process restart (fresh
                # interpreter, XLA import, bind) is seconds, far past
                # the control client's own ~30 ms retry budget — keep
                # knocking until the recycle's remaining drain budget
                # runs out
                while True:
                    try:
                        rep.declared = rep.control.hello()
                        rep.role = (rep.declared or {}).get("role")
                        rep.model_id = (rep.declared or {}) \
                            .get("model_id")
                        break
                    except ServeError:
                        raise             # it answered: misconfigured
                    except Exception:     # noqa: BLE001 — transport;
                        if time.monotonic() >= deadline:
                            raise         # outer fail-open -> SUSPECT
                        time.sleep(0.05)
            if warm:
                self._warm_replica(rep)
        except Exception as exc:          # noqa: BLE001 — fail OPEN:
            # a botched restart/hello must not strand the replica in
            # DRAINING (a permanently shrunk fleet); park it SUSPECT
            # so the poller readmits it the moment it answers stats
            with self._lock:
                rep.state = ReplicaState.SUSPECT
            self._update_gauges()
            _telemetry.journal_event("serve.router.recycle",
                                     name=name, phase="failed",
                                     error=type(exc).__name__)
            raise
        self._poll_replica(rep)
        with self._lock:
            if admit:
                rep.state = ReplicaState.LIVE
                # the observed-draining flag must not outlive the
                # recycle: if the final poll blipped, a stale True
                # here would keep dispatch skipping a replica the
                # gauge counts as live (and a poll_now-driven
                # deployment would never clear it)
                rep.stats.pop("draining", None)
            rep.recycles += 1
        self._c_recycles.inc()
        self._update_gauges()
        _telemetry.journal_event(
            "serve.router.recycle", name=name,
            phase="readmit" if admit else "quarantined",
            drained_ms=round(drained_ms, 3),
            total_ms=round(_telemetry.now_ms() - t0, 3))

    def admit_replica(self, name):
        """Admit a quarantined replica (``recycle(admit=False)``) to
        traffic: state -> live, routable from this instant. Idempotent
        on an already-live replica; KeyError on an unknown one."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError("no replica %r" % name)
            rep.state = ReplicaState.LIVE
            rep.stats.pop("draining", None)
        self._update_gauges()
        _telemetry.journal_event("serve.router.admit", name=name)

    def retire_replica(self, name, timeout=None):
        """Zero-drop scale-in: stop routing to the replica, drain it
        exactly like :meth:`recycle` (decode-role replicas evacuate
        their active sessions onto survivors first), then REMOVE it
        from the fleet. The replica process itself is not stopped —
        its lifecycle belongs to whoever started it (the fleet
        controller's ``retire`` hook reaps it after this returns).
        Refuses to retire the last live replica; a drain past the
        budget raises TimeoutError with the replica failed OPEN
        (routable again — nothing dropped, nothing removed)."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError("no replica %r" % name)
            if timeout is not None:
                budget = float(timeout)
            elif rep.role == "decode":
                budget = _decode_drain_timeout()
            else:
                budget = self._drain_timeout
            deadline = time.monotonic() + budget
            if not any(r.state == ReplicaState.LIVE
                       and r.name != name
                       for r in self._replicas.values()):
                raise ValueError(
                    "retiring %r would leave no live replica — the "
                    "fleet floor is one" % name)
            rep.state = ReplicaState.DRAINING
            for sid in [s for s, n in self._sessions.items()
                        if n == name]:
                self._sessions.pop(sid, None)   # pins re-place fresh
            idle = list(rep.idle)
            rep.idle.clear()
        for cl in idle:
            cl.close()
        self._update_gauges()
        drained_ms = self._drain_replica(rep, deadline, budget,
                                         event="serve.router.retire")
        self.remove_replica(name)
        _telemetry.journal_event("serve.router.retire", name=name,
                                 phase="removed",
                                 drained_ms=round(drained_ms, 3))

    def _drain_replica(self, rep, deadline, budget, event):
        """THE zero-drop drain body recycle() and retire_replica()
        share: evacuate a decode replica's active sessions, wait for
        the router's own in-flight count (condition-signaled, exact),
        then for the replica's stats-observed engine in-flight/queue
        depth (covers other frontends). The replica must already be
        DRAINING. A budget overrun raises TimeoutError with the
        replica failed OPEN (SUSPECT for decode roles — wedged
        sequences make it suspect by definition; LIVE otherwise) so
        it is never stranded unroutable. Returns the drain wall time
        in ms."""
        name = rep.name
        t0 = _telemetry.now_ms()
        _telemetry.journal_event(event, name=name, phase="drain")
        if rep.role == "decode":
            # migrating recycle: evacuate active sessions FIRST —
            # each in-flight generate on this replica answers with
            # its portable state and resumes on a survivor (the
            # dispatch threads repin it there), so the drain below
            # is bounded by export+import cost instead of the
            # longest sequence in flight. A replica that declines
            # (no evacuate(): an old build) falls back to the full
            # drain; an unreachable one is already dead — the drain
            # loop below classifies that as drained.
            try:
                evacuated = rep.control.evacuate()
                self._c_evacuations.inc()
                _telemetry.journal_event(
                    event, name=name,
                    phase="evacuate", sessions=int(evacuated or 0))
            except ServeError as exc:
                self._log.warning(
                    "router: %s declined evacuation (%s) — falling "
                    "back to a full decode drain", name, exc)
            except Exception as exc:      # noqa: BLE001 — transport:
                self._log.warning(
                    "router: evacuate frame to %s failed (%s) — "
                    "continuing with the drain", name, exc)
        # a decode replica that cannot drain is suspect by definition
        # (sequences wedged past their own drain clock); any other
        # role fails open LIVE — its requests are short, the timeout
        # usually means budget misconfiguration, and SUSPECT would
        # deprioritize a working replica. Either way the replica is
        # never stranded DRAINING: the next successful poll revives
        # a suspect, and LIVE routes immediately.
        fail_open = ReplicaState.SUSPECT if rep.role == "decode" \
            else ReplicaState.LIVE
        timed_out = 0
        with self._cond:
            while rep.inflight > 0:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    # re-checked AFTER every wait: a wait that times
                    # out concurrently with the last completion must
                    # re-read the predicate, not fail a finished drain
                    rep.state = fail_open
                    timed_out = rep.inflight
                    break
                self._cond.wait(remain)
        if timed_out:
            self._update_gauges()         # the fail-open is routable
            raise TimeoutError(
                "replica %r still has %d router-dispatched "
                "request(s) in flight after %.1fs drain budget"
                % (name, timed_out, budget))
        # router-sent work is answered; now confirm the replica-side
        # engine is empty too (work from OTHER frontends counts)
        while True:
            try:
                st = self._extract(rep.control.stats())
            except Exception as exc:      # noqa: BLE001 — a replica
                # mid-external-restart stops answering; that IS drained
                self._log.info("router: %s stopped answering during "
                               "drain (%s) — treating as drained",
                               name, exc)
                break
            if st["in_flight"] == 0 and st["queue_depth"] == 0:
                break
            if time.monotonic() >= deadline:
                with self._lock:
                    rep.state = fail_open
                self._update_gauges()
                raise TimeoutError(
                    "replica %r engine still reports %d in flight / "
                    "%d queued after %.1fs drain budget"
                    % (name, st["in_flight"], st["queue_depth"],
                       budget))
            with self._cond:
                self._cond.wait(0.01)     # remote state: bounded poll
        return _telemetry.now_ms() - t0

    # -- engine-surface lifecycle / introspection ---------------------------
    def _warm_replica(self, rep):
        """One warm frame + bookkeeping — THE warm path for both
        warmup() and recycle(). A typed ServeError decline (engine
        without warmup()/feature shapes) is logged, not raised: the
        replica works, it just pays its compiles on live traffic.
        Transport errors propagate to the caller's policy."""
        try:
            warmed = rep.control.warm()
            with self._lock:
                rep.stats["warmed"] = list(warmed or [])
        except ServeError as exc:
            self._log.warning("router: warm of %s declined: %s",
                              rep.name, exc)

    def warmup(self):
        """Engine-surface warmup: re-warm every non-draining replica
        (the ``warm`` frame on each)."""
        with self._lock:
            reps = [r for r in self._replicas.values()
                    if r.state != ReplicaState.DRAINING]
        for rep in reps:
            try:
                self._warm_replica(rep)
            except Exception as exc:      # noqa: BLE001 — a TRANSPORT
                # failure during warmup is a health signal
                self._mark_suspect(rep, exc)

    @property
    def warmed_buckets(self):
        """Buckets warmed on EVERY non-draining replica (the fleet
        serves a bucket cold-compile-free only when all of them can)."""
        with self._lock:
            pools = [set(r.stats.get("warmed") or ())
                     for r in self._replicas.values()
                     if r.state != ReplicaState.DRAINING]
        return sorted(set.intersection(*pools)) if pools else []

    @property
    def draining(self):
        return self._closed

    def stats(self):
        """Aggregated engine-style stats (sums over the fleet) +
        router accounting."""
        with self._lock:
            reps = list(self._replicas.values())
            sessions = len(self._sessions)
        return {
            "replicas": len(reps),
            "live": sum(r.state == ReplicaState.LIVE for r in reps),
            "dispatched": sum(r.dispatched for r in reps),
            "in_flight": sum(r.inflight for r in reps),
            "queue_depth": sum(r.stats.get("queue_depth", 0)
                               for r in reps),
            "rerouted": sum(r.rerouted_from for r in reps),
            "recycles": sum(r.recycles for r in reps),
            "sessions": sessions,
            # fleet-wide windowed rates (per poll window, summed over
            # replicas) — the controller's scale signals, next to the
            # cumulative counters above
            "shed_rate": sum(r.stats.get("shed_rate", 0)
                             for r in reps),
            "req_rate": sum(r.stats.get("req_rate", 0) for r in reps),
        }

    def introspect(self):
        """The ``stats`` frame's engine half when a ServeServer fronts
        the router: fleet aggregate + per-replica detail — one query
        answers for the whole fleet."""
        out = self.stats()
        out["role"] = self.role
        out["draining"] = self.draining
        with self._lock:
            out["per_replica"] = {n: r.describe()
                                  for n, r in self._replicas.items()}
        return out

    def close(self):
        """Stop the poller and close every client. Replicas are NOT
        told anything — their lifecycle belongs to whoever started
        them (drain them via recycle()/their own SIGTERM path)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(5.0)
        with self._lock:
            reps = list(self._replicas.values())
            clients = []
            for rep in reps:
                clients.extend(rep.idle)
                rep.idle.clear()
                if rep.control is not None:
                    clients.append(rep.control)
        for cl in clients:
            cl.close()
        _telemetry.journal_event("serve.router.stop")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
