"""Dedicated prefill engine — the compute half of prefill/decode
disaggregation (docs/serving.md §disaggregated prefill).

In a colocated replica every long prompt stalls the
``ContinuousDecoder`` step loop: the (B, P) prefill graph call runs on
the same device stream as the (B, 1) decode step, so every active
slot's inter-token latency inflates by the whole prefill while it
runs, and decode HBM headroom has to cover prefill activation peaks.
Splitting the phases is the paper's own identity applied to inference
— state moves between machines (the exported KV rows over the wire,
PAPER.md's push/pull), compute stays local (the prefill graph on
prefill chips, the decode step on decode chips) — grounded by the
portable O(1) decode state of arXiv 2603.09555 and halved in bytes by
the int8 KV cache (PR 13).

:class:`PrefillEngine` is the engine a prefill replica's
``ServeServer`` fronts: it answers the ``prefill`` wire frame with
``{"first_token", "kv_blob", "pos"}`` — one shared-position prefill
forward, the first sampled/greedy token (consuming exactly the first
split of the request's PRNG stream, so the decode side continues the
``generate()`` key discipline bit-for-bit), and the sequence's cache
rows exported via :meth:`Generator.export_kv_rows`. Prefill is PURE:
the same prompt + seed always lands the same reply, so a transport
fault mid-handoff simply replays (no dedup table, exactly like the
infer path's contract in serve/net.py). The same purity is one leg
of the fleet's replica-death failover: when a decode replica dies
mid-generate, the router replays the whole request — a re-run
prefill (local or remote) recomputes the identical first token and
blob, so the replayed completion is token-for-token what the dead
replica would have emitted (docs/robustness.md, fleet failure
semantics).

No sockets here — transport is serve/net.py's job (lint-enforced).
"""
from __future__ import annotations

import logging
import threading

import numpy as np

from .. import telemetry as _telemetry
from .. import trace as _trace
from ..generation import kv_blob_nbytes

__all__ = ["PrefillEngine"]


class PrefillEngine:
    """One Generator serving the ``prefill`` frame.

    The generator's ``batch_size`` is a compute detail here (the
    prompt is replicated across rows and row 0 exported); size it 1
    on a dedicated prefill chip unless you batch prefills some other
    way. ``max_len`` bounds the prompt length this replica accepts —
    the DECODE side's capacity bounds prompt + max_new_tokens.

    ``warm_lengths``: prompt lengths ``warmup()`` pre-compiles (the
    prefill graph specializes per (B, P) like any bucket; the fleet
    router's ``warm`` frame lands here on recycle). Empty = warmup is
    a no-op."""

    role = "prefill"                      # the hello frame's identity

    def __init__(self, generator, warm_lengths=(), logger=None):
        if getattr(generator, "_rolling", False):
            raise ValueError(
                "prefill disaggregation does not support rolling "
                "caches (export_kv_rows needs position-aligned rows)")
        self._gen = generator
        self._log = logger or logging.getLogger(__name__)
        self._warm_lengths = tuple(int(p) for p in warm_lengths)
        # exactly prefill()'s own prompt bounds — a length the
        # constructor accepts must never make warmup() raise later
        # (a recycle re-warm that always fails would park the freshly
        # restarted replica SUSPECT every time)
        cap = generator.max_len
        if generator._pos_rows is not None:
            cap = min(cap, generator._pos_rows)
        if any(p < 1 or p >= cap for p in self._warm_lengths):
            raise ValueError(
                "warm_lengths %r out of range 1..%d (max_len and the "
                "trained position table both need decode headroom "
                "past the prompt)" % (self._warm_lengths, cap - 1))
        self._lock = threading.Lock()
        self._inflight = 0
        self._prefills = 0
        self._warmed = []
        self._c_requests = _telemetry.counter("serve.prefill.requests")
        self._c_tokens = _telemetry.counter("serve.prefill.tokens")
        self._h_ms = _telemetry.histogram("serve.prefill.ms")
        self._h_export = _telemetry.histogram("serve.prefill.export_ms")
        # byte-scale buckets (the ms/count defaults top out far below
        # a cache blob): 1 KiB .. 64 MiB in x4 steps
        self._h_bytes = _telemetry.histogram(
            "serve.prefill.blob_bytes",
            buckets=tuple(float(1 << s) for s in range(10, 27, 2)))

    def prefill(self, prompt, temperature=0.0, top_k=None, top_p=None,
                seed=0, _record=True, **_ignored):
        """One sequence's prefill: returns the handoff dict
        ``{"first_token": int, "kv_blob": export_kv_rows blob,
        "pos": len(prompt)}`` a remote
        ``ContinuousDecoder.submit(handoff=...)`` admits from.
        Pure — replaying the same call lands the same reply.
        ``_record=False`` (warmup's compile drives) keeps the
        request-level telemetry/stats clean: ``serve.prefill.*`` and
        ``stats()['prefills']`` count served traffic only."""
        import jax

        from ..generation import _pick_token
        gen = self._gen
        gen._check_sampling(temperature, top_k, top_p)
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        P = int(prompt.shape[0])
        if P < 1:
            raise ValueError("empty prompt")
        if P >= gen.max_len:
            raise ValueError(
                "prompt (%d) leaves no decode headroom at this "
                "prefill replica's max_len=%d" % (P, gen.max_len))
        if gen._pos_rows is not None and P >= gen._pos_rows:
            raise ValueError(
                "prompt (%d) exceeds the trained position table (%d "
                "rows)" % (P, gen._pos_rows))
        t0 = _telemetry.now_ms()
        sp = _trace.start_span("serve.prefill", tokens=P)
        try:
            with self._lock:
                self._inflight += 1
            rows = np.stack([prompt] * gen.batch_size)
            logits, aux = gen._forward(gen._fresh_aux(),
                                       rows.astype(np.float32), 0)
            # the request PRNG stream's FIRST split picks the first
            # token — exactly generate()'s round-1 discipline; the
            # decode side advances its own key past this split
            _, sub = jax.random.split(jax.random.PRNGKey(seed))
            tok = int(np.asarray(_pick_token(
                logits[:1, -1], temperature, top_k, sub, top_p))[0])
            t_exp = _telemetry.now_ms()
            blob = gen.export_kv_rows(aux, 0, P)
            t1 = _telemetry.now_ms()
            if _record:
                nbytes = kv_blob_nbytes(blob)
                with self._lock:
                    self._prefills += 1
                self._c_requests.inc()
                self._c_tokens.inc(P)
                self._h_ms.observe(t1 - t0)
                self._h_export.observe(t1 - t_exp)
                self._h_bytes.observe(nbytes)
                _telemetry.journal_event(
                    "serve.prefill", tokens=P, blob_bytes=nbytes,
                    ms=round(t1 - t0, 3))
            return {"first_token": tok, "kv_blob": blob, "pos": P}
        finally:
            with self._lock:
                self._inflight -= 1
            _trace.end_span(sp)

    # -- engine-surface lifecycle / introspection ---------------------------
    def warmup(self):
        """Pre-compile the declared prompt-length specializations so a
        recycled prefill replica never pays a cold XLA compile on a
        live prompt (the fleet router's ``warm`` frame)."""
        for P in self._warm_lengths:
            # compile drive only: request-level telemetry stays clean
            # (warmups must never read as served traffic)
            self.prefill(np.zeros((P,), np.int64), _record=False)
            if P not in self._warmed:
                self._warmed.append(P)
        _telemetry.journal_event("serve.prefill.warmup",
                                 lengths=list(self._warm_lengths))

    @property
    def warmed_buckets(self):
        """Prompt lengths warmup() pre-compiled (the warm frame's
        reply; a prefill 'bucket' is a prompt length)."""
        return list(self._warmed)

    @property
    def draining(self):
        return False

    def stats(self):
        with self._lock:
            return {"prefills": self._prefills,
                    "in_flight": self._inflight}

    def introspect(self):
        """The ``stats`` frame's engine half: in-flight prefills are
        the load signal (there is no queue — concurrency is the
        connection count, each prefill synchronous on its handler
        thread)."""
        out = self.stats()
        out["queue_depth"] = 0
        out["draining"] = self.draining
        out["warmed"] = self.warmed_buckets
        return out

    def close(self, timeout=None):
        """Nothing to drain: in-flight prefills finish on their
        handler threads; the engine holds no background thread
        (``timeout`` accepted for engine-surface parity)."""
        del timeout

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
