"""Dedicated prefill engine — the compute half of prefill/decode
disaggregation (docs/serving.md §disaggregated prefill).

In a colocated replica every long prompt stalls the
``ContinuousDecoder`` step loop: the (B, P) prefill graph call runs on
the same device stream as the (B, 1) decode step, so every active
slot's inter-token latency inflates by the whole prefill while it
runs, and decode HBM headroom has to cover prefill activation peaks.
Splitting the phases is the paper's own identity applied to inference
— state moves between machines (the exported KV rows over the wire,
PAPER.md's push/pull), compute stays local (the prefill graph on
prefill chips, the decode step on decode chips) — grounded by the
portable O(1) decode state of arXiv 2603.09555 and halved in bytes by
the int8 KV cache (PR 13).

:class:`PrefillEngine` is the engine a prefill replica's
``ServeServer`` fronts: it answers the ``prefill`` wire frame with
``{"first_token", "kv_blob", "pos"}`` — one shared-position prefill
forward, the first sampled/greedy token (consuming exactly the first
split of the request's PRNG stream, so the decode side continues the
``generate()`` key discipline bit-for-bit), and the sequence's cache
rows exported via :meth:`Generator.export_kv_rows`. Prefill is PURE:
the same prompt + seed always lands the same reply, so a transport
fault mid-handoff simply replays (no dedup table, exactly like the
infer path's contract in serve/net.py). The same purity is one leg
of the fleet's replica-death failover: when a decode replica dies
mid-generate, the router replays the whole request — a re-run
prefill (local or remote) recomputes the identical first token and
blob, so the replayed completion is token-for-token what the dead
replica would have emitted (docs/robustness.md, fleet failure
semantics).

No sockets here — transport is serve/net.py's job (lint-enforced).
"""
from __future__ import annotations

import logging
import threading

import numpy as np

from .. import config as _config
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..generation import kv_blob_nbytes
from .engine import EngineClosed

__all__ = ["PrefillEngine"]


class _PendingPrefill:
    """One queued prefill awaiting the coalescing batcher."""

    __slots__ = ("prompt", "temperature", "top_k", "top_p", "seed",
                 "ev", "out", "exc")

    def __init__(self, prompt, temperature, top_k, top_p, seed):
        self.prompt = prompt
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = seed
        self.ev = threading.Event()
        self.out = None                    # (first_token, blob)
        self.exc = None


class PrefillEngine:
    """One Generator serving the ``prefill`` frame.

    The generator's ``batch_size`` is a compute detail here (the
    prompt is replicated across rows and row 0 exported); size it 1
    on a dedicated prefill chip unless you batch prefills some other
    way. ``max_len`` bounds the prompt length this replica accepts —
    the DECODE side's capacity bounds prompt + max_new_tokens.

    ``warm_lengths``: prompt lengths ``warmup()`` pre-compiles (the
    prefill graph specializes per (B, P) like any bucket; the fleet
    router's ``warm`` frame lands here on recycle). Empty = warmup is
    a no-op.

    Batched prefill (PR 17): with ``batch_size > 1``, concurrent
    prefills coalesce — a batcher thread holds the oldest queued
    prompt for the ``MXNET_SERVE_MAX_WAIT_MS`` window (the serve
    batcher's own knob: one coalescing clock for the whole stack),
    right-pads the group to its longest prompt and runs ONE shared-
    position (B, P_max) forward, exporting each row at its own true
    length. Causal masking makes the padding inert: a row's kept
    positions attend only its own prefix, and the masked tail
    contributes exact zeros to every reduction — each coalesced reply
    is bitwise the solo reply (pinned in
    tests/test_serve_streaming.py). A window of 0 or a 1-row pool
    restores the direct per-request path."""

    role = "prefill"                      # the hello frame's identity

    def __init__(self, generator, warm_lengths=(), logger=None):
        if getattr(generator, "_rolling", False):
            raise ValueError(
                "prefill disaggregation does not support rolling "
                "caches (export_kv_rows needs position-aligned rows)")
        self._gen = generator
        self._log = logger or logging.getLogger(__name__)
        self._warm_lengths = tuple(int(p) for p in warm_lengths)
        # exactly prefill()'s own prompt bounds — a length the
        # constructor accepts must never make warmup() raise later
        # (a recycle re-warm that always fails would park the freshly
        # restarted replica SUSPECT every time)
        cap = generator.max_len
        if generator._pos_rows is not None:
            cap = min(cap, generator._pos_rows)
        if any(p < 1 or p >= cap for p in self._warm_lengths):
            raise ValueError(
                "warm_lengths %r out of range 1..%d (max_len and the "
                "trained position table both need decode headroom "
                "past the prompt)" % (self._warm_lengths, cap - 1))
        self._lock = threading.Lock()
        self._inflight = 0
        self._prefills = 0
        self._warmed = []
        self._c_requests = _telemetry.counter("serve.prefill.requests")
        self._c_tokens = _telemetry.counter("serve.prefill.tokens")
        self._h_ms = _telemetry.histogram("serve.prefill.ms")
        self._h_export = _telemetry.histogram("serve.prefill.export_ms")
        # byte-scale buckets (the ms/count defaults top out far below
        # a cache blob): 1 KiB .. 64 MiB in x4 steps
        self._h_bytes = _telemetry.histogram(
            "serve.prefill.blob_bytes",
            buckets=tuple(float(1 << s) for s in range(10, 27, 2)))
        self._c_batched = _telemetry.counter("serve.prefill.batched")
        self._h_fill = _telemetry.histogram(
            "serve.prefill.batch_fill",
            buckets=_telemetry.COUNT_BUCKETS)
        # the coalescing batcher: only worth a thread when the pool
        # can actually hold more than one row and the window allows
        # coalescing at all
        self._wait_ms = float(
            _config.get("MXNET_SERVE_MAX_WAIT_MS") or 0.0)
        self._pending = []
        self._pcond = threading.Condition()
        self._closed = False
        self._batcher = None
        if generator.batch_size > 1 and self._wait_ms > 0:
            self._batcher = threading.Thread(
                target=self._batch_loop, name="mxnet-serve-prefill",
                daemon=True)
            self._batcher.start()

    def prefill(self, prompt, temperature=0.0, top_k=None, top_p=None,
                seed=0, _record=True, speculative=False, **_ignored):
        """One sequence's prefill: returns the handoff dict
        ``{"first_token": int, "kv_blob": export_kv_rows blob,
        "pos": len(prompt)}`` a remote
        ``ContinuousDecoder.submit(handoff=...)`` admits from.
        Pure — replaying the same call lands the same reply.
        ``_record=False`` (warmup's compile drives) keeps the
        request-level telemetry/stats clean: ``serve.prefill.*`` and
        ``stats()['prefills']`` count served traffic only — and skips
        the coalescing batcher (a warmup must compile the exact
        declared length, not a group's padded one).

        ``speculative`` is accepted and deliberately IGNORED: prefill
        replicas are draft-agnostic. The handoff blob carries TARGET
        cache rows only — a speculative decode admission prefills its
        DRAFT cache locally from the prompt ids it already holds,
        riding the chunked-prefill widths (decode.py
        ``_draft_prefill_rows``), so drafts never change the wire
        format, the blob bytes, or this replica's compiled shapes."""
        gen = self._gen
        gen._check_sampling(temperature, top_k, top_p)
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        P = int(prompt.shape[0])
        if P < 1:
            raise ValueError("empty prompt")
        if P >= gen.max_len:
            raise ValueError(
                "prompt (%d) leaves no decode headroom at this "
                "prefill replica's max_len=%d" % (P, gen.max_len))
        if gen._pos_rows is not None and P >= gen._pos_rows:
            raise ValueError(
                "prompt (%d) exceeds the trained position table (%d "
                "rows)" % (P, gen._pos_rows))
        t0 = _telemetry.now_ms()
        sp = _trace.start_span("serve.prefill", tokens=P)
        req = _PendingPrefill(prompt, float(temperature or 0.0),
                              top_k, top_p, int(seed or 0))
        try:
            with self._lock:
                self._inflight += 1
            if self._batcher is not None and _record:
                with self._pcond:
                    if self._closed:
                        raise EngineClosed("prefill engine closed")
                    self._pending.append(req)
                    self._pcond.notify_all()
                req.ev.wait()
            else:
                self._run_group([req])
            if req.exc is not None:
                raise req.exc
            tok, blob, export_ms = req.out
            t1 = _telemetry.now_ms()
            if _record:
                nbytes = kv_blob_nbytes(blob)
                with self._lock:
                    self._prefills += 1
                self._c_requests.inc()
                self._c_tokens.inc(P)
                self._h_ms.observe(t1 - t0)
                self._h_export.observe(export_ms)
                self._h_bytes.observe(nbytes)
                _telemetry.journal_event(
                    "serve.prefill", tokens=P, blob_bytes=nbytes,
                    ms=round(t1 - t0, 3))
            return {"first_token": tok, "kv_blob": blob, "pos": P}
        finally:
            with self._lock:
                self._inflight -= 1
            _trace.end_span(sp)

    def _run_group(self, group):
        """One shared-position forward for a coalesced group: prompts
        right-pad to the group's longest, spare pool rows replicate
        row 0, and each request's first token and cache rows come off
        ITS row at ITS true length — causal masking keeps every kept
        position's math identical to a solo run (the padded tail is
        never attended by a real position, and masked terms are exact
        zeros in the reductions), so coalescing is invisible in the
        bits. Solo callers (warmup, 1-row pools, window 0) pass a
        1-element group and run on their own thread.

        SSM generators coalesce only length-homogeneous groups: the
        recurrent state has no positional mask — a padded tail's
        tokens would be ABSORBED into the exported state blob — so a
        mixed-length group splits into per-length subgroups, each its
        own shared forward (same replies, one extra graph call per
        extra distinct length)."""
        import jax

        from ..generation import _pick_token
        gen = self._gen
        if getattr(gen, "_has_ssm", False):
            by_len = {}
            for g in group:
                by_len.setdefault(int(g.prompt.shape[0]),
                                  []).append(g)
            if len(by_len) > 1:
                for sub in by_len.values():
                    self._run_group(sub)
                return
        pmax = max(int(g.prompt.shape[0]) for g in group)
        rows = np.zeros((gen.batch_size, pmax), np.int64)
        for i, g in enumerate(group):
            rows[i, :g.prompt.shape[0]] = g.prompt
        for i in range(len(group), gen.batch_size):
            rows[i] = rows[0]
        try:
            logits, aux = gen._forward(gen._fresh_aux(),
                                       rows.astype(np.float32), 0)
        except Exception as exc:          # noqa: BLE001 — each waiter
            # owns its own failure; the batcher thread must survive
            for g in group:
                g.exc = exc
                g.ev.set()
            return
        if len(group) > 1:
            self._c_batched.inc()
        self._h_fill.observe(len(group))
        for i, g in enumerate(group):
            try:
                P = int(g.prompt.shape[0])
                # the request PRNG stream's FIRST split picks the
                # first token — exactly generate()'s round-1
                # discipline; the decode side advances its own key
                # past this split
                _, sub = jax.random.split(jax.random.PRNGKey(g.seed))
                tok = int(np.asarray(_pick_token(
                    logits[i:i + 1, P - 1], g.temperature, g.top_k,
                    sub, g.top_p))[0])
                t_exp = _telemetry.now_ms()
                blob = gen.export_kv_rows(aux, i, P)
                g.out = (tok, blob,
                         _telemetry.now_ms() - t_exp)
            except Exception as exc:      # noqa: BLE001 — per-row
                g.exc = exc
            g.ev.set()

    def _batch_loop(self):
        """The coalescing batcher (one per engine, like the serve
        batcher): hold the oldest queued prefill for the
        MXNET_SERVE_MAX_WAIT_MS window or until the pool is full,
        then run the group as one padded forward."""
        B = self._gen.batch_size
        while True:
            with self._pcond:
                while not self._pending and not self._closed:
                    self._pcond.wait(0.05)
                if self._closed and not self._pending:
                    return
                t0 = _telemetry.now_ms()
                while len(self._pending) < B and not self._closed:
                    left = self._wait_ms - (_telemetry.now_ms() - t0)
                    if left <= 0:
                        break
                    self._pcond.wait(left / 1000.0)
                group = self._pending[:B]
                del self._pending[:B]
            if group:
                self._run_group(group)

    # -- engine-surface lifecycle / introspection ---------------------------
    def warmup(self):
        """Pre-compile the declared prompt-length specializations so a
        recycled prefill replica never pays a cold XLA compile on a
        live prompt (the fleet router's ``warm`` frame)."""
        for P in self._warm_lengths:
            # compile drive only: request-level telemetry stays clean
            # (warmups must never read as served traffic)
            self.prefill(np.zeros((P,), np.int64), _record=False)
            if P not in self._warmed:
                self._warmed.append(P)
        _telemetry.journal_event("serve.prefill.warmup",
                                 lengths=list(self._warm_lengths))

    @property
    def warmed_buckets(self):
        """Prompt lengths warmup() pre-compiled (the warm frame's
        reply; a prefill 'bucket' is a prompt length)."""
        return list(self._warmed)

    @property
    def draining(self):
        return False

    def stats(self):
        with self._lock:
            return {"prefills": self._prefills,
                    "in_flight": self._inflight}

    def introspect(self):
        """The ``stats`` frame's engine half: in-flight prefills are
        the load signal (there is no queue — concurrency is the
        connection count, each prefill synchronous on its handler
        thread)."""
        out = self.stats()
        out["queue_depth"] = 0
        out["draining"] = self.draining
        out["warmed"] = self.warmed_buckets
        return out

    def close(self, timeout=None):
        """In-flight prefills finish on their handler threads; the
        coalescing batcher (when running) drains its queue and
        exits — anything still queued after the join fails with
        ``EngineClosed`` rather than hanging its waiter."""
        batcher = self._batcher
        with self._pcond:
            self._closed = True
            self._pcond.notify_all()
        if batcher is not None:
            batcher.join(5.0 if timeout is None else timeout)
        with self._pcond:
            stranded, self._pending = self._pending, []
        for req in stranded:
            req.exc = EngineClosed("prefill engine closed")
            req.ev.set()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
