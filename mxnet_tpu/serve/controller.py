"""Fleet controller: the serving fleet operates itself
(docs/serving.md §fleet controller).

The :class:`~mxnet_tpu.serve.ServeRouter` can dispatch, drain,
recycle and re-warm replicas, and its poller already sees every load
signal — but a human has to watch the gauges and act.
:class:`FleetController` closes that loop: it supervises one router
against a declared capacity policy and turns the polled signals into
actions.

* **Health-gated autoscaling** — sustained queue depth (or a shedding
  window) scales out through a caller-supplied ``spawn()`` hook; the
  new replica warms its declared buckets BEFORE admitting traffic
  (``add_replica(warm=True)``). A sustained idle window scales in
  through the router's zero-drop ``retire_replica`` drain. Hysteresis
  (``MXNET_CTRL_SUSTAIN`` consecutive ticks) and a post-action
  cooldown (``MXNET_CTRL_COOLDOWN`` ticks) keep a noisy signal from
  flapping the fleet. Both are counted in TICKS, never wall time, so
  every decision is deterministic under ``tick()``-driven tests.
* **Self-healing** — a replica the poller marked suspect whose control
  probe confirms dead is retired and respawned under the SAME name;
  in-flight generates ride the router's token-exact failover path, so
  healing changes nobody's tokens. Healing is exempt from cooldown —
  a dead replica is replaced immediately.
* **Rolling rollout with automatic rollback** — :meth:`rollout`
  promotes a new ``Predictor.export_buckets`` artifact
  (manifest-addressed) replica by replica through
  ``router.recycle(restart=)``, gating every step on a health probe:
  the promoted replica must come back live, carry the new artifact's
  ``model_id`` stamp, answer a canary infer within
  ``MXNET_CTRL_CANARY_TIMEOUT``, and keep its shed window under the
  policy. A failed gate rolls every already-promoted replica BACK to
  the prior manifest — the fleet is never left mixed-version after
  the controller returns.
* **Crash-safe state** — every action journals through
  ``guardrail.durable_replace`` atomic writes. A controller restarted
  on the same journal resumes: a rollout that died mid-promote is
  rolled back to the prior manifest on the next :meth:`tick` instead
  of being re-decided from scratch.

The controller owns NO transport: every byte still rides the router's
``serve/net.py`` clients (the serve lint holds), and every decision
reads the router's polled state (the ``poll_now()`` discipline —
``MXNET_CTRL_POLL_MS=0`` disables the background loop and tests drive
:meth:`tick` explicitly, no wall-clock sleeps anywhere).
"""
from __future__ import annotations

import json
import logging
import math
import os
import threading

from .. import config as _config
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..guardrail import durable_replace
from .router import ReplicaState

__all__ = ["FleetController", "RolloutResult"]

_JOURNAL_VERSION = 1
_MAX_ACTIONS = 256                 # journaled action-log bound


class RolloutResult:
    """What :meth:`FleetController.rollout` did: which replicas were
    promoted, whether the fleet rolled back, and the manifest the
    fleet uniformly serves now."""

    __slots__ = ("promoted", "rolled_back", "manifest", "reason")

    def __init__(self, promoted, rolled_back, manifest, reason=None):
        self.promoted = list(promoted)
        self.rolled_back = bool(rolled_back)
        self.manifest = manifest
        self.reason = reason

    def __repr__(self):
        return ("RolloutResult(promoted=%r, rolled_back=%r, "
                "manifest=%r, reason=%r)"
                % (self.promoted, self.rolled_back, self.manifest,
                   self.reason))


def _checked(name, value, typ, low=None, finite=False):
    value = typ(value)
    if low is not None and value < low:
        raise ValueError("%s must be >= %r, got %r" % (name, low, value))
    if finite and not math.isfinite(value):
        raise ValueError("%s must be finite, got %r" % (name, value))
    return value


class FleetController:
    """Supervise a :class:`ServeRouter` against a capacity policy.

    Parameters
    ----------
    router : ServeRouter
        The fleet to supervise. The controller polls it, scales it,
        heals it and rolls artifacts through it; it never owns the
        replica processes themselves.
    spawn : callable
        ``spawn(manifest) -> address`` — start one replica serving the
        given ``export_buckets`` manifest (``None`` = the caller's
        current/default artifact) and return its ``"host:port"`` /
        ``(host, port)`` once it answers the wire. The controller's
        only way to create capacity.
    retire : callable, optional
        ``retire(name, address)`` — reap a replica process the
        controller just drained-and-removed (scale-in) or declared
        dead (heal). Omitted: the caller leak-checks its own
        processes.
    journal : str, optional
        Path for the crash-safe state file (atomic
        ``durable_replace`` rewrites after every action). An existing
        file is LOADED: the controller resumes its manifest and
        finishes any interrupted rollout (by rolling back) on the
        next :meth:`tick`. Omitted: state is process-local.
    canary_inputs : list of array, optional
        Inputs for the rollout health gate's canary infer (batch-1
        shaped like a live request). Omitted: the gate skips the
        canary and checks liveness/stamp/shed only.
    clock : callable, optional
        Timestamp source for journal records (default
        ``telemetry.now_ms``). Decisions never read it — hysteresis
        and cooldown count ticks, so tests inject nothing and still
        get determinism.
    min_replicas / max_replicas / scale_out_depth / scale_out_shed /
    scale_in_depth / sustain / cooldown / canary_timeout / poll_ms
        Override the ``MXNET_CTRL_*`` knobs (docs/env_vars.md). All
        validated loudly here.
    """

    def __init__(self, router, spawn, retire=None, *, journal=None,
                 canary_inputs=None, clock=None, logger=None,
                 min_replicas=None, max_replicas=None,
                 scale_out_depth=None, scale_out_shed=None,
                 scale_in_depth=None, sustain=None, cooldown=None,
                 canary_timeout=None, poll_ms=None):
        if not callable(spawn):
            raise ValueError("spawn must be callable, got %r" % (spawn,))
        if retire is not None and not callable(retire):
            raise ValueError("retire must be callable, got %r"
                             % (retire,))
        self._router = router
        self._spawn = spawn
        self._retire = retire
        self._log = logger or logging.getLogger(__name__)
        self._now = clock or _telemetry.now_ms
        self._canary_inputs = canary_inputs

        def knob(override, name):
            return override if override is not None \
                else _config.get(name)
        self._min = _checked(
            "MXNET_CTRL_MIN_REPLICAS",
            knob(min_replicas, "MXNET_CTRL_MIN_REPLICAS"), int, low=1)
        self._max = _checked(
            "MXNET_CTRL_MAX_REPLICAS",
            knob(max_replicas, "MXNET_CTRL_MAX_REPLICAS"), int,
            low=self._min)
        self._out_depth = _checked(
            "MXNET_CTRL_SCALE_OUT_DEPTH",
            knob(scale_out_depth, "MXNET_CTRL_SCALE_OUT_DEPTH"), float,
            low=0.0, finite=True)
        self._out_shed = _checked(
            "MXNET_CTRL_SCALE_OUT_SHED",
            knob(scale_out_shed, "MXNET_CTRL_SCALE_OUT_SHED"), float,
            finite=True)
        if self._out_shed <= 0:
            raise ValueError(
                "MXNET_CTRL_SCALE_OUT_SHED must be > 0 (a zero "
                "threshold would scale out on every single shed), "
                "got %r" % self._out_shed)
        self._in_depth = _checked(
            "MXNET_CTRL_SCALE_IN_DEPTH",
            knob(scale_in_depth, "MXNET_CTRL_SCALE_IN_DEPTH"), float,
            low=0.0, finite=True)
        if self._in_depth >= self._out_depth:
            raise ValueError(
                "MXNET_CTRL_SCALE_IN_DEPTH (%r) must be below "
                "MXNET_CTRL_SCALE_OUT_DEPTH (%r) — an overlapping "
                "band would scale in and out of the same signal"
                % (self._in_depth, self._out_depth))
        self._sustain = _checked(
            "MXNET_CTRL_SUSTAIN",
            knob(sustain, "MXNET_CTRL_SUSTAIN"), int, low=1)
        self._cooldown = _checked(
            "MXNET_CTRL_COOLDOWN",
            knob(cooldown, "MXNET_CTRL_COOLDOWN"), int, low=0)
        self._canary_timeout = _checked(
            "MXNET_CTRL_CANARY_TIMEOUT",
            knob(canary_timeout, "MXNET_CTRL_CANARY_TIMEOUT"), float,
            finite=True)
        if self._canary_timeout <= 0:
            raise ValueError(
                "MXNET_CTRL_CANARY_TIMEOUT must be positive, got %r"
                % self._canary_timeout)
        self._poll_ms = _checked(
            "MXNET_CTRL_POLL_MS",
            knob(poll_ms, "MXNET_CTRL_POLL_MS"), float, low=0.0,
            finite=True)

        # decision state: tick-counted, never wall-clocked
        self._ticks = 0
        self._hot = 0                  # consecutive over-threshold ticks
        self._cold = 0                 # consecutive idle ticks
        self._cooldown_until = 0       # tick number scaling resumes at
        self._manifest = None          # the artifact the fleet serves
        self._pending = None           # interrupted-rollout record
        self._actions = []
        self._op_lock = threading.Lock()   # tick/rollout serialization

        # the serve.ctrl.* vocabulary (docs/observability.md). These
        # counters are registered HERE — a process that never builds a
        # controller never publishes them, so the pre-controller perf
        # baselines stay byte-identical (router precedent, PR 14)
        self._c_scale_outs = _telemetry.counter("serve.ctrl.scale_outs")
        self._c_scale_ins = _telemetry.counter("serve.ctrl.scale_ins")
        self._c_heals = _telemetry.counter("serve.ctrl.heals")
        self._c_promotes = _telemetry.counter("serve.ctrl.promotes")
        self._c_rollbacks = _telemetry.counter("serve.ctrl.rollbacks")

        self._journal_path = journal
        if journal and os.path.exists(journal):
            self._load_journal()
        _telemetry.journal_event(
            "serve.ctrl.start", min_replicas=self._min,
            max_replicas=self._max, resumed=self._pending is not None)

        self._closed = False
        self._tick_thread = None
        self._tick_stop = threading.Event()
        if self._poll_ms > 0:
            self._tick_thread = threading.Thread(
                target=self._tick_loop, name="mxnet-ctrl-tick",
                daemon=True)
            self._tick_thread.start()

    # -- crash-safe journal -------------------------------------------------
    def _load_journal(self):
        with open(self._journal_path) as f:
            doc = json.load(f)
        if doc.get("version") != _JOURNAL_VERSION:
            raise ValueError(
                "controller journal %s has version %r, this build "
                "reads %d — refusing to guess at its semantics"
                % (self._journal_path, doc.get("version"),
                   _JOURNAL_VERSION))
        self._manifest = doc.get("manifest")
        self._pending = doc.get("pending_rollout")
        self._actions = list(doc.get("actions") or [])[-_MAX_ACTIONS:]

    def _save_journal(self):
        if not self._journal_path:
            return
        doc = {"version": _JOURNAL_VERSION,
               "manifest": self._manifest,
               "pending_rollout": self._pending,
               "actions": self._actions[-_MAX_ACTIONS:]}
        tmp = self._journal_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        durable_replace(tmp, self._journal_path)

    def _record(self, action, **fields):
        """One action: journal event + durable state write. The event
        rides the telemetry journal (operators), the state file makes
        a restarted controller resume instead of re-deciding."""
        rec = {"action": action, "t": self._now()}
        rec.update(fields)
        self._actions.append(rec)
        del self._actions[:-_MAX_ACTIONS]
        _telemetry.journal_event("serve.ctrl.%s" % action, **fields)
        self._save_journal()

    # -- the decision step --------------------------------------------------
    def tick(self):
        """One deterministic supervision step: poll the fleet, finish
        any journal-recovered rollout, heal confirmed-dead replicas,
        then evaluate the scale policy (hysteresis + cooldown, all
        tick-counted). Returns ``{"healed": [...], "scaled_out": [...],
        "scaled_in": [...], "recovered": bool}`` describing what this
        tick actually did. The background loop calls this every
        ``MXNET_CTRL_POLL_MS``; deterministic tests call it directly
        (the ``poll_now()`` discipline — no sleeps anywhere)."""
        with self._op_lock:
            return self._tick_locked()

    def _tick_locked(self):
        self._ticks += 1
        out = {"healed": [], "scaled_out": [], "scaled_in": [],
               "recovered": False}
        if self._pending is not None:
            # a previous controller died mid-rollout (the journal
            # still holds the pending record): restore the invariant
            # FIRST — a mixed-version fleet must not also be scaled
            self._recover_pending()
            out["recovered"] = True
        self._router.poll_now()
        reps = self._router.replicas()

        # -- heal: suspect + probe-confirmed dead -> retire + respawn
        for name, desc in list(reps.items()):
            if desc["state"] != ReplicaState.SUSPECT:
                continue
            if self._router.probe_replica(name):
                continue               # a blip; the poller revives it
            self._heal(name, desc)
            out["healed"].append(name)
        if out["healed"]:
            self._router.poll_now()
            reps = self._router.replicas()

        # -- scale signals over the routable fleet ----------------------
        live = {n: d for n, d in reps.items()
                if d["state"] == ReplicaState.LIVE
                and not d["stats"].get("draining")}
        n_live = len(live)
        if not n_live:
            return out                 # nothing routable: healing only
        depth = sum(d["stats"].get("queue_depth", 0)
                    for d in live.values()) / n_live
        shed = sum(d["stats"].get("shed_rate", 0)
                   for d in live.values())
        hot = depth >= self._out_depth or shed >= self._out_shed
        cold = depth <= self._in_depth and shed == 0
        # hysteresis: an oscillating signal keeps resetting the
        # streak and never moves the fleet
        self._hot = self._hot + 1 if hot else 0
        self._cold = self._cold + 1 if cold else 0
        if self._ticks < self._cooldown_until:
            return out                 # observing the last action
        if self._hot >= self._sustain and n_live < self._max:
            name = self._scale_out(depth, shed)
            if name is not None:
                out["scaled_out"].append(name)
        elif self._cold >= self._sustain and n_live > self._min:
            name = self._scale_in(live, depth)
            if name is not None:
                out["scaled_in"].append(name)
        return out

    def _arm_cooldown(self):
        self._hot = self._cold = 0
        self._cooldown_until = self._ticks + self._cooldown

    def _scale_out(self, depth, shed):
        addr = self._spawn(self._manifest)
        host, port = _split_addr(addr)
        name = self._router.add_replica(host, port, warm=True)
        self._c_scale_outs.inc()
        self._arm_cooldown()
        self._record("scale_out", name=name,
                     addr="%s:%d" % (host, port),
                     depth=round(depth, 3), shed_rate=shed)
        self._log.info("ctrl: scaled out -> %s at %s:%d "
                       "(depth %.2f, shed %s)", name, host, port,
                       depth, shed)
        return name

    def _scale_in(self, live, depth):
        # victim: the last-admitted live replica (insertion order) —
        # the longest-standing replicas keep their warmed caches and
        # session gravity
        name = next(reversed(list(live)))
        desc = live[name]
        addr = "%s:%d" % (desc["host"], desc["port"])
        self._router.retire_replica(name)      # zero-drop drain+remove
        if self._retire is not None:
            self._retire(name, addr)
        self._c_scale_ins.inc()
        self._arm_cooldown()
        self._record("scale_in", name=name, addr=addr,
                     depth=round(depth, 3))
        self._log.info("ctrl: scaled in -> retired %s at %s (depth "
                       "%.2f)", name, addr, depth)
        return name

    def _heal(self, name, desc):
        addr = "%s:%d" % (desc["host"], desc["port"])
        if self._retire is not None:
            self._retire(name, addr)           # reap the corpse
        self._router.remove_replica(name)
        new_addr = self._spawn(self._manifest)
        host, port = _split_addr(new_addr)
        # same name: in-flight generates pinned to the dead replica
        # already took the token-exact failover path the moment their
        # transport faulted; the respawn just restores capacity
        self._router.add_replica(host, port, name=name, warm=True)
        self._c_heals.inc()
        self._record("heal", name=name, dead_addr=addr,
                      addr="%s:%d" % (host, port))
        self._log.warning("ctrl: healed %s — dead at %s, respawned "
                          "at %s:%d", name, addr, host, port)

    # -- rolling rollout ----------------------------------------------------
    def rollout(self, manifest, model_id=None, canary_inputs=None):
        """Promote ``manifest`` (an ``export_buckets`` prefix) across
        the fleet, one replica at a time, each step gated on health.

        Every replica recycles through the router's zero-drop drain
        with a ``restart`` hook that retires the old process and
        spawns one serving ``manifest`` — and comes back QUARANTINED
        (``recycle(admit=False)``): warmed but unroutable, so live
        traffic cannot reach the candidate artifact while it is still
        unproven. The gate then requires: the quarantined replica
        answering its liveness probe, its hello-declared ``model_id``
        matching the new artifact's stamp, a canary infer answered
        within ``MXNET_CTRL_CANARY_TIMEOUT`` (when canary inputs are
        configured), and the shed window under the scale-out
        threshold. Only a passed gate admits the replica to traffic.
        A failed gate rolls the failed replica AND every
        already-promoted one back to the prior manifest — the fleet
        is uniform again before this returns, and no client request
        was ever routed to the rejected artifact.

        ``model_id``: the expected stamp; default reads it from
        ``manifest + ".serve.json"`` when that file is readable here,
        else the stamp check is skipped (the spawn hook may realize
        manifests on machines this process cannot read).

        Returns a :class:`RolloutResult`. Raises only when the
        recovery itself fails (a rollback recycle erroring) — the
        journal then still holds the pending record, and the next
        :meth:`tick` (or a restarted controller) retries the
        rollback."""
        with self._op_lock:
            return self._rollout_locked(manifest, model_id,
                                        canary_inputs)

    def _rollout_locked(self, manifest, model_id, canary_inputs):
        if self._pending is not None:
            self._recover_pending()
        if model_id is None:
            model_id = _manifest_stamp(manifest)
        prior = self._manifest
        names = list(self._router.replicas())
        self._pending = {"manifest": manifest, "prior": prior,
                         "model_id": model_id, "promoted": [],
                         "promoting": None}
        self._record("rollout", phase="start", manifest=str(manifest),
                     prior=str(prior), replicas=len(names))
        sp = _trace.start_span("serve.ctrl.rollout",
                               replicas=len(names))
        try:
            for name in names:
                self._pending["promoting"] = name
                self._save_journal()
                try:
                    self._promote(name, manifest, admit=False)
                except Exception as exc:   # noqa: BLE001 — a recycle/
                    # spawn error mid-promote: the failed replica may
                    # be on either version — roll back everything
                    # touched (it is in the pending record)
                    self._rollback("promote of %s failed: %s"
                                   % (name, exc))
                    return RolloutResult(
                        [], True, prior,
                        reason="promote of %s failed: %s (%s)"
                        % (name, exc, type(exc).__name__))
                self._pending["promoted"].append(name)
                self._pending["promoting"] = None
                self._save_journal()
                ok, reason = self._gate(name, model_id, canary_inputs)
                if not ok:
                    self._rollback("gate failed on %s: %s"
                                   % (name, reason))
                    return RolloutResult(
                        [], True, prior,
                        reason="gate failed on %s: %s" % (name, reason))
                self._router.admit_replica(name)
                self._c_promotes.inc()
                self._record("promote", name=name,
                             manifest=str(manifest))
            promoted = list(self._pending["promoted"])
            self._manifest = manifest
            self._pending = None
            self._record("rollout", phase="complete",
                         manifest=str(manifest), promoted=len(promoted))
            return RolloutResult(promoted, False, manifest)
        finally:
            _trace.end_span(sp)

    def _promote(self, name, manifest, admit=True):
        def restart():
            desc = self._router.replicas().get(name)
            if desc is not None and self._retire is not None:
                self._retire(name, "%s:%d" % (desc["host"],
                                              desc["port"]))
            return self._spawn(manifest)
        self._router.recycle(name, restart=restart, warm=True,
                             admit=admit)

    def _gate(self, name, model_id, canary_inputs):
        """The per-step health probe, run against the QUARANTINED
        candidate (still unroutable — only canary traffic can reach
        it). Returns ``(ok, reason)``."""
        self._router.poll_now()
        desc = self._router.replicas().get(name)
        if desc is None:
            return False, "replica vanished during promote"
        if not self._router.probe_replica(name):
            return False, "liveness probe failed after promote"
        if model_id is not None and desc.get("model_id") != model_id:
            return False, ("artifact stamp mismatch: hello says %r, "
                           "manifest says %r"
                           % (desc.get("model_id"), model_id))
        inputs = canary_inputs if canary_inputs is not None \
            else self._canary_inputs
        if inputs is not None:
            try:
                self._router.canary(name, inputs,
                                    timeout=self._canary_timeout)
            except Exception as exc:   # noqa: BLE001 — ANY failure —
                # typed replica error, timeout, transport fault — is
                # exactly what the gate exists to catch
                return False, ("canary failed: %s (%s)"
                               % (exc, type(exc).__name__))
            self._router.poll_now()
            desc = self._router.replicas().get(name) or desc
        if desc["stats"].get("shed_rate", 0) >= self._out_shed:
            return False, ("shed window over policy: %s >= %s"
                           % (desc["stats"]["shed_rate"],
                              self._out_shed))
        return True, None

    def _rollback(self, reason):
        """Restore the fleet to the pending record's prior manifest:
        recycle every touched replica (promoted + the one mid-promote)
        back, newest first. Clears the pending record only when every
        rollback recycle succeeded — a crash or error here leaves the
        journal intact for the next attempt."""
        pend = self._pending
        names = list(pend.get("promoted") or [])
        if pend.get("promoting") and pend["promoting"] not in names:
            names.append(pend["promoting"])
        prior = pend.get("prior")
        for name in reversed(names):
            if self._router.replicas().get(name) is None:
                continue               # vanished: nothing to restore
            self._promote(name, prior)
            self._c_rollbacks.inc()
            self._record("rollback", name=name, manifest=str(prior))
        self._manifest = prior
        self._pending = None
        self._record("rollout", phase="rolled_back",
                     reason=str(reason), replicas=len(names))
        self._log.warning("ctrl: rollout rolled back (%s) — fleet "
                          "uniform on %r", reason, prior)

    def _recover_pending(self):
        """Finish a journal-recovered rollout by rolling it back —
        the conservative resume: the prior manifest is the last state
        the journal PROVES every replica can serve."""
        self._log.warning("ctrl: resuming interrupted rollout from "
                          "journal — rolling back to %r",
                          self._pending.get("prior"))
        self._rollback("controller restarted mid-rollout")

    # -- lifecycle ----------------------------------------------------------
    @property
    def manifest(self):
        """The artifact the fleet uniformly serves (None = whatever
        the replicas were born with — no rollout has completed)."""
        return self._manifest

    def describe(self):
        """Controller introspection: policy, decision state, and the
        journaled action tail."""
        with self._op_lock:
            return {
                "min_replicas": self._min, "max_replicas": self._max,
                "scale_out_depth": self._out_depth,
                "scale_out_shed": self._out_shed,
                "scale_in_depth": self._in_depth,
                "sustain": self._sustain, "cooldown": self._cooldown,
                "ticks": self._ticks, "hot": self._hot,
                "cold": self._cold,
                "cooldown_until": self._cooldown_until,
                "manifest": self._manifest,
                "pending_rollout": self._pending is not None,
                "actions": list(self._actions),
            }

    def _tick_loop(self):
        failing = False
        while not self._tick_stop.wait(self._poll_ms / 1000.0):
            try:
                self.tick()
                failing = False
            except Exception:   # noqa: BLE001 — the supervision loop
                # must outlive any one failed action (a spawn hook
                # erroring, a drain timing out): log the first failure
                # of a streak loudly, repeats at debug
                if not failing:
                    self._log.exception(
                        "ctrl: tick failed — loop keeps running "
                        "(repeats logged at debug)")
                else:
                    self._log.debug("ctrl: tick failed again",
                                    exc_info=True)
                failing = True

    def close(self):
        """Stop the background loop. The router and the replicas stay
        up — the controller supervises, it does not own."""
        if self._closed:
            return
        self._closed = True
        self._tick_stop.set()
        if self._tick_thread is not None:
            self._tick_thread.join(5.0)
        _telemetry.journal_event("serve.ctrl.stop")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def _split_addr(addr):
    if addr is None:
        raise ValueError("spawn() returned no address")
    if isinstance(addr, (tuple, list)):
        host, port = addr
        return str(host), int(port)
    host, _, port = str(addr).rpartition(":")
    if not host:
        raise ValueError("spawn() must return HOST:PORT or "
                         "(host, port), got %r" % (addr,))
    return host, int(port)


def _manifest_stamp(manifest):
    """The expected model_id of an export_buckets manifest, when its
    ``.serve.json`` is readable from this process (the spawn hook may
    realize manifests on machines this controller cannot read — then
    the stamp gate is skipped rather than guessed)."""
    if not isinstance(manifest, str):
        return None
    path = manifest + ".serve.json"
    try:
        with open(path) as f:
            return json.load(f).get("model_id")
    except (OSError, ValueError):
        return None
