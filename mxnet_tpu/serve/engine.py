"""In-process inference serving engine — dynamic batching over the
deploy artifacts (docs/serving.md).

The reference shipped a deploy-time predict path (c_predict_api + the
amalgamation) but left *serving* to the user: every caller paid one
framework dispatch per request. On TPU that is the whole ballgame —
XLA dispatch and kernel launch amortize beautifully over a batch and
terribly over a stream of singletons — so the TPU-native analogue of
the predict API is an engine that coalesces concurrent requests into
bucketed batches (the bucketed-specialization idea the compiler stack
rewards, cf. TVM arXiv:1802.04799):

* **Bounded request queue** — admission beyond ``MXNET_SERVE_QUEUE_CAP``
  fails fast with the typed :class:`Overloaded` (load shedding; never a
  silent drop, never an unbounded queue).
* **Batcher thread** — coalesces queued requests for up to
  ``MXNET_SERVE_MAX_WAIT_MS``, pads the group to the smallest
  configured bucket (``MXNET_SERVE_BUCKETS``), runs ONE forward, and
  slices the outputs back per request. One XLA specialization per
  bucket, not one per arrival pattern.
* **Per-request deadlines** — a request still queued past its deadline
  fails with the typed :class:`RequestTimeout` instead of occupying a
  batch slot.
* **Graceful drain** — ``close()`` (and SIGTERM, through
  ``guardrail.GracefulShutdown``'s chaining handler) finishes every
  admitted request and rejects new ones with :class:`EngineClosed`.
* **Telemetry** — every layer feeds the PR-8 registry
  (``serve.queue_depth`` gauge; ``serve.batch_fill`` /
  ``serve.queue_wait_ms`` / ``serve.request_ms`` histograms;
  ``serve.admitted`` / ``serve.shed`` / ``serve.timeouts`` counters)
  and the run journal (``serve.batch`` / ``serve.shed`` /
  ``serve.timeout`` / ``serve.drain`` events), which
  ``tools/telemetry_report.py`` renders as a serving section.

The model is anything with ``forward(*arrays) -> [outputs]``: an
in-process :class:`~mxnet_tpu.predictor.Predictor` (jit specializes per
bucket), a ``{bucket: CompiledPredictor}`` dict from
:meth:`~mxnet_tpu.predictor.Predictor.export_buckets` (the AOT deploy
chain — see :meth:`ServeEngine.from_export`), or any user callable
wrapper. Outputs must be row-aligned with inputs (axis 0 is the batch),
which every predict-path graph in this framework satisfies.

The TCP front end lives in ``serve/net.py``; the continuous-batching
decode engine for the transformer ``Generator`` in ``serve/decode.py``.
"""
from __future__ import annotations

import logging
import signal
import threading
from collections import deque

import numpy as np

from .. import config as _config
from .. import telemetry as _telemetry
from .. import trace as _trace

__all__ = ["ServeEngine", "ServeFuture", "ServeError", "Overloaded",
           "RequestTimeout", "EngineClosed", "SessionEvacuated",
           "typed_error"]


class ServeError(RuntimeError):
    """Base of the typed serving errors — the wire protocol
    (serve/net.py) round-trips the concrete class by name, so a remote
    client raises exactly what the engine raised."""


class Overloaded(ServeError):
    """The request was shed at admission: the bounded queue is full (or
    the engine is past its deadline budget). Fast-fail backpressure —
    the client learns immediately and can retry elsewhere; nothing is
    ever silently dropped."""


class RequestTimeout(ServeError):
    """The request's deadline expired while it was still queued; it
    never reached a batch. The deadline is the caller's, so the caller
    gets a typed error rather than a stale answer."""


class EngineClosed(ServeError):
    """The engine is draining (close() or SIGTERM): admitted requests
    finish, new ones are rejected with this."""


class SessionEvacuated(ServeError):
    """An in-flight decode session was exported off its replica
    (migrating recycle or SIGTERM — ``ContinuousDecoder.evacuate``):
    ``.state`` carries the portable session dict from
    ``export_session`` instead of a finished row. This never crosses
    the wire as a typed error — the generate handler catches it and
    answers an ``evacuated`` reply, which the fleet router resumes on
    a survivor token-exactly (docs/robustness.md, fleet failure
    semantics)."""

    def __init__(self, state):
        super().__init__(
            "session evacuated after %d emitted token(s) — resume it "
            "on a survivor" % len(state.get("emitted") or ()))
        self.state = state


_TYPED = {c.__name__: c for c in (Overloaded, RequestTimeout,
                                  EngineClosed, ServeError)}


def typed_error(kind, msg):
    """Reconstruct a typed serving error from its class name (the wire
    representation serve/net.py ships)."""
    return _TYPED.get(kind, ServeError)(msg)


class ServeFuture:
    """One request's pending response: exactly one of a payload (list
    of per-request output arrays) or a typed error, set by the batcher
    thread."""

    __slots__ = ("inputs", "rows", "t_enq", "deadline", "tc", "_ev",
                 "_value", "_exc")

    def __init__(self, inputs, rows, t_enq, deadline, tc=None):
        self.inputs = inputs
        self.rows = rows
        self.t_enq = t_enq
        self.deadline = deadline           # now_ms scale; None = none
        self.tc = tc                       # TraceContext of the caller
        self._ev = threading.Event()
        self._value = None
        self._exc = None

    def _finish(self, value):
        self._value = value
        self._ev.set()

    def _fail(self, exc):
        self._exc = exc
        self._ev.set()

    def done(self):
        return self._ev.is_set()

    def result(self, timeout=None):
        """Block for the response. Raises the engine's typed error if
        the request failed, or RequestTimeout if ``timeout`` seconds
        pass locally."""
        if not self._ev.wait(timeout):
            raise RequestTimeout(
                "no response within %.3fs (request still in flight)"
                % timeout)
        if self._exc is not None:
            raise self._exc
        return self._value


def _parse_buckets(raw):
    try:
        buckets = sorted({int(b) for b in
                          str(raw).replace(",", " ").split()})
    except ValueError:
        raise ValueError("bad bucket list %r (want comma-separated "
                         "ints, e.g. '1,2,4,8')" % (raw,))
    if not buckets or buckets[0] < 1:
        raise ValueError("buckets must be positive ints, got %r"
                         % (raw,))
    return tuple(buckets)


class ServeEngine:
    """Dynamic-batching inference engine over a forward-capable model.

    Parameters
    ----------
    model : forward-capable or dict {bucket: forward-capable}
        Called as ``model.forward(*arrays)`` with every array padded to
        the chosen bucket's batch; must return a list of row-aligned
        outputs. A dict routes each bucket to its own (typically AOT
        compiled) model — the :meth:`from_export` deploy chain.
    buckets : iterable of int, optional
        Padded batch sizes, ascending. Defaults to the dict's keys, or
        ``MXNET_SERVE_BUCKETS``.
    max_wait_ms / queue_cap / deadline_ms : optional
        Override ``MXNET_SERVE_MAX_WAIT_MS`` / ``MXNET_SERVE_QUEUE_CAP``
        / ``MXNET_SERVE_DEADLINE_MS``.
    feature_shapes : list of tuple, optional
        Per-input feature shape WITHOUT the batch axis, for
        :meth:`warmup` and submit-time validation. Learned from the
        first request when omitted.
    dtype : str
        Input dtype for warmup zeros (default float32).
    install_sigterm : bool
        Install the chaining ``guardrail.GracefulShutdown`` handler so
        SIGTERM drains the engine (default True; degrades to a no-op
        off the main thread).
    """

    # the hello frame's identity: a batch-inference replica (the fleet
    # router's role-aware dispatch keys off declared roles — "prefill"
    # and "decode" replicas split the generation phases; everything
    # else, this engine included, serves the colocated paths)
    role = "batch"

    # generation stamp of the served artifact: set by from_export from
    # the export_buckets manifest, None for in-process models. Rides
    # the hello frame so a fleet controller (and `describe()`) can tell
    # a half-promoted fleet from a uniform one.
    model_id = None

    def __init__(self, model, buckets=None, max_wait_ms=None,
                 queue_cap=None, deadline_ms=None, feature_shapes=None,
                 dtype="float32", install_sigterm=True, logger=None):
        self._log = logger or logging.getLogger(__name__)
        if isinstance(model, dict):
            if not model:
                raise ValueError("empty model dict")
            self._by_bucket = {int(k): v for k, v in model.items()}
            derived = tuple(sorted(self._by_bucket))
            if buckets is not None and \
                    tuple(sorted(int(b) for b in buckets)) != derived:
                raise ValueError(
                    "buckets %r disagree with the model dict keys %r"
                    % (tuple(buckets), derived))
            self._buckets = derived
            self._model = None
        else:
            self._by_bucket = None
            self._model = model
            self._buckets = (
                tuple(sorted(int(b) for b in buckets)) if buckets
                else _parse_buckets(_config.get("MXNET_SERVE_BUCKETS")))
        if self._buckets[0] < 1:
            raise ValueError("buckets must be >= 1")
        self._max_bucket = self._buckets[-1]
        self._max_wait_ms = float(
            max_wait_ms if max_wait_ms is not None
            else _config.get("MXNET_SERVE_MAX_WAIT_MS"))
        self._cap = int(queue_cap if queue_cap is not None
                        else _config.get("MXNET_SERVE_QUEUE_CAP"))
        self._default_deadline = float(
            deadline_ms if deadline_ms is not None
            else _config.get("MXNET_SERVE_DEADLINE_MS"))
        self._feature_shapes = ([tuple(s) for s in feature_shapes]
                                if feature_shapes else None)
        self._dtype = np.dtype(dtype)

        self._queue = deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._draining = False            # flipped by close()/SIGTERM
        self._closed = False
        # per-engine counts for callers/tests (the registry aggregates
        # across engines; these don't)
        self._admitted = 0
        self._shed = 0
        self._timeouts = 0
        self._forwards = 0
        self._completed = 0
        self._failed = 0
        self._fill_sum = 0
        self._warmed = []                 # buckets pre-compiled by warmup()

        # telemetry handles hoisted once (name-is-identity registry)
        self._g_depth = _telemetry.gauge("serve.queue_depth")
        self._h_fill = _telemetry.histogram(
            "serve.batch_fill", buckets=_telemetry.COUNT_BUCKETS)
        self._h_qwait = _telemetry.histogram("serve.queue_wait_ms")
        self._h_req = _telemetry.histogram("serve.request_ms")
        self._c_admitted = _telemetry.counter("serve.admitted")
        self._c_shed = _telemetry.counter("serve.shed")
        self._c_timeouts = _telemetry.counter("serve.timeouts")

        self._shutdown = None
        if install_sigterm:
            from .. import guardrail as _guardrail
            self._shutdown = _guardrail.GracefulShutdown(
                signals=(signal.SIGTERM,), logger=self._log,
                on_request=self._request_drain,
                action="serving engine draining (in-flight requests "
                       "finish, new ones are rejected)").install()

        _telemetry.journal_event(
            "serve.start", buckets=list(self._buckets),
            queue_cap=self._cap, max_wait_ms=self._max_wait_ms)
        self._thread = threading.Thread(
            target=self._batcher_loop, name="mxnet-serve-batcher",
            daemon=True)
        self._thread.start()

    # -- admission ----------------------------------------------------------
    def submit(self, *inputs, deadline_ms=None, tc=None, session=None):
        """Enqueue one request; returns a :class:`ServeFuture`.

        ``inputs``: one array per model input, each with a leading
        batch axis (a single sample is shape ``(1, ...)``); a request
        may carry several rows, up to the largest bucket. Raises
        :class:`Overloaded` when the queue is full and
        :class:`EngineClosed` while draining — both BEFORE any work is
        queued, so backpressure is immediate.

        ``tc``: an explicit :class:`~mxnet_tpu.trace.TraceContext` the
        batcher's lifecycle spans should parent to (the TCP front end
        hands in the remote caller's); defaults to the submitting
        thread's current span.

        ``session``: accepted and ignored — session ids are a ROUTING
        concern (the fleet router pins a session to the replica
        holding its decode state, serve/router.py); a single engine
        has nothing to route, but must accept fleet traffic
        unchanged."""
        del session                       # routing concern, see above
        arrays = [np.asarray(a) for a in inputs]
        if not arrays:
            raise ValueError("submit needs at least one input array")
        rows = int(arrays[0].shape[0]) if arrays[0].ndim else 0
        if rows < 1:
            raise ValueError(
                "inputs need a leading batch axis (a single sample is "
                "shape (1, ...)), got %r" % (arrays[0].shape,))
        if rows > self._max_bucket:
            raise ValueError(
                "request rows (%d) exceed the largest bucket (%d); "
                "split the request or configure a larger bucket"
                % (rows, self._max_bucket))
        if any(int(a.shape[0]) != rows for a in arrays):
            raise ValueError(
                "rows must agree across inputs, got %r"
                % ([a.shape for a in arrays],))
        feats = [a.shape[1:] for a in arrays]
        if self._feature_shapes is None:
            self._feature_shapes = feats
        elif feats != self._feature_shapes:
            raise ValueError(
                "inputs %r do not match the engine's feature shapes "
                "%r" % ([a.shape for a in arrays],
                        self._feature_shapes))
        t_enq = _telemetry.now_ms()
        if deadline_ms is None:
            deadline_ms = self._default_deadline
        deadline = t_enq + float(deadline_ms) if deadline_ms else None
        if tc is None:
            tc = _trace.current_context()
        req = ServeFuture(arrays, rows, t_enq, deadline, tc=tc)
        with self._cond:
            if self._draining or self._closed:
                raise EngineClosed(
                    "serving engine is draining — request rejected")
            if len(self._queue) >= self._cap:
                self._shed += 1
                self._c_shed.inc()
                _telemetry.journal_event("serve.shed",
                                         depth=len(self._queue))
                raise Overloaded(
                    "serving queue full (%d requests) — shed"
                    % len(self._queue))
            self._queue.append(req)
            self._admitted += 1
            self._c_admitted.inc()
            self._g_depth.set(len(self._queue))
            self._cond.notify_all()
        return req

    def infer(self, *inputs, deadline_ms=None, timeout=None):
        """submit + result in one blocking call."""
        return self.submit(*inputs,
                           deadline_ms=deadline_ms).result(timeout)

    # -- batcher ------------------------------------------------------------
    def _rows_queued(self):
        return sum(r.rows for r in self._queue)

    def _pop_group(self):
        """(live FIFO group that fits the largest bucket, expired
        requests). Deadline-expired requests pop out of the way here so
        they never consume group row budget — a live request that fits
        is never displaced by a doomed one."""
        group, expired = [], []
        rows = 0
        now = _telemetry.now_ms()
        while self._queue:
            nxt = self._queue[0]
            if nxt.deadline is not None and now > nxt.deadline:
                expired.append(self._queue.popleft())
                continue
            if group and rows + nxt.rows > self._max_bucket:
                break
            group.append(self._queue.popleft())
            rows += nxt.rows
        return group, expired

    def _batcher_loop(self):
        while True:
            with self._cond:
                while not self._queue and not self._draining:
                    # bounded waits: the SIGTERM handler only sets the
                    # drain flag (it must not touch this lock), so the
                    # loop has to notice it by polling
                    self._cond.wait(0.05)
                if not self._queue:
                    break                    # draining and drained
                first_t = self._queue[0].t_enq
                while (self._rows_queued() < self._max_bucket
                       and not self._draining):
                    remain = self._max_wait_ms - \
                        (_telemetry.now_ms() - first_t)
                    if remain <= 0:
                        break
                    self._cond.wait(min(remain / 1000.0, 0.05))
                group, expired = self._pop_group()
                self._g_depth.set(len(self._queue))
            for r in expired:
                self._fail_timeout(r)
            if group:
                self._run_group(group)
        _telemetry.journal_event("serve.stop")

    def _bucket_for(self, rows):
        for b in self._buckets:
            if b >= rows:
                return b
        return self._max_bucket            # unreachable: submit caps

    def _forward(self, bucket, feed):
        model = self._by_bucket[bucket] if self._by_bucket is not None \
            else self._model
        return model.forward(*feed)

    @staticmethod
    def _to_np(out):
        return out.asnumpy() if hasattr(out, "asnumpy") \
            else np.asarray(out)

    def _fail_timeout(self, r):
        now = _telemetry.now_ms()
        self._timeouts += 1
        self._c_timeouts.inc()
        _telemetry.journal_event("serve.timeout",
                                 wait_ms=round(now - r.t_enq, 3))
        _trace.add_span("serve.queue", r.t_enq, now, parent=r.tc,
                        timeout=True)
        r._fail(RequestTimeout(
            "deadline exceeded after %.1f ms in queue"
            % (now - r.t_enq)))

    def _run_group(self, group):
        now = _telemetry.now_ms()
        live = []
        for r in group:
            # re-checked here: a deadline can lapse between the pop
            # and this dispatch
            if r.deadline is not None and now > r.deadline:
                self._fail_timeout(r)
            else:
                self._h_qwait.observe(now - r.t_enq)
                live.append(r)
        if not live:
            return
        rows = sum(r.rows for r in live)
        bucket = self._bucket_for(rows)
        t0 = _telemetry.now_ms()
        try:
            feed = [np.concatenate([r.inputs[i] for r in live], axis=0)
                    for i in range(len(live[0].inputs))]
            if rows < bucket:
                feed = [np.concatenate(
                    [a, np.zeros((bucket - rows,) + a.shape[1:],
                                 a.dtype)], axis=0) for a in feed]
            t_fwd = _telemetry.now_ms()   # pad/concat vs forward split
            outs = [self._to_np(o)
                    for o in self._forward(bucket, feed)]
        except Exception as exc:           # noqa: BLE001 — every
            # request gets exactly one response; an engine-side error
            # IS that response, typed as itself
            self._failed += len(live)
            for r in live:
                r._fail(exc)
            _telemetry.journal_event("serve.error",
                                     error=type(exc).__name__)
            self._log.exception("serve: batch forward failed "
                                "(%d requests)", len(live))
            return
        fwd_ms = _telemetry.now_ms() - t0
        self._forwards += 1
        self._fill_sum += rows
        self._h_fill.observe(rows)
        end = _telemetry.now_ms()
        t_done = t0 + fwd_ms
        off = 0
        for r in live:
            r._finish([o[off:off + r.rows] for o in outs])
            self._h_req.observe(end - r.t_enq)
            off += r.rows
        self._completed += len(live)
        if _trace.enabled():
            # request lifecycle, reconstructed from the timestamps
            # already taken and parented to each request's own caller
            # span (across threads — the report draws the arrows):
            # queue -> batch(pad) -> forward -> respond. respond ends
            # AFTER the finish loop — it covers the output slicing and
            # the future wakeups, not just bookkeeping.
            t_resp = _telemetry.now_ms()
            for r in live:
                _trace.add_span("serve.queue", r.t_enq, now,
                                parent=r.tc)
                _trace.add_span("serve.pad", t0, t_fwd, parent=r.tc,
                                bucket=bucket, fill=rows)
                _trace.add_span("serve.forward", t_fwd, t_done,
                                parent=r.tc, bucket=bucket, fill=rows,
                                requests=len(live))
                _trace.add_span("serve.respond", t_done, t_resp,
                                parent=r.tc)
            # one spill write per batch, not one per record (the
            # batcher thread has no open span to trigger a flush)
            _trace.flush()
        _telemetry.journal_event(
            "serve.batch", bucket=bucket, fill=rows,
            requests=len(live), forward_ms=round(fwd_ms, 3),
            wait_ms=round(t0 - min(r.t_enq for r in live), 3))

    # -- lifecycle ----------------------------------------------------------
    def warmup(self):
        """Run one zero batch through every bucket so every XLA
        specialization compiles BEFORE traffic arrives (needs
        ``feature_shapes``, given or learned)."""
        if self._feature_shapes is None:
            raise ValueError(
                "warmup needs feature_shapes (pass them to the engine "
                "or serve one request first)")
        for b in self._buckets:
            feed = [np.zeros((b,) + s, self._dtype)
                    for s in self._feature_shapes]
            self._forward(b, feed)
            if b not in self._warmed:
                self._warmed.append(b)
        _telemetry.journal_event("serve.warmup",
                                 buckets=list(self._buckets))
        # HBM watermark with every bucket specialization resident —
        # the serving steady-state footprint (boundary-only sample)
        from .. import profiler as _profiler
        _profiler.sample_device_memory("serve.warmup")

    def _request_drain(self):
        # called from the signal handler: set-a-flag only (the batcher
        # polls with bounded waits; no lock may be touched here)
        self._draining = True

    @property
    def draining(self):
        return self._draining or self._closed

    def close(self, timeout=30.0):
        """Graceful drain: admitted requests finish, new submissions
        raise EngineClosed, then the batcher thread exits."""
        with self._cond:
            already = self._closed
            self._draining = True
            pending = len(self._queue)
            self._cond.notify_all()
        if not already:
            _telemetry.journal_event("serve.drain", pending=pending)
        self._thread.join(timeout)
        if self._shutdown is not None:
            self._shutdown.uninstall()
            self._shutdown = None
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    @property
    def in_flight(self):
        """Admitted requests not yet resolved (queued or mid-batch) —
        the figure a drain-aware router watches reach zero before it
        recycles this replica (serve/router.py)."""
        return (self._admitted - self._completed - self._timeouts
                - self._failed)

    def stats(self):
        """This engine's own counters (the registry aggregates across
        engines; these don't)."""
        return {"admitted": self._admitted, "shed": self._shed,
                "timeouts": self._timeouts, "forwards": self._forwards,
                "completed": self._completed,
                "mean_fill": (self._fill_sum / self._forwards
                              if self._forwards else None),
                "queued": len(self._queue)}

    @property
    def warmed_buckets(self):
        """Buckets whose XLA specialization warmup() pre-compiled."""
        return list(self._warmed)

    def introspect(self):
        """Live engine state for the ``stats`` introspection frame
        (serve/net.py): queue depth, drain state, bucket config and
        which buckets are warmed, on top of :meth:`stats`."""
        out = self.stats()
        out["queue_depth"] = out.pop("queued")
        out["in_flight"] = self.in_flight
        out["draining"] = self.draining
        out["buckets"] = list(self._buckets)
        out["warmed"] = self.warmed_buckets
        out["model_id"] = self.model_id
        return out

    # -- AOT deploy chain ---------------------------------------------------
    @classmethod
    def from_export(cls, prefix, **kwargs):
        """Serve a :meth:`Predictor.export_buckets` artifact set: loads
        one CompiledPredictor per bucket (prefix.b<K>.stablehlo) by the
        prefix.serve.json manifest — the headless deployment target
        (no symbol source, no op registry, no parameter files)."""
        import json

        from ..predictor import CompiledPredictor
        with open(prefix + ".serve.json") as f:
            manifest = json.load(f)
        models = {int(b): CompiledPredictor.load("%s.b%d" % (prefix, b))
                  for b in manifest["buckets"]}
        kwargs.setdefault("feature_shapes",
                          [tuple(s) for s in
                           manifest["feature_shapes"]])
        kwargs.setdefault("dtype", manifest.get("dtype", "float32"))
        engine = cls(models, **kwargs)
        engine.model_id = manifest.get("model_id")
        return engine
