"""Inference serving engine — dynamic batching, continuous decode,
backpressure (docs/serving.md).

Three layers, each usable alone:

* :class:`ServeEngine` (``engine.py``) — in-process dynamic batching
  over any forward-capable deploy artifact (``Predictor``, the
  bucketed AOT export, or a custom wrapper): bounded queue, bucketed
  coalescing, typed backpressure, graceful drain, full telemetry.
* :class:`ContinuousDecoder` (``decode.py``) — continuous-batching
  token generation for the transformer ``Generator``: a fixed slot
  pool over the on-device KV cache where finished sequences free their
  slot and queued prompts are admitted the following step.
* :class:`ServeServer` / :class:`ServeClient` (``net.py``) — a thin
  TCP front end on the async-PS wire plumbing, so the
  ``MXNET_FAULT_SPEC`` fault grammar tests the serving path unchanged.
* :class:`ServeRouter` (``router.py``) — one endpoint over N
  replicas: least-loaded dispatch, decode session affinity,
  shed-and-retry, zero-drop rolling restarts. Speaks the same wire on
  both sides (``ServeServer(router)`` fronts it; ``ServeClient``s fan
  out), so clients cannot tell a router from a replica.
* :class:`PrefillEngine` (``prefill.py``) — the prefill half of
  prefill/decode disaggregation: answers the ``prefill`` wire frame
  with ``{first_token, kv_blob, pos}``; the router fans generate
  requests prefill-replica → decode-replica with the KV blob shipped
  in the admit (``ContinuousDecoder.submit(handoff=...)``).
* :class:`FleetController` (``controller.py``) — the fleet operates
  itself: health-gated autoscaling against a declared capacity
  policy, self-healing of probe-confirmed-dead replicas, and rolling
  model rollout with automatic rollback, all journaled crash-safe.

Raw ``socket`` use is confined to ``net.py`` by the
``tools/serve_smoke.sh`` lint (router.py included) — everything else
in this package is transport-free by construction.
"""
from .controller import FleetController, RolloutResult
from .decode import ContinuousDecoder, DecodeFuture
from .engine import (EngineClosed, Overloaded, RequestTimeout,
                     ServeEngine, ServeError, ServeFuture,
                     SessionEvacuated)
from .net import ServeClient, ServeServer
from .prefill import PrefillEngine
from .router import ReplicaState, ServeRouter

__all__ = ["ServeEngine", "ServeFuture", "ServeError", "Overloaded",
           "RequestTimeout", "EngineClosed", "SessionEvacuated",
           "ContinuousDecoder", "DecodeFuture", "PrefillEngine",
           "ServeClient", "ServeServer", "ServeRouter",
           "ReplicaState", "FleetController", "RolloutResult"]
