"""Continuous-batching decode for the transformer ``Generator``
(docs/serving.md §continuous decode).

Static-batch generation dies with its slowest sequence: a (B,) batch
holds every slot until the LAST row finishes, so mean device
utilization decays toward 1/B as lengths diverge. Continuous batching
(the O(1)-per-token cached-decode serving model, arXiv:2603.09555)
fixes the shape instead of the membership: a fixed slot pool over the
on-device decode state — per-slot KV-cache rows for attention blocks,
a constant (H, hd, hd) recurrent blob for ``block_type="ssm"`` layers
— where a finished sequence frees its slot at the step it finishes
and the next queued prompt is admitted at the following step. Decode
throughput then tracks offered load, not the longest request in
flight.

What makes the single compiled step possible is the per-row-position
decode graph (``get_decode_symbol(per_row_pos=True)`` →
``cached_attention`` — or ``cached_attention_q8`` under
``quantize_kv`` — with a (B,) ``pos``): every slot decodes at its
own depth inside ONE (B, 1) XLA program, so slot membership changes
never recompile. SSM layers need no twin at all — the recurrent
state carries its own position, so their per-row graph IS the shared
graph and the same one-program discipline holds for free. Prompt
admission reuses the Generator's ordinary shared-position prefill
(all admitted rows start at position 0) and merges the prefilled
state into the pool with a batch-axis scatter — under ``quantize_kv``
that merge carries the per-token f32 scale caches alongside the int8
rows, and SSM state blobs ride the same scatter with no length axis.

Decode is bandwidth-bound and the per-slot state is its dominant HBM
stream (re-read every step; each weight read once), so shrinking that
state directly raises how many slots fit a chip: an int8 cache
(``Generator(quantize_kv=True)``) roughly halves an attention slot's
bytes, and an SSM slot pins a CONSTANT byte count independent of
``max_len`` entirely. The ``serve.decode.kv_bytes_per_slot`` gauge
(state-agnostic despite the legacy name —
``Generator.state_bytes_per_slot()``) and :meth:`describe` /
``MXNET_DECODE_SLOTS=auto`` report the sizing math.

Exactness contract: greedy decode (temperature 0) emits token-for-token
what ``Generator.generate`` emits for the same prompt — the per-row
graph computes the same per-row math and rows are independent (pinned
in tests/test_serve_decode.py). Sampled requests are reproducible per
request (each carries its own PRNG stream keyed by ``seed``, split once
per emitted token exactly like ``generate``'s loop) and match a
``batch_size=1`` ``Generator.generate(seed=...)``, but not a
multi-row static batch — ``jax.random.categorical`` draws one noise
tensor per CALL, so row b of a (B, V) batch and the same logits alone
see different noise.

Because every piece of a mid-decode sequence is either portable
(cache rows via ``export_kv_rows``) or derivable (PRNG progress =
``len(emitted)`` splits — ``generation.replay_key``), an active
session survives its replica: :meth:`export_session` packages one
slot's full recovery state, ``submit(resume=...)`` readmits it on
another pool at its own depth, and :meth:`evacuate` (also wired to
SIGTERM via ``install_sigterm=True``) exports every active slot at
once so a migrating recycle is bounded by export+import cost instead
of longest-sequence drain (docs/robustness.md, fleet failure
semantics).

Speculative decoding (docs/serving.md §speculative): an optional
draft Generator (``draft=`` or ``MXNET_SPEC_DRAFT``) gives the pool a
second, smaller model sharing the slot shape. When any live slot
opted in (``submit(speculative=True)``), a loop iteration becomes a
ROUND: γ compiled (B, 1) draft steps propose tokens per slot, then
ONE (B, γ+1) target verify forward scores them all — with PER-ROW
acceptance (each slot keeps its own longest-matching prefix, unlike
the eager path's lockstep rule) and per-row position bookkeeping, so
rejected speculative cache entries are simply overwritten in place
and never attended. Verification is common-random-numbers exact: the
emission at index j is always ``_pick_token(target_logits_j, sub_j)``
with ``sub_j`` the request stream's (j+1)-th split, and the draft
proposes with the SAME sub — so output is byte-identical to plain
``generate``/non-speculative serving for the same (seed, prompt,
sampling args), which keeps failover replay and the dedup contract
token-exact. ``speculative`` is therefore a pure performance hint: a
draft-less replica admits the same request down the ordinary (B, 1)
path with identical output. Every emission funnels through
:meth:`_emit` one token at a time, so TTFT/inter-token metrics,
streamed frames and mid-stream failover cursors work unchanged.
"""
from __future__ import annotations

import logging
import signal
import threading
from collections import OrderedDict, deque

import numpy as np

import jax
import jax.numpy as jnp

from .. import config as _config
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..executor import _graph_eval_fn
from ..generation import _pick_token, replay_key
from ..models import transformer
from .engine import (EngineClosed, Overloaded, RequestTimeout,
                     SessionEvacuated)

__all__ = ["ContinuousDecoder", "DecodeFuture", "drain_timeout",
           "prefill_chunk", "spec_draft"]

# replay dedup (PR 1's (cid, seq) pattern on the serving side): how
# many admit ids a decode replica remembers. Sized far past any
# plausible in-flight window — eviction is LRU, and evicting an id
# that could still be replayed would re-open the double-admit hole,
# so the cap exists only to bound memory over a replica's lifetime.
_DEDUP_CAP = 4096


def drain_timeout():
    """``MXNET_DECODE_DRAIN_TIMEOUT``, loudly validated: the drain
    budget for a decode replica — :meth:`ContinuousDecoder.close`
    waits this long for admitted sequences to finish, and the fleet
    router's :meth:`~mxnet_tpu.serve.ServeRouter.recycle` of a
    replica whose hello declared ``role: decode`` budgets its drain
    from the SAME knob (one drain clock; the router knob keeps
    covering every other role)."""
    import math
    t = float(_config.get("MXNET_DECODE_DRAIN_TIMEOUT"))
    if not (math.isfinite(t) and t > 0):
        raise ValueError(
            "MXNET_DECODE_DRAIN_TIMEOUT=%r: wants a positive finite "
            "number of seconds (a non-positive or non-finite drain "
            "budget would wedge or skip the drain silently)" % (t,))
    return t


def prefill_chunk():
    """``MXNET_PREFILL_CHUNK``, loudly validated: the colocated
    chunked-prefill width in tokens (0 = off, whole-prompt prefill).
    Read per admission round so tests and live reconfigures take
    effect without rebuilding the pool."""
    c = int(_config.get("MXNET_PREFILL_CHUNK") or 0)
    if c < 0:
        raise ValueError(
            "MXNET_PREFILL_CHUNK=%r: wants a non-negative chunk width "
            "in tokens (0 disables chunking)" % (c,))
    return c


def spec_draft():
    """``MXNET_SPEC_DRAFT``, loudly validated: the serving fleet's
    zero-config speculative draft. ``'layers=<d>[,gamma=<g>]'`` makes
    every :class:`ContinuousDecoder` built WITHOUT an explicit
    ``draft=`` attach ``generator.truncated_draft(num_layers=<d>)``
    and verify ``<g>`` proposals per round (default 4) — subprocess
    replicas (the chaos harness's children, spawned fleets) opt in
    through the environment with zero code changes. Empty = no draft.
    Returns ``(layers, gamma)`` or ``None``."""
    raw = str(_config.get("MXNET_SPEC_DRAFT") or "").strip()
    if not raw:
        return None
    layers, gamma = None, 4
    for part in raw.split(","):
        if "=" not in part:
            raise ValueError(
                "MXNET_SPEC_DRAFT=%r: wants 'layers=<d>[,gamma=<g>]' "
                "(got the fieldless part %r)" % (raw, part))
        k, v = (s.strip() for s in part.split("=", 1))
        try:
            val = int(v)
        except ValueError:
            raise ValueError(
                "MXNET_SPEC_DRAFT=%r: %s wants an integer, got %r"
                % (raw, k, v)) from None
        if k == "layers":
            layers = val
        elif k == "gamma":
            gamma = val
        else:
            raise ValueError(
                "MXNET_SPEC_DRAFT=%r: unknown field %r (supported: "
                "layers, gamma)" % (raw, k))
    if layers is None or layers < 1:
        raise ValueError(
            "MXNET_SPEC_DRAFT=%r: wants layers >= 1 (the draft must "
            "run at least one block)" % (raw,))
    if gamma < 1:
        raise ValueError(
            "MXNET_SPEC_DRAFT=%r: wants gamma >= 1 (a round must "
            "propose at least one token)" % (raw,))
    return layers, gamma


class DecodeFuture:
    """One sequence's pending result: the full token row
    (prompt + generated, eos included when hit) or a typed error.

    Streaming consumers :meth:`subscribe` a sink to see every emitted
    token as the decode loop picks it (plus a ``None`` sentinel when
    the sequence settles) — the engine half of the serve path's
    streamed generate frames."""

    __slots__ = ("prompt", "max_new", "eos_id", "temperature", "top_k",
                 "top_p", "seed", "_key", "t_enq", "t_admit", "t_last",
                 "tc", "emitted", "pending", "n_cached", "handoff",
                 "resume", "speculative", "_ev", "_value", "_exc",
                 "_slock", "_sinks")

    def __init__(self, prompt, max_new, eos_id, temperature, top_k,
                 top_p, seed, handoff=None, speculative=False):
        self.prompt = prompt               # (P,) int64
        self.max_new = max_new
        self.eos_id = eos_id
        self.temperature = float(temperature or 0.0)
        self.top_k = top_k
        self.top_p = top_p
        self.seed = int(seed or 0)         # kept for export_session
        # one PRNG stream per request, split once per emitted token —
        # the exact key discipline of Generator.generate's loop, so a
        # sampled request reproduces independently of what else shares
        # the pool
        self._key = jax.random.PRNGKey(seed) \
            if self.temperature > 0 else None
        self.handoff = handoff             # remote-prefill admit state
        self.resume = None                 # migrated-session admit state
        self.speculative = bool(speculative)   # performance HINT only
        if handoff is not None and self._key is not None:
            # the remote prefill consumed the stream's FIRST split for
            # the first token it ships — advance past it so local
            # picks continue the exact generate() key discipline
            self._key, _ = jax.random.split(self._key)
        self.t_enq = _telemetry.now_ms()
        self.t_admit = None                # set when a slot is claimed
        self.t_last = None                 # last emission (inter-token)
        self.tc = _trace.current_context()  # submitter's span, if any
        self.emitted = []
        self.pending = None                # sampled but not yet fed
        self.n_cached = 0
        self._ev = threading.Event()
        self._value = None
        self._exc = None
        self._slock = threading.Lock()     # emitted/sink consistency
        self._sinks = []                   # streaming subscribers

    def _pick(self, row_logits):
        """Next token id from this row's last-position logits."""
        if self.temperature > 0:
            self._key, sub = jax.random.split(self._key)
            return int(np.asarray(_pick_token(
                row_logits[None], self.temperature, self.top_k, sub,
                self.top_p))[0])
        return int(np.argmax(np.asarray(row_logits)))

    def _peek_subs(self, k):
        """The next ``k`` sampling subs WITHOUT advancing the stream —
        the speculative draft proposes with the SAME noise the verify
        pick will use (common random numbers), and the stream itself
        only advances per EMITTED token (via :meth:`_pick`), so the
        key discipline stays exactly ``generate``'s whatever mix of
        proposals gets accepted."""
        key, subs = self._key, []
        for _ in range(k):
            key, sub = jax.random.split(key)
            subs.append(sub)
        return subs

    def subscribe(self, sink):
        """Register a token sink: it is first fed every
        already-emitted token in order (the replayed prefix a deduped
        or resumed streaming attempt owes its client), then each new
        token as the loop emits it, then ``None`` once the sequence
        settles (result or error). Delivery holds the emission lock,
        so a sink sees the stream exactly once, in order, with no gap
        between the prefix replay and live emissions — sinks must be
        cheap and non-blocking (a queue put)."""
        with self._slock:
            for t in self.emitted:
                sink(t)
            if self._ev.is_set():
                sink(None)
            else:
                self._sinks.append(sink)

    def unsubscribe(self, sink):
        with self._slock:
            try:
                self._sinks.remove(sink)
            except ValueError:
                pass

    def _emit(self, tok):
        """One emission: append + notify streaming sinks atomically
        (decode loop thread only)."""
        with self._slock:
            self.emitted.append(tok)
            for s in self._sinks:
                s(tok)
        self.pending = tok

    def _settle_sinks(self):
        with self._slock:
            self._ev.set()
            sinks, self._sinks = self._sinks, []
        for s in sinks:
            s(None)

    def _finish_ok(self):
        self._value = np.concatenate(
            [self.prompt, np.asarray(self.emitted, np.int64)])
        self._settle_sinks()

    def _fail(self, exc):
        self._exc = exc
        self._settle_sinks()

    def done(self):
        return self._ev.is_set()

    def result(self, timeout=None):
        if not self._ev.wait(timeout):
            raise RequestTimeout(
                "sequence still decoding after %.3fs" % timeout)
        if self._exc is not None:
            raise self._exc
        return self._value


class ContinuousDecoder:
    """Fixed-slot continuous batching over a Generator's decode state
    (KV caches for attention blocks, O(1) recurrent blobs for ssm
    blocks, both side by side in a mixed stack).

    The pool width is the Generator's ``batch_size``; its ``max_len``
    caps prompt + max_new_tokens per request. Requests queue FIFO
    (bounded by ``queue_cap`` → typed ``Overloaded``); ``close()``
    drains: admitted sequences finish, new submissions raise
    ``EngineClosed``.

    Int8 KV caches (``Generator(quantize_kv=True)``) are supported:
    the per-row op scatters the int8 rows and their per-token f32
    scale rows at each slot's own depth, halving cache bytes per slot.
    SSM blocks (``block_type="ssm"``) are supported: each slot's state
    is a constant-size blob, so a slot costs the same HBM at any
    depth. Not supported: rolling caches (the circular-buffer op has
    no per-row-position variant) and speculative drafts with ssm
    blocks (no per-position state to roll back) — both raised at
    construction here, not mid-request.

    Disaggregated serving (docs/serving.md §disaggregated prefill):
    ``submit(handoff=...)`` admits a sequence whose prefill ran on a
    REMOTE prefill replica — the shipped cache rows scatter into the
    slot (:meth:`import_kv_rows`) and admission runs zero prefill
    graph calls; the ``role`` attribute is what the fleet router's
    hello frame reads to learn this replica decodes."""

    role = "decode"                       # the hello frame's identity

    def __init__(self, generator, queue_cap=64, logger=None,
                 install_sigterm=False, draft=None, lookahead=None):
        if getattr(generator, "_rolling", False):
            raise ValueError(
                "continuous batching does not support rolling caches "
                "(the circular-buffer op has no per-row-position "
                "variant; quantize_kv int8 caches ARE supported — "
                "drop rolling_cache and size max_len to prompt + "
                "max_new_tokens instead)")
        self._gen = generator
        self._B = int(generator.batch_size)
        self._log = logger or logging.getLogger(__name__)
        self._cap = int(queue_cap)

        # the per-row-position twin of the generator's decode graph —
        # same parameter names, so the generator's own (placed, maybe
        # quantized) param dict binds unchanged
        opts = dict(generator._decode_opts, per_row_pos=True)
        sym_p = transformer.get_decode_symbol(**opts)
        if sym_p.list_arguments() != generator._sym.list_arguments():
            # checkpoint-binding contract: both variants must bind the
            # same parameter names (a bare assert would vanish under -O)
            raise ValueError(
                "per-row decode symbol drifted from the scalar twin: "
                "%r vs %r" % (sym_p.list_arguments(),
                              generator._sym.list_arguments()))
        eval_fn = _graph_eval_fn(sym_p, mesh=generator.mesh)
        self._step_fn = jax.jit(
            lambda args, aux, rng: eval_fn(args, aux, rng, False))
        self._rng0 = jax.random.PRNGKey(0)

        self._aux = generator._fresh_aux()     # the pool caches
        self._import_jit = {}                  # pos -> fused scatter

        # -- speculative decoding (docs/serving.md §speculative) --
        # draft=None consults MXNET_SPEC_DRAFT so subprocess replicas
        # opt whole fleets in through the environment; an explicit
        # draft= (any Generator sharing vocab + slot-pool width) wins
        if draft is None:
            cfg = spec_draft()
            if cfg is not None:
                layers, env_gamma = cfg
                draft = generator.truncated_draft(num_layers=layers)
                if lookahead is None:
                    lookahead = env_gamma
        self._draft = draft
        self._gamma = max(1, int(lookahead)) if lookahead else 4
        if draft is not None:
            if getattr(generator, "_has_ssm", False) or \
                    getattr(draft, "_has_ssm", False):
                # the env path (MXNET_SPEC_DRAFT -> truncated_draft)
                # already refused above; this catches an explicit
                # draft= with ssm blocks on either side
                raise ValueError(
                    "speculative decoding is not supported with ssm "
                    "blocks: the recurrent state has no per-position "
                    "entries to overwrite, so rejected proposals "
                    "would corrupt it (serve SSM models without a "
                    "draft, or use attention blocks for speculative "
                    "serving)")
            if draft.vocab_size != generator.vocab_size or \
                    draft.batch_size != generator.batch_size:
                raise ValueError(
                    "speculative draft must share vocab_size/"
                    "batch_size with the target (draft %d/%d vs "
                    "target %d/%d) — the draft decodes the same slot "
                    "pool" % (draft.vocab_size, draft.batch_size,
                              generator.vocab_size,
                              generator.batch_size))
            if getattr(draft, "_rolling", False):
                raise ValueError(
                    "speculative draft must not use a rolling cache "
                    "(rejected entries could alias older positions)")
            # the draft's own per-row-position twin: γ (B, 1) propose
            # steps per round, ONE compiled program across slot
            # turnover — same discipline as the target step
            d_opts = dict(draft._decode_opts, per_row_pos=True)
            d_sym = transformer.get_decode_symbol(**d_opts)
            if d_sym.list_arguments() != draft._sym.list_arguments():
                raise ValueError(
                    "per-row draft symbol drifted from the scalar "
                    "twin: %r vs %r" % (d_sym.list_arguments(),
                                        draft._sym.list_arguments()))
            d_eval = _graph_eval_fn(d_sym, mesh=draft.mesh)
            self._draft_step_fn = jax.jit(
                lambda args, aux, rng: d_eval(args, aux, rng, False))
            self._daux = draft._fresh_aux()    # the draft's pool caches
            # verify rounds write up to γ speculative entries past a
            # row's live depth (on BOTH pools: the target's verify
            # chunk and the draft's propose steps), so every admission
            # needs γ headroom while a draft is attached — enforced
            # pool-wide in submit() because non-speculative rows ride
            # the same verify forward with junk tails
            self._spec_cap = min(int(generator.max_len),
                                 int(draft.max_len)) - self._gamma
            if self._spec_cap < 2:
                raise ValueError(
                    "lookahead %d leaves no speculative headroom at "
                    "min(target max_len=%d, draft max_len=%d) — grow "
                    "max_len or shrink lookahead"
                    % (self._gamma, generator.max_len, draft.max_len))
        else:
            self._draft_step_fn = None
            self._daux = None
            self._spec_cap = None
        self._slots = [None] * self._B         # DecodeFuture per slot
        self._reserved = set()                 # slots held mid-chunk
        self._chunking = None                  # in-progress chunked prefill
        self._queue = deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._draining = False
        self._closed = False
        # replay dedup (admit id -> the admission's own future): a
        # fleet-router replay after a transient fault returns the
        # ORIGINAL admission instead of double-admitting
        self._dedup = OrderedDict()
        self._evac_waiters = []                # (Event, [result]) pairs
        self._evac_flag = False                # SIGTERM handler sets

        self._admitted = 0
        self._finished = 0
        self._shed = 0
        self._steps = 0
        self._prefills = 0
        self._imported = 0
        self._resumed = 0
        self._evacuated = 0
        self._deduped = 0
        self._streams = 0
        self._streams_inflight = 0
        self._g_active = _telemetry.gauge("serve.decode.active_slots")
        # pool-measured twin of the Generator's static sizing gauge:
        # actual device-array bytes of the live cache pytree per slot.
        # Re-published every step (the gauge is last-write-wins and
        # any OTHER Generator construction — a speculative draft, a
        # second model — overwrites it with ITS static figure; the
        # live pool must win while it is serving)
        self._kv_bytes_per_slot = sum(
            int(v.nbytes) for v in self._aux.values()) // self._B
        self._g_kv = _telemetry.gauge("serve.decode.kv_bytes_per_slot")
        self._g_kv.set(self._kv_bytes_per_slot)
        # one compiled (B, 1) executable across slot turnover is THE
        # property continuous batching exists for; with a speculative
        # draft the target owns exactly TWO programs — the (B, 1) step
        # plus the (B, γ+1) verify — and never more. The gauge feeds
        # the decode/decode_q8/spec_decode perf-gate fingerprints
        self._g_jit = _telemetry.gauge("serve.decode.jit_cache_size")
        self._h_slotfill = _telemetry.histogram(
            "serve.decode.slot_fill", buckets=_telemetry.COUNT_BUCKETS)
        self._h_req = _telemetry.histogram("serve.decode.request_ms")
        self._c_admitted = _telemetry.counter("serve.decode.admitted")
        self._c_finished = _telemetry.counter("serve.decode.finished")
        self._c_steps = _telemetry.counter("serve.decode.steps")
        self._c_imported = _telemetry.counter("serve.decode.imported")
        self._h_import = _telemetry.histogram("serve.decode.import_ms")
        self._c_resumed = _telemetry.counter("serve.decode.resumed")
        self._c_evacuated = _telemetry.counter("serve.decode.evacuated")
        self._c_deduped = _telemetry.counter("serve.decode.deduped")
        # interactive-latency product metrics (PR 17): time to first
        # emitted token (from enqueue) and the gap between consecutive
        # emissions of one sequence — what streaming users actually
        # feel; tools/telemetry_report.py renders the quantiles
        self._h_ttft = _telemetry.histogram("serve.ttft_ms")
        self._h_itl = _telemetry.histogram("serve.inter_token_ms")
        self._c_streams = _telemetry.counter("serve.decode.streams")
        self._g_streams = _telemetry.gauge(
            "serve.decode.streams_active")
        self._c_chunks = _telemetry.counter(
            "serve.decode.prefill_chunks")

        # speculative accounting: instance ints always (stats() deltas
        # for benches), but the serve.spec.* telemetry series register
        # ONLY when a draft is attached — a draft-less pool must leave
        # the global snapshot exactly as before (perf-gate baselines
        # fingerprint every counter in it)
        self._spec_rounds = 0
        self._draft_steps = 0
        self._verify_steps = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._draft_prefills = 0
        if self._draft is not None:
            self._c_srounds = _telemetry.counter("serve.spec.rounds")
            self._c_dsteps = _telemetry.counter(
                "serve.spec.draft_steps")
            self._c_vsteps = _telemetry.counter(
                "serve.spec.verify_steps")
            self._c_proposed = _telemetry.counter(
                "serve.spec.proposed")
            self._c_accepted = _telemetry.counter(
                "serve.spec.accepted")
            self._c_dprefills = _telemetry.counter(
                "serve.spec.draft_prefills")
            # per-round per-row accepted/γ in [0, 1]; eighths resolve
            # the useful range at any lookahead <= 8
            self._h_accept = _telemetry.histogram(
                "serve.spec.accept_rate",
                buckets=tuple((i + 1) / 8 for i in range(8)))
            # one compiled (B, 1) draft propose program across slot
            # turnover — the draft half of the jit-cache discipline
            # (the target's gauge covers its step + verify pair)
            self._g_djit = _telemetry.gauge(
                "serve.spec.draft_jit_cache_size")

        self._shutdown = None
        if install_sigterm:
            from .. import guardrail as _guardrail
            self._shutdown = _guardrail.GracefulShutdown(
                signals=(signal.SIGTERM,), logger=self._log,
                on_request=self._request_evacuate,
                action="decode pool evacuating (active sessions "
                       "export for migration, then the pool drains)"
            ).install()

        slots_hint = str(_config.get("MXNET_DECODE_SLOTS") or "")
        if slots_hint and not slots_hint.startswith("auto"):
            raise ValueError(
                "MXNET_DECODE_SLOTS=%r: supported forms are '' (off), "
                "'auto' (report against the device HBM limit) or "
                "'auto:<bytes>' — the pool width itself is the "
                "Generator's batch_size, not this knob" % (slots_hint,))
        if slots_hint:
            budget = None
            if ":" in slots_hint:
                raw = slots_hint.split(":", 1)[1]
                try:
                    budget = float(raw)
                except ValueError:
                    budget = float("nan")
                import math
                if not (math.isfinite(budget) and budget > 0):
                    raise ValueError(
                        "MXNET_DECODE_SLOTS=%r: the budget after "
                        "'auto:' must be a positive finite number of "
                        "bytes (e.g. auto:16e9), got %r"
                        % (slots_hint, raw))
            self._log.info("decode slot sizing\n%s",
                           self.describe(hbm_budget=budget))

        self._thread = threading.Thread(
            target=self._loop, name="mxnet-serve-decode", daemon=True)
        self._thread.start()

    def describe(self, hbm_budget=None):
        """SpecLayout.describe()-style sizing report: pool geometry,
        state bytes per slot (KV rows — int8 + f32 scale rows under
        quantize_kv — and/or fixed-size SSM state blobs), and — given
        an HBM budget in bytes — how many slots would fit at the
        configured max_len. hbm_budget=None tries the device's
        reported bytes_limit (``MXNET_DECODE_SLOTS=auto:<bytes>``
        passes one explicitly). The budget math covers per-slot
        decode state only; weights and activations claim their share
        of HBM on top."""
        gen = self._gen
        bps = self._kv_bytes_per_slot
        kinds = []
        if any(not n.endswith("_state") for n in self._aux):
            kind = "int8 + f32 per-token scales" if gen._quantize_kv \
                else str(jnp.dtype(gen._cache_dtype))
            kinds.append("KV rows %s (%s)" % (
                "x".join(str(d) for d in gen._cache_shape[1:]), kind))
        if any(n.endswith("_state") for n in self._aux):
            kinds.append("ssm state %s (float32, O(1) in max_len)" % (
                "x".join(str(d) for d in gen._state_shape[1:])))
        lines = [
            "ContinuousDecoder pool: %d slot(s), max_len=%d, "
            "%d layer(s)" % (self._B, gen.max_len,
                             gen.num_layers),
            "  per-slot state: %s" % "; ".join(kinds),
            "  kv_bytes_per_slot: %d (%.2f MiB)  pool total: %.2f MiB"
            % (bps, bps / 2 ** 20, bps * self._B / 2 ** 20),
        ]
        if hbm_budget is None:
            try:
                stats = jax.local_devices()[0].memory_stats() or {}
                hbm_budget = float(stats.get("bytes_limit") or 0) \
                    or None
            except Exception:  # noqa: BLE001 — backends may not report
                hbm_budget = None
        if hbm_budget:
            fit = int(hbm_budget // bps) if bps else 0
            lines.append(
                "  HBM budget %.2f GiB -> %d slot(s) fit at "
                "max_len=%d (cache bytes only; weights/activations "
                "not counted)" % (hbm_budget / 2 ** 30, fit,
                                  gen.max_len))
        else:
            lines.append(
                "  no HBM budget known (backend reports no "
                "bytes_limit) — set MXNET_DECODE_SLOTS=auto:<bytes>")
        return "\n".join(lines)

    # -- admission ----------------------------------------------------------
    def _check_blob(self, blob, want_pos=None,
                    why="the handoff must ship exactly the prompt's "
                        "prefill state"):
        """Loud structural validation of a handoff/resume blob BEFORE
        it is queued: names/shapes/dtypes must match this pool's own
        cache spec exactly (a blob from a mismatched generator — wrong
        architecture, wrong quantize_kv, wrong dtype — would scatter
        silently-wrong state; device-roundtrip exactness starts with
        refusing anything that isn't bit-compatible). ``want_pos``:
        the cached depth the blob must cover exactly — the prompt
        length for a handoff, prompt + fed tokens for a migrated
        session (None = trust the blob's own ``pos`` — the bare
        import_kv_rows surface)."""
        if not isinstance(blob, dict) or blob.get("v") != 1:
            raise ValueError("kv_blob is not an export_kv_rows v1 "
                             "blob: %r" % (type(blob).__name__,))
        pos = int(blob.get("pos", 0))
        if not 1 <= pos <= self._gen.max_len:
            raise ValueError(
                "kv_blob pos %d out of range for max_len=%d"
                % (pos, self._gen.max_len))
        if want_pos is not None and pos != want_pos:
            raise ValueError(
                "kv_blob covers %d cached token(s) but the admission "
                "expects %d — %s" % (pos, want_pos, why))
        rows = blob.get("rows") or {}
        if set(rows) != set(self._aux):
            raise ValueError(
                "kv_blob caches %s do not match this pool's %s"
                % (sorted(rows), sorted(self._aux)))
        for name, arr in rows.items():
            _, dtype = self._gen._aux_spec(name)
            want = self._gen._aux_row_shape(name, pos)
            if np.asarray(arr).dtype != dtype or arr.shape != want:
                raise ValueError(
                    "kv_blob cache %r is %s%r, expected %s%r — blob "
                    "and pool generators disagree (architecture, "
                    "dtype or quantize_kv mismatch)"
                    % (name, np.asarray(arr).dtype, arr.shape, dtype,
                       want))
        return pos

    def import_kv_rows(self, slot, blob):
        """Scatter one exported sequence's decode state into ``slot``
        — the decode half of the handoff, exact to the bit vs the
        prefill device's own state. For KV caches only the blob's
        ``pos``-token prefix lands; stale entries past it in the slot
        are never attended (the per-row cache-position mask). SSM
        state blobs have no length axis and land whole — the same
        O(1) bytes at any ``pos``. Called by the decode loop during
        handoff admission; external callers must own a quiescent pool
        (the loop thread is the aux mutator)."""
        slot = int(slot)
        if not 0 <= slot < self._B:
            raise ValueError("slot %d out of range for %d-slot pool"
                             % (slot, self._B))
        pos = self._check_blob(blob)
        t0 = _telemetry.now_ms()
        # ONE fused scatter program per pos (slot rides as a traced
        # scalar; the pool aux is donated so the update is in place,
        # not a whole-pool copy) — a separate jit from the (B, 1)
        # step, whose cache-size-1 gauge it never touches
        fn = self._import_jit.get(pos)
        if fn is None:
            def scatter(aux, rows, slot_):
                out = dict(aux)
                for name, r in rows.items():
                    start = (slot_,) + (0,) * (r.ndim)
                    out[name] = jax.lax.dynamic_update_slice(
                        aux[name], r[None], start)
                return out
            fn = jax.jit(scatter, donate_argnums=0)
            self._import_jit[pos] = fn
        self._aux = fn(self._aux,
                       {n: jnp.asarray(a)
                        for n, a in blob["rows"].items()},
                       jnp.int32(slot))
        # block before timing: JAX dispatch is async, and an import_ms
        # that records dispatch-only would read ~0 while the real
        # scatter cost silently lands on the next (B, 1) step — the
        # histogram exists to budget the decode side of the handoff
        jax.block_until_ready(self._aux)
        ms = _telemetry.now_ms() - t0
        self._imported += 1
        self._c_imported.inc()
        self._h_import.observe(ms)
        return pos

    def export_session(self, slot):
        """The portable mid-decode state of one active slot — every
        piece a survivor needs to continue the sequence bit-exactly:
        the cache rows at ``pos = prompt + fed`` (device-exact, via
        the Generator's ``export_kv_rows``), the full request
        contract (prompt, sampling opts, seed), the emitted tokens
        and the pending not-yet-fed one. PRNG progress ships as
        DERIVED state — the stream splits once per drawn token, so
        ``submit(resume=...)`` re-derives the key by advancing
        ``len(emitted)`` splits (``generation.replay_key``) instead
        of trusting a shipped key. Callers outside the decode loop
        must own a quiescent pool (the loop thread is the aux
        mutator; :meth:`evacuate` runs this ON the loop thread)."""
        slot = int(slot)
        if not 0 <= slot < self._B:
            raise ValueError("slot %d out of range for %d-slot pool"
                             % (slot, self._B))
        req = self._slots[slot]
        if req is None:
            raise ValueError("slot %d holds no active sequence" % slot)
        blob = self._gen.export_kv_rows(self._aux, slot, req.n_cached)
        return {"v": 1,
                "prompt": np.asarray(req.prompt, np.int64),
                "max_new_tokens": int(req.max_new),
                "eos_id": req.eos_id,
                "temperature": req.temperature,
                "top_k": req.top_k,
                "top_p": req.top_p,
                "seed": req.seed,
                "emitted": [int(t) for t in req.emitted],
                "pending": int(req.pending),
                # a HINT for the survivor, not identity: resume works
                # (byte-identically) whether or not it carries a draft
                "speculative": bool(req.speculative),
                "kv_blob": blob}

    def submit(self, prompt, max_new_tokens, eos_id=None,
               temperature=0.0, top_k=None, top_p=None, seed=0,
               handoff=None, admit_id=None, resume=None,
               speculative=False):
        """Queue one sequence; returns a :class:`DecodeFuture` whose
        result is the full (prompt + generated) id row, exactly as
        ``Generator.generate`` would emit it for this prompt alone.

        ``handoff``: a remote prefill's ``{"first_token", "kv_blob",
        "pos"}`` reply (the ``prefill`` wire frame / a
        :class:`PrefillEngine` return). Admission then scatters the
        shipped cache rows into the slot and emits the shipped first
        token — zero prefill graph calls on this replica (asserted by
        the ``prefills`` stat).

        ``admit_id``: opaque exactly-once token (the fleet router
        sends one per generate). A resubmission carrying an id this
        replica has already admitted returns the ORIGINAL admission's
        future — a failover replay after a transient transport fault
        can never double-admit onto a replica that actually survived.

        ``resume``: an :meth:`export_session` state dict — readmit a
        session migrated off another replica mid-decode. The request
        args must describe the SAME request (the router re-sends the
        originals); the state supplies the progress: emitted tokens,
        the pending not-yet-fed token, and the cache rows, which
        scatter at ``pos = prompt + fed`` with zero prefill graph
        calls. The PRNG stream re-derives its key by advancing
        ``len(emitted)`` splits (``generation.replay_key``), so the
        remaining tokens are bit-identical to an unmigrated run.

        ``speculative``: opt this request into draft/verify rounds
        when the pool carries a draft — a pure performance HINT, not
        part of the request's identity: output is byte-identical
        either way (common-random-numbers verification), so a
        draft-less replica — e.g. the failover survivor of a
        speculative session — admits the same request down the
        ordinary (B, 1) path, and a resume need not restate it."""
        self._gen._check_sampling(temperature, top_k, top_p)
        prefill_chunk()   # loud knob validation on the CALLER's
        #                   thread — the decode loop must never die
        #                   on a config typo
        prompt = np.asarray(prompt, np.int64).reshape(-1)
        P, n = int(prompt.shape[0]), int(max_new_tokens)
        if P < 1:
            raise ValueError("empty prompt")
        if handoff is not None and resume is not None:
            raise ValueError(
                "handoff and resume are mutually exclusive — a "
                "migrated session's state already contains its cache "
                "rows; the original handoff was consumed before the "
                "export")
        if handoff is not None:
            if not isinstance(handoff, dict) or \
                    "first_token" not in handoff or \
                    "kv_blob" not in handoff:
                raise ValueError(
                    "handoff wants the prefill frame's {'first_token',"
                    " 'kv_blob', 'pos'} dict, got %r"
                    % (type(handoff).__name__,))
            # structural blob validation happens HERE on the caller's
            # thread — a mismatched blob must fail the submission
            # loudly, never reach the decode loop
            self._check_blob(handoff["kv_blob"], P)
        emitted = None
        if resume is not None:
            if not isinstance(resume, dict) or resume.get("v") != 1 \
                    or "kv_blob" not in resume \
                    or "emitted" not in resume:
                raise ValueError(
                    "resume wants an export_session() state dict, "
                    "got %r" % (type(resume).__name__,))
            if not np.array_equal(
                    prompt, np.asarray(resume["prompt"],
                                       np.int64).reshape(-1)):
                raise ValueError(
                    "resume state is for a different prompt — the "
                    "request args and the migrated state must "
                    "describe the same generate")
            emitted = [int(t) for t in resume["emitted"]]
            if not emitted:
                raise ValueError(
                    "resume state carries no emitted tokens — a "
                    "session exports only after its first emission; "
                    "replay the request from scratch instead")
            if len(emitted) >= n:
                raise ValueError(
                    "resume state already holds %d emitted token(s) "
                    "of a max_new_tokens=%d request — nothing left "
                    "to decode" % (len(emitted), n))
            # the request args are authoritative, but they must
            # RESTATE the migrated request: a resume admitted under
            # different sampling opts would continue the stream
            # silently diverged from the donor (the PRNG key and the
            # pick discipline both derive from these args)
            for fld, have in (
                    ("temperature", float(temperature or 0.0)),
                    ("top_k", top_k), ("top_p", top_p),
                    ("seed", int(seed or 0))):
                theirs = resume.get(fld)
                if fld == "temperature":
                    theirs = float(theirs or 0.0)
                elif fld == "seed":
                    theirs = int(theirs or 0)
                if theirs != have:
                    raise ValueError(
                        "resume state was exported with %s=%r but "
                        "this admission says %s=%r — the request "
                        "args must restate the migrated request "
                        "(the resumed stream would silently "
                        "diverge)" % (fld, theirs, fld, have))
            # after k emitted tokens the last one is still pending
            # (not yet fed through the step), so the cache covers
            # exactly P + k - 1 positions
            self._check_blob(
                resume["kv_blob"], P + len(emitted) - 1,
                why="a migrated session's rows must cover prompt + "
                    "fed tokens")
        if P + n > self._gen.max_len:
            raise ValueError(
                "prompt (%d) + max_new_tokens (%d) exceeds the cache "
                "capacity max_len=%d" % (P, n, self._gen.max_len))
        if self._draft is not None and P + n > self._spec_cap:
            # pool-wide, not per-request: verify rounds write up to
            # lookahead speculative entries past EVERY live row's
            # depth (non-speculative rows ride the verify forward with
            # junk tails), so the headroom must hold for any row that
            # could share a round
            raise ValueError(
                "prompt (%d) + max_new_tokens (%d) exceeds the "
                "speculative headroom %d = min(target max_len=%d, "
                "draft max_len=%d) - lookahead %d; while a draft is "
                "attached every admission needs the headroom"
                % (P, n, self._spec_cap, self._gen.max_len,
                   self._draft.max_len, self._gamma))
        if self._gen._pos_rows is not None and \
                P + n > self._gen._pos_rows:
            raise ValueError(
                "prompt (%d) + max_new_tokens (%d) exceeds the "
                "trained position table (%d rows)"
                % (P, n, self._gen._pos_rows))
        req = DecodeFuture(prompt, n, eos_id, temperature, top_k,
                           top_p, seed, handoff=handoff,
                           speculative=speculative)
        if resume is not None:
            # PRNG progress is DERIVED state: one split per drawn
            # token, whatever path drew it (local pick or remote
            # handoff) — re-derive rather than ship a key
            if req._key is not None:
                req._key = replay_key(req.seed, len(emitted))
            req.emitted = emitted
            req.pending = int(resume["pending"])
            req.resume = resume["kv_blob"]
        if n == 0:                        # generate()'s n=0 contract
            req._finish_ok()
            return req
        if admit_id is not None:
            admit_id = str(admit_id)
        with self._cond:
            if admit_id is not None:
                prev = self._dedup.get(admit_id)
                if prev is not None:
                    # exactly-once admit: the replay rides the
                    # original admission (checked before the draining
                    # gate so a replayed request can still collect
                    # its answer from a draining replica)
                    self._dedup.move_to_end(admit_id)
                    self._deduped += 1
                    self._c_deduped.inc()
                    return prev
            if self._draining or self._closed:
                raise EngineClosed(
                    "decoder is draining — sequence rejected")
            if len(self._queue) >= self._cap:
                self._shed += 1
                _telemetry.counter("serve.shed").inc()
                raise Overloaded(
                    "decode queue full (%d sequences)"
                    % len(self._queue))
            if admit_id is not None:
                self._dedup[admit_id] = req
                while len(self._dedup) > _DEDUP_CAP:
                    self._dedup.popitem(last=False)
            self._queue.append(req)
            self._admitted += 1
            self._c_admitted.inc()
            self._cond.notify_all()
        return req

    def handle_generate(self, payload):
        """The ``generate`` wire frame (serve/net.py): submit one
        sequence — with its ``handoff`` blob when a remote prefill ran
        — and block the handler thread until the full row is back
        (concurrency comes from concurrent connections, the wire's
        standing contract). Payload keys mirror :meth:`submit`."""
        fut = self.submit(
            payload["prompt"], payload["max_new_tokens"],
            eos_id=payload.get("eos_id"),
            temperature=payload.get("temperature") or 0.0,
            top_k=payload.get("top_k"), top_p=payload.get("top_p"),
            seed=payload.get("seed") or 0,
            handoff=payload.get("handoff"),
            admit_id=payload.get("admit_id"),
            resume=payload.get("resume"),
            speculative=bool(payload.get("speculative")))
        try:
            return fut.result(payload.get("timeout"))
        except SessionEvacuated as exc:
            # the reply IS the session's portable state — the fleet
            # router resumes it on a survivor (serve/router.py) rather
            # than surfacing an error for a request nothing lost
            return {"evacuated": exc.state}

    def handle_generate_stream(self, payload, emit):
        """The streamed twin of :meth:`handle_generate`
        (serve/net.py's ``generate`` frame with ``stream: True``):
        submit the sequence, then relay every emitted token to
        ``emit(tokens, offset)`` ON THIS handler thread as the decode
        loop picks it — ``offset`` is the emission index of the
        chunk's first token, so a deduped replay (whose subscription
        replays the already-emitted prefix from offset 0) lets the
        client resume token-exact with no duplicated or missing
        frames. Returns the same final value as the one-shot path
        (the full id row, or the ``evacuated`` state dict) — the
        terminal frame carries it for bitwise comparison."""
        import queue as _qmod
        fut = self.submit(
            payload["prompt"], payload["max_new_tokens"],
            eos_id=payload.get("eos_id"),
            temperature=payload.get("temperature") or 0.0,
            top_k=payload.get("top_k"), top_p=payload.get("top_p"),
            seed=payload.get("seed") or 0,
            handoff=payload.get("handoff"),
            admit_id=payload.get("admit_id"),
            resume=payload.get("resume"),
            speculative=bool(payload.get("speculative")))
        q = _qmod.Queue()
        sink = q.put
        timeout = payload.get("timeout")
        deadline = None if timeout is None else \
            _telemetry.now_ms() + float(timeout) * 1000.0
        with self._lock:
            self._streams += 1
            self._streams_inflight += 1
            self._g_streams.set(self._streams_inflight)
        self._c_streams.inc()
        fut.subscribe(sink)
        try:
            offset = 0
            settled = False
            while not settled:
                wait = None if deadline is None else max(
                    0.0, (deadline - _telemetry.now_ms()) / 1000.0)
                try:
                    item = q.get(timeout=wait)
                except _qmod.Empty:
                    raise RequestTimeout(
                        "sequence still decoding after %.3fs"
                        % float(timeout))
                toks = []
                while True:
                    if item is None:       # settle sentinel
                        settled = True
                        break
                    toks.append(int(item))
                    try:
                        item = q.get_nowait()
                    except _qmod.Empty:
                        break
                if toks:
                    emit(toks, offset)
                    offset += len(toks)
        finally:
            fut.unsubscribe(sink)
            with self._lock:
                self._streams_inflight -= 1
                self._g_streams.set(self._streams_inflight)
        try:
            return fut.result(0)
        except SessionEvacuated as exc:
            return {"evacuated": exc.state}

    def generate_many(self, prompts, max_new_tokens, eos_id=None,
                      timeout=None, **kwargs):
        """Submit a batch of (possibly ragged) prompts and wait for all
        results — the closed-loop convenience wrapper; returns a list
        of id rows (ragged lengths when eos fires early)."""
        futs = [self.submit(p, max_new_tokens, eos_id=eos_id, **kwargs)
                for p in prompts]
        return [f.result(timeout) for f in futs]

    # -- the decode loop ----------------------------------------------------
    def _free_slots(self):
        return [i for i, s in enumerate(self._slots)
                if s is None and i not in self._reserved]

    def _draft_prefill_rows(self, slot, tokens):
        """Prefill the DRAFT cache for one admitted row from raw token
        ids — the local draft leg of handoff/resume admission (the
        wire blobs carry TARGET rows only; prefill replicas stay
        draft-agnostic). Rides the draft Generator's shared-position
        prefill graph, chunked by ``MXNET_PREFILL_CHUNK`` when set so
        arbitrary handoff lengths reuse the chunk-width programs
        instead of compiling one prefill shape per length."""
        toks = np.asarray(tokens, np.int64).reshape(-1)
        n = len(toks)
        aux = self._draft._fresh_aux()
        width = prefill_chunk() or n
        lo = 0
        while lo < n:
            hi = min(lo + width, n)
            rows = np.stack([toks[lo:hi]] * self._B)
            _, aux = self._draft._forward(
                aux, rows.astype(np.float32), lo)
            lo = hi
        idx = jnp.asarray(np.array([slot], np.int32))
        self._daux = {
            name: self._daux[name].at[idx].set(aux[name][:1])
            for name in self._daux}
        self._draft_prefills += 1
        self._c_dprefills.inc()

    def _admit_handoff(self, slot, req):
        """Admit one remote-prefilled sequence: scatter its shipped
        cache rows into the slot (zero TARGET prefill graph calls —
        the ``prefills`` stat must not move; a speculative request
        does prefill its DRAFT cache locally) and emit the shipped
        first token. A bad blob fails THAT request's future and frees
        the slot; the loop and the other slots are untouched."""
        t0 = _telemetry.now_ms()
        try:
            pos = self.import_kv_rows(slot, req.handoff["kv_blob"])
            tok = int(req.handoff["first_token"])
            if self._draft is not None and req.speculative:
                self._draft_prefill_rows(slot, req.prompt)
        except Exception as exc:          # noqa: BLE001 — the future
            # is this sequence's one response; a scatter failure must
            # not kill the decode loop for every other slot
            req._fail(exc)
            return
        self._slots[slot] = req
        req.handoff = None     # the rows live on device now — holding
        #                        the host blob would double memory per
        #                        imported slot for the whole decode
        req.t_admit = _telemetry.now_ms()
        req.n_cached = pos
        if _trace.enabled():
            _trace.add_span("serve.decode.import", t0, req.t_admit,
                            parent=req.tc, slot=slot, pos=pos)
        self._emit(req, tok)
        self._maybe_finish(slot, tok)

    def _admit_resume(self, slot, req):
        """Admit one migrated mid-decode session: scatter its exported
        rows at ``pos = prompt + fed`` and continue the stream — no
        first-token emission (the state already carries the pending
        token) and no prefill graph call. A bad blob fails THAT
        request's future; the loop and the other slots are
        untouched."""
        t0 = _telemetry.now_ms()
        try:
            pos = self.import_kv_rows(slot, req.resume)
            if self._draft is not None and req.speculative:
                # the cache covers prompt + fed tokens (the pending
                # last emission is not yet fed) — prefill the draft
                # over exactly that prefix
                self._draft_prefill_rows(
                    slot, np.concatenate(
                        [np.asarray(req.prompt, np.int64),
                         np.asarray(req.emitted[:-1], np.int64)]))
        except Exception as exc:          # noqa: BLE001 — the future
            # is this sequence's one response; an import failure must
            # not kill the decode loop for every other slot
            req._fail(exc)
            return
        self._slots[slot] = req
        req.resume = None      # the rows live on device now
        req.t_admit = _telemetry.now_ms()
        req.n_cached = pos
        self._resumed += 1
        self._c_resumed.inc()
        if _trace.enabled():
            _trace.add_span("serve.decode.resume", t0, req.t_admit,
                            parent=req.tc, slot=slot, pos=pos,
                            emitted=len(req.emitted))

    def _admit(self):
        """Move queued prompts into free slots. Remote-prefilled
        sequences (a ``handoff`` rode the submit) scatter their
        shipped rows directly — no prefill graph call. Fresh prompts:
        one shared-position prefill per distinct prompt length per
        round (all admitted rows start at position 0, so the
        Generator's ordinary prefill graph serves); cache rows merge
        into the pool by a batch-axis scatter that walks the WHOLE aux
        pytree — under quantize_kv that carries the per-token f32
        scale caches alongside the int8 k/v rows (a merged row without
        its scales would dequant to garbage)."""
        with self._lock:
            free = self._free_slots()
            if not free or not self._queue:
                return
            batch = [self._queue.popleft()
                     for _ in range(min(len(free), len(self._queue)))]
        chunk = prefill_chunk()
        by_len = {}
        waiting = []       # long prompts parked behind an active chunk
        for req in batch:
            if req.resume is not None:
                self._admit_resume(free.pop(0), req)
                continue
            if req.handoff is not None:
                self._admit_handoff(free.pop(0), req)
                continue
            if chunk and len(req.prompt) > chunk:
                # long prompt: feed it to the cache chunk-by-chunk,
                # interleaved with decode steps, instead of stalling
                # every active slot for one monolithic (B, P) forward.
                # One chunked prefill at a time; later long prompts
                # wait their turn at the queue front (short prompts
                # are deliberately NOT held behind them)
                if self._chunking is None:
                    slot = free.pop(0)
                    self._reserved.add(slot)
                    self._chunking = {"req": req, "slot": slot,
                                      "aux": self._gen._fresh_aux(),
                                      "pos": 0,
                                      "t0": _telemetry.now_ms()}
                    if self._draft is not None and req.speculative:
                        # the draft cache prefills alongside, chunk
                        # by chunk on the same widths
                        self._chunking["daux"] = \
                            self._draft._fresh_aux()
                else:
                    waiting.append(req)
                continue
            by_len.setdefault(len(req.prompt), []).append(req)
        if waiting:
            with self._lock:
                self._queue.extendleft(reversed(waiting))
        for P, reqs in sorted(by_len.items()):
            rows = np.stack([r.prompt for r in reqs] +
                            [reqs[0].prompt] * (self._B - len(reqs)))
            logits, pref_aux = self._gen._forward(
                self._gen._fresh_aux(), rows.astype(np.float32), 0)
            self._prefills += 1
            last = np.asarray(logits[:, -1].astype(jnp.float32))
            idx = jnp.asarray(
                np.array(free[:len(reqs)], np.int32))
            self._aux = {
                name: self._aux[name].at[idx].set(
                    pref_aux[name][:len(reqs)])
                for name in self._aux}
            if self._draft is not None and \
                    any(r.speculative for r in reqs):
                # the draft's cache rows for this group, one shared-
                # position prefill on the draft's OWN graph (its
                # per-row propose program never sees prefill shapes) —
                # scattered for the whole group: non-speculative rows'
                # draft rows are unread garbage either way
                _, d_pref = self._draft._forward(
                    self._draft._fresh_aux(),
                    rows.astype(np.float32), 0)
                self._daux = {
                    name: self._daux[name].at[idx].set(
                        d_pref[name][:len(reqs)])
                    for name in self._daux}
                self._draft_prefills += 1
                self._c_dprefills.inc()
            for i, req in enumerate(reqs):
                slot = free.pop(0)
                self._slots[slot] = req
                req.t_admit = _telemetry.now_ms()
                req.n_cached = P
                tok = req._pick(last[i])
                self._emit(req, tok)
                self._maybe_finish(slot, tok)

    def _emit(self, req, tok):
        """One token emission: latency metrics (TTFT on the first
        emission of a fresh request, inter-token gap after that), then
        the append + streaming-sink notify. Every emission path —
        fresh-prefill pick, shipped handoff token, chunked-prefill
        completion, per-step pick — funnels through here so the
        latency histograms and streamed frames can never drift from
        the row the one-shot path returns."""
        now = _telemetry.now_ms()
        if not req.emitted:
            self._h_ttft.observe(now - req.t_enq)
        elif req.t_last is not None:
            # resumed sessions arrive with a non-empty emitted prefix
            # but no local t_last — their first local emission gap
            # spans the migration, not a decode step, so it is skipped
            self._h_itl.observe(now - req.t_last)
        req.t_last = now
        req._emit(tok)

    def _maybe_finish(self, slot, tok):
        """Retire the slot's sequence if this emission ended it (eos or
        budget) — the slot frees NOW, so the next admission round can
        reuse it at the following step."""
        req = self._slots[slot]
        if (req.eos_id is not None and tok == req.eos_id) or \
                len(req.emitted) >= req.max_new:
            req._finish_ok()
            now = _telemetry.now_ms()
            self._h_req.observe(now - req.t_enq)
            self._finished += 1
            self._c_finished.inc()
            _telemetry.journal_event(
                "serve.decode.finish",
                tokens=len(req.emitted),
                ms=round(now - req.t_enq, 3))
            if _trace.enabled():
                # sequence lifecycle spans, retroactive from the
                # timestamps already taken: queue wait, then the slot
                # occupancy from admission to the finishing emission
                ctx = _trace.add_span(
                    "serve.decode.seq", req.t_enq, now, parent=req.tc,
                    tokens=len(req.emitted), prompt=len(req.prompt))
                if req.t_admit is not None:
                    _trace.add_span("serve.decode.queue", req.t_enq,
                                    req.t_admit, parent=ctx)
                    _trace.add_span("serve.decode.slot", req.t_admit,
                                    now, parent=ctx, slot=slot,
                                    tokens=len(req.emitted))
                # the decode thread holds no open span — flush the
                # retired sequence's records as one write
                _trace.flush()
            self._slots[slot] = None

    def _step(self):
        """One (B, 1) per-row-position decode step: every active slot
        ingests its pending token at its own depth and samples the
        next; inactive slots feed a dummy token at position 0 (their
        cache rows are garbage until the next admission overwrites
        them wholesale)."""
        active = [i for i, s in enumerate(self._slots) if s is not None]
        if not active:
            return
        toks = np.zeros((self._B, 1), np.float32)
        pos = np.zeros((self._B,), np.float32)
        for i in active:
            toks[i, 0] = float(self._slots[i].pending)
            pos[i] = float(self._slots[i].n_cached)
        args = dict(self._gen._params)
        args["data"] = jnp.asarray(toks)
        args["positions"] = jnp.asarray(pos[:, None])
        args["cache_pos"] = jnp.asarray(pos)
        outs, self._aux = self._step_fn(args, self._aux, self._rng0)
        last = np.asarray(outs[0][:, -1].astype(jnp.float32))
        self._steps += 1
        self._c_steps.inc()
        self._h_slotfill.observe(len(active))
        self._g_active.set(len(active))
        cache_size = getattr(self._step_fn, "_cache_size", None)
        if cache_size is not None:
            # stays 1 across slot turnover — admissions must never
            # recompile the (B, 1) step (gate-fingerprinted)
            self._g_jit.set(cache_size())
        self._g_kv.set(self._kv_bytes_per_slot)   # live pool wins
        for i in active:
            req = self._slots[i]
            req.n_cached += 1
            tok = req._pick(last[i])
            self._emit(req, tok)
            self._maybe_finish(i, tok)

    def _draft_forward(self, toks, pos):
        """One (B, 1) per-row-position DRAFT step: the propose half of
        a speculative round. Returns the (B, V) last-position logits
        as float32 numpy."""
        args = dict(self._draft._params)
        args["data"] = jnp.asarray(toks)
        args["positions"] = jnp.asarray(pos[:, None])
        args["cache_pos"] = jnp.asarray(pos)
        outs, self._daux = self._draft_step_fn(args, self._daux,
                                               self._rng0)
        self._draft_steps += 1
        self._c_dsteps.inc()
        cache_size = getattr(self._draft_step_fn, "_cache_size", None)
        if cache_size is not None:
            # stays 1 across slot turnover and round count — the
            # draft's half of the compiled-shape discipline
            self._g_djit.set(cache_size())
        return np.asarray(outs[0][:, -1].astype(jnp.float32))

    def _spec_round(self):
        """One speculative draft/verify round: γ compiled (B, 1) draft
        steps propose per-slot continuations, ONE (B, γ+1) target
        forward verifies them, and each row keeps its own longest-
        matching prefix plus the target's next token — per-row
        acceptance, lifting the eager path's lockstep rule.

        Exactness: the emission at index j is ALWAYS the target's own
        ``_pick`` on its logits for that index, with the request
        stream's (j+1)-th split; the draft proposed with the SAME sub
        (``_peek_subs`` — common random numbers), so "proposal
        accepted" literally means "equals what generate() would have
        picked". Byte-identity to the non-speculative path follows for
        greedy AND sampled requests, up to the verify forward's
        Tnew=γ+1 kernel-numerics caveat (generation.py,
        generate_speculative docstring).

        Cache discipline, per row: the verify forward writes γ+1
        entries at positions n_cached..n_cached+γ; the walk advances
        n_cached once per EMITTED token, so rejected entries sit past
        the row's depth where (a) the per-row mask keeps any
        correctly-conditioned query from attending them and (b) the
        next round's writes overwrite them before the row's depth
        reaches them. Same argument on the draft pool, which is why
        every admission pays γ headroom (``submit``'s _spec_cap
        check). Non-speculative rows ride the verify forward with
        junk tails and take only their column-0 pick — identical math
        to :meth:`_step`."""
        t0 = _telemetry.now_ms()
        active = [i for i, s in enumerate(self._slots)
                  if s is not None]
        spec = [i for i in active if self._slots[i].speculative]
        g = self._gamma
        toks = np.zeros((self._B, 1), np.float32)
        pos0 = np.zeros((self._B,), np.float32)
        for i in active:
            toks[i, 0] = float(self._slots[i].pending)
            pos0[i] = float(self._slots[i].n_cached)
        # peek each sampled row's subs WITHOUT advancing its stream —
        # the verify walk's _pick() calls advance it, once per
        # emitted token, exactly like every other emission path
        subs = {i: self._slots[i]._peek_subs(g) for i in spec
                if self._slots[i].temperature > 0}

        # -- propose: γ (B, 1) draft steps -----------------------------
        props = np.zeros((self._B, g), np.int64)
        cur = np.zeros((self._B, 1), np.float32)
        dpos = np.zeros((self._B,), np.float32)
        for t in range(g):
            for i in spec:
                cur[i, 0] = toks[i, 0] if t == 0 else \
                    float(props[i, t - 1])
                dpos[i] = pos0[i] + t
            # non-speculative and empty rows feed token 0 at draft
            # position 0: their draft cache rows are garbage by
            # definition, and position 0 is always in capacity
            dl = self._draft_forward(cur, dpos)
            for i in spec:
                req = self._slots[i]
                if req.temperature > 0:
                    props[i, t] = int(np.asarray(_pick_token(
                        dl[i][None], req.temperature, req.top_k,
                        subs[i][t], req.top_p))[0])
                else:
                    props[i, t] = int(np.argmax(dl[i]))

        # -- verify: ONE (B, γ+1) target forward -----------------------
        chunk = np.zeros((self._B, g + 1), np.float32)
        for i in active:
            chunk[i, 0] = toks[i, 0]
            for t in range(g):
                # non-speculative rows repeat their pending token as a
                # junk tail; only their column-0 logits are read
                chunk[i, t + 1] = float(props[i, t]) if i in spec \
                    else toks[i, 0]
        args = dict(self._gen._params)
        args["data"] = jnp.asarray(chunk)
        args["positions"] = jnp.asarray(
            pos0[:, None] + np.arange(g + 1, dtype=np.float32)[None])
        args["cache_pos"] = jnp.asarray(pos0)
        outs, self._aux = self._step_fn(args, self._aux, self._rng0)
        logits = np.asarray(outs[0].astype(jnp.float32))  # (B,g+1,V)
        self._steps += 1
        self._c_steps.inc()
        self._verify_steps += 1
        self._c_vsteps.inc()
        self._spec_rounds += 1
        self._c_srounds.inc()
        self._h_slotfill.observe(len(active))
        self._g_active.set(len(active))
        cache_size = getattr(self._step_fn, "_cache_size", None)
        if cache_size is not None:
            # exactly TWO target programs — (B, 1) step + (B, γ+1)
            # verify — across admissions and rounds (gate-pinned)
            self._g_jit.set(cache_size())
        self._g_kv.set(self._kv_bytes_per_slot)   # live pool wins

        # -- per-row acceptance walk -----------------------------------
        accepted = proposed = 0
        full = []          # rows needing the draft catch-up feed
        for i in active:
            req = self._slots[i]
            if i not in spec:
                # the _step() math, read off the verify forward
                req.n_cached += 1
                tok = req._pick(logits[i, 0])
                self._emit(req, tok)
                self._maybe_finish(i, tok)
                continue
            proposed += g
            acc = 0
            for j in range(g + 1):
                req.n_cached += 1
                tok = req._pick(logits[i, j])
                self._emit(req, tok)
                matched = j < g and int(props[i, j]) == tok
                if matched:
                    acc += 1
                self._maybe_finish(i, tok)
                if self._slots[i] is None or not matched:
                    break
            accepted += acc
            self._h_accept.observe(acc / g)
            if acc == g and self._slots[i] is not None:
                full.append(i)
        self._spec_proposed += proposed
        self._spec_accepted += accepted
        self._c_proposed.inc(proposed)
        self._c_accepted.inc(accepted)

        if full:
            # full acceptance: the draft never ingested its last
            # proposal's k/v (its loop stops after computing it) —
            # one conditional catch-up step fills the hole at the
            # row's old pos0+γ. Rows that were speculative this round
            # but are not catching up write JUNK at their own pos0+γ
            # (inside the γ headroom, past their valid prefix, and
            # overwritten by a later feed before any correctly-
            # conditioned query can attend it — NEVER at position 0,
            # which holds their live prompt k/v); non-speculative and
            # empty rows write at their garbage rows' position 0
            for i in range(self._B):
                if i in full:
                    cur[i, 0] = float(props[i, g - 1])
                    dpos[i] = pos0[i] + g
                elif i in spec:
                    cur[i, 0] = 0.0
                    dpos[i] = pos0[i] + g
                else:
                    cur[i, 0] = 0.0
                    dpos[i] = 0.0
            self._draft_forward(cur, dpos)
        if _trace.enabled():
            _trace.add_span("serve.spec.round", t0,
                            _telemetry.now_ms(), rows=len(spec),
                            proposed=proposed, accepted=accepted)

    def _chunk_step(self):
        """Feed ONE chunk of the in-progress chunked prefill — called
        once per loop iteration between admission and the (B, 1) step,
        so active sessions pay one chunk-width forward per token
        instead of the whole prompt at once. Chunk forwards ride the
        Generator's ordinary shared-position graph (one XLA program
        per chunk width — the ragged final chunk adds at most one
        more); the per-row (B, 1) step's jit cache never moves. The
        math is bit-identical to the monolithic prefill: every forward
        attends the full masked cache buffer, so splitting the query
        axis changes no reduction a kept position sees."""
        ch = self._chunking
        if ch is None:
            return
        req, slot = ch["req"], ch["slot"]
        P = len(req.prompt)
        lo = ch["pos"]
        hi = min(lo + prefill_chunk(), P)
        rows = np.stack([req.prompt[lo:hi]] * self._B)
        try:
            logits, ch["aux"] = self._gen._forward(
                ch["aux"], rows.astype(np.float32), lo)
            if "daux" in ch:
                _, ch["daux"] = self._draft._forward(
                    ch["daux"], rows.astype(np.float32), lo)
        except Exception as exc:          # noqa: BLE001 — the future
            # is this sequence's one response; a failed chunk must not
            # kill the decode loop for every other slot
            self._chunking = None
            self._reserved.discard(slot)
            req._fail(exc)
            return
        ch["pos"] = hi
        self._c_chunks.inc()
        if _trace.enabled():
            _trace.add_span("serve.decode.prefill_chunk",
                            ch.pop("t_chunk", ch["t0"]),
                            _telemetry.now_ms(), parent=req.tc,
                            slot=slot, lo=lo, hi=hi)
            ch["t_chunk"] = _telemetry.now_ms()
        if hi < P:
            return
        # final chunk: merge the fully-prefilled row into the pool
        # (same batch-axis scatter as the monolithic path) and emit
        # the first token
        idx = jnp.asarray(np.array([slot], np.int32))
        self._aux = {
            name: self._aux[name].at[idx].set(ch["aux"][name][:1])
            for name in self._aux}
        if "daux" in ch:
            self._daux = {
                name: self._daux[name].at[idx].set(
                    ch["daux"][name][:1])
                for name in self._daux}
            self._draft_prefills += 1
            self._c_dprefills.inc()
        self._prefills += 1
        last = np.asarray(logits[:1, -1].astype(jnp.float32))
        self._chunking = None
        self._reserved.discard(slot)
        self._slots[slot] = req
        req.t_admit = _telemetry.now_ms()
        req.n_cached = P
        tok = req._pick(last[0])
        self._emit(req, tok)
        self._maybe_finish(slot, tok)

    def _loop(self):
        while True:
            with self._cond:
                while not self._queue and not self._draining and \
                        not self._evac_waiters and \
                        not self._evac_flag and \
                        self._chunking is None and \
                        all(s is None for s in self._slots):
                    self._cond.wait(0.05)
                if self._draining and not self._queue and \
                        not self._evac_waiters and \
                        not self._evac_flag and \
                        self._chunking is None and \
                        all(s is None for s in self._slots):
                    break
            if self._evac_waiters or self._evac_flag:
                self._do_evacuate()
                continue
            self._admit()
            self._chunk_step()
            if self._draft is not None and any(
                    s is not None and s.speculative
                    for s in self._slots):
                self._spec_round()
            else:
                # draft-less pools and rounds with no speculative
                # participant run the ordinary (B, 1) step — a
                # mixed-traffic pool flips between the two compiled
                # target programs, never compiles a third
                self._step()
        self._g_active.set(0)
        _telemetry.journal_event("serve.decode.stop")

    # -- migration ----------------------------------------------------------
    def evacuate(self, timeout=30.0):
        """Export every active session off the pool: each in-flight
        generate's future fails with :class:`SessionEvacuated`
        carrying its :meth:`export_session` state (the wire handler
        turns that into an ``evacuated`` reply the fleet router
        resumes on a survivor); queued-but-unadmitted requests fail
        with ``EngineClosed`` and replay from scratch. The export runs
        on the decode loop thread (the pool's one aux mutator); this
        call blocks until it completes and returns the number of
        sessions exported. The pool itself stays OPEN — a
        config-reload recycle re-warms and readmits this replica —
        so a migrating recycle is bounded by export+import cost, not
        by its longest sequence."""
        ev = threading.Event()
        out = []
        with self._cond:
            if self._closed:
                raise EngineClosed("decoder is closed")
            self._evac_waiters.append((ev, out))
            self._cond.notify_all()
        if not ev.wait(timeout):
            raise RequestTimeout(
                "evacuation still pending after %.3fs" % timeout)
        return out[0]

    def _request_evacuate(self):
        # SIGTERM-handler context (guardrail.GracefulShutdown): set
        # the flag only — no locks, no telemetry, no XLA. The decode
        # loop notices within one 0.05s cond-wait tick.
        self._evac_flag = True

    def _do_evacuate(self):
        """Runs ON the decode loop thread: export + fail every active
        slot, reject the queue, wake the evacuate() waiters. A SIGTERM
        evacuation (``_evac_flag``) also drains the pool — the process
        is ending, so there is nothing to readmit for."""
        t0 = _telemetry.now_ms()
        with self._cond:
            waiters, self._evac_waiters = self._evac_waiters, []
            sig, self._evac_flag = self._evac_flag, False
            if sig:
                self._draining = True
            queued = list(self._queue)
            self._queue.clear()
        n = 0
        for slot in range(self._B):
            req = self._slots[slot]
            if req is None:
                continue
            try:
                state = self.export_session(slot)
            except Exception as exc:      # noqa: BLE001 — the future
                # is this sequence's one response; a failed export
                # must surface there, not kill the loop
                self._slots[slot] = None
                req._fail(exc)
                continue
            self._slots[slot] = None
            req._fail(SessionEvacuated(state))
            n += 1
        ch, self._chunking = self._chunking, None
        if ch is not None:
            # a half-prefilled prompt has no portable session yet
            # (no emitted token, partial cache) — prefill is pure, so
            # it replays from scratch exactly like a queued request
            self._reserved.discard(ch["slot"])
            queued.append(ch["req"])
        for req in queued:
            req._fail(EngineClosed(
                "evacuated before admission — replay the request on "
                "another replica"))
        self._evacuated += n
        if n:
            self._c_evacuated.inc(n)
        self._g_active.set(0)
        _telemetry.journal_event(
            "serve.decode.evacuate", sessions=n, queued=len(queued),
            sigterm=bool(sig),
            ms=round(_telemetry.now_ms() - t0, 3))
        for ev, out in waiters:
            out.append(n)
            ev.set()

    # -- lifecycle ----------------------------------------------------------
    @property
    def draining(self):
        return self._draining or self._closed

    def close(self, timeout=None):
        """Drain: admitted sequences decode to completion, new
        submissions raise EngineClosed, then the loop thread exits.
        ``timeout=None`` reads ``MXNET_DECODE_DRAIN_TIMEOUT`` (the
        router's recycle of a decode replica budgets its drain from
        the same knob — one drain clock, not a hardcoded 60 here and
        a knob everywhere else)."""
        if timeout is None:
            timeout = drain_timeout()
        with self._cond:
            already = self._closed
            self._draining = True
            pending = len(self._queue)
            self._cond.notify_all()
        if not already:
            _telemetry.journal_event("serve.decode.drain",
                                     pending=pending)
        self._thread.join(timeout)
        self._closed = True
        if self._shutdown is not None:
            self._shutdown.uninstall()
            self._shutdown = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def stats(self):
        return {"admitted": self._admitted, "finished": self._finished,
                "shed": self._shed,
                "steps": self._steps, "prefills": self._prefills,
                "imported": self._imported, "resumed": self._resumed,
                "evacuated": self._evacuated,
                "deduped": self._deduped,
                "streams": self._streams,
                "spec_rounds": self._spec_rounds,
                "draft_steps": self._draft_steps,
                "spec_proposed": self._spec_proposed,
                "spec_accepted": self._spec_accepted,
                "draft_prefills": self._draft_prefills,
                "active": sum(s is not None for s in self._slots),
                "queued": len(self._queue)}

    def introspect(self):
        """Live state for the ``stats`` introspection frame
        (serve/net.py answers it for ANY engine-like object): slot
        headroom and queue depth. ``decode_free_slots`` is the signal
        the fleet router's session placement consumes — a new decode
        session goes to the replica with the most free slots
        (serve/router.py)."""
        out = self.stats()
        out["queue_depth"] = out.pop("queued")
        out["in_flight"] = out["active"] + out["queue_depth"]
        out["decode_free_slots"] = (self._B - out["active"]
                                    - len(self._reserved))
        out["slots"] = self._B
        out["streams_in_flight"] = self._streams_inflight
        out["speculative"] = self._draft is not None
        out["draining"] = self.draining
        return out
