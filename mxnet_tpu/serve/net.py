"""Thin TCP front end for the serving engine.

Deliberately REUSES the async-PS wire plumbing instead of inventing a
second transport: the 4-byte length-prefixed pickle framing
(``parallel/ps_async._send_msg`` / ``_recv_msg``), the
``RetryPolicy`` transient/fatal classification, and the deterministic
``FaultInjector`` — so the whole ``MXNET_FAULT_SPEC`` fault grammar
works unchanged against the serving path, under the serve-specific
point names:

* ``serve_send`` / ``serve_recv`` — client request/reply plumbing
* ``serve_srv_send`` / ``serve_srv_recv`` — server-side plumbing
* ``prefill_send`` / ``prefill_recv`` — the ``prefill`` frame's
  client plumbing, a GLOBAL pair regardless of the client's point
  family: the disaggregation handoff leg can be killed
  deterministically without perturbing infer/stats counts. Prefill is
  pure (same prompt + seed → same reply), so a torn handoff simply
  replays — the replayed prefill lands the identical blob and the
  decode side admits exactly once.

(The fleet router's clients rename the client-side pair per replica —
``router<I>_send`` / ``router<I>_recv`` and ``router<I>_ctl_*`` for
its control connection — via ``ServeClient(fault_points=...)``, so a
single replica's transport can be killed deterministically.)

e.g. ``MXNET_FAULT_SPEC="serve_send:disconnect@3;serve_recv:drop@5"``
tears the 3rd request frame mid-message and severs before the 5th
reply read — and the client's retry/reconnect must still deliver
exactly one response per request (inference is pure, so a replayed
request is safe — no dedup table needed, unlike the PS push path).

Typed engine errors (Overloaded, RequestTimeout, EngineClosed) cross
the wire BY NAME and re-raise as themselves client-side; they are
application replies over a working transport, so RetryPolicy correctly
classifies them fatal (retrying an Overloaded against the same full
queue is how retry storms are born — the client backs off or routes
elsewhere, its call).

Trusted-cluster assumption, exactly like the PS: the wire unpickles.
The server binds 127.0.0.1 unless told otherwise; exposing it is an
explicit operator decision, never the default.
"""
from __future__ import annotations

import logging
import socket
import threading

import numpy as np

from .. import config as _config
from .. import telemetry as _telemetry
from .. import trace as _trace
from ..parallel.ps_async import _recv_msg, _send_msg
from ..parallel.resilience import RetryPolicy
from . import engine as _engine

__all__ = ["ServeServer", "ServeClient", "stream_idle_timeout"]


def stream_idle_timeout():
    """``MXNET_STREAM_IDLE_TIMEOUT``, loudly validated: the per-frame
    idle bound every streamed-generate read applies — the gap since
    the previous frame, not the whole completion, is what a healthy
    streaming replica keeps short, so a hung replica surfaces as a
    transport fault after ONE missed inter-frame gap instead of the
    one-shot path's whole-completion deadline. The first frame's gap
    covers queue wait + prefill (TTFT), so size the knob past worst-
    case admission latency — the fleet router warms recycled replicas
    precisely so a cold XLA compile never lands here."""
    import math
    t = float(_config.get("MXNET_STREAM_IDLE_TIMEOUT"))
    if not (math.isfinite(t) and t > 0):
        raise ValueError(
            "MXNET_STREAM_IDLE_TIMEOUT=%r: wants a positive finite "
            "number of seconds (a non-positive or non-finite idle "
            "bound would either fail every stream instantly or wedge "
            "on a hung replica forever)" % (t,))
    return t


class ServeServer:
    """Accept loop + one handler thread per connection, each feeding
    the shared :class:`~mxnet_tpu.serve.ServeEngine`. Requests on one
    connection serialize (reply order = request order, like the PS
    client plumbing); concurrency comes from concurrent connections —
    which is exactly what the engine's batcher wants to coalesce."""

    def __init__(self, engine, host="127.0.0.1", port=0, logger=None):
        self._engine = engine
        self._log = logger or logging.getLogger(__name__)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        # accept() must notice close(): on Linux closing the listener
        # does NOT unblock a blocked accept, so the loop polls
        self._sock.settimeout(0.2)
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = False
        self._conns = set()
        self._conn_threads = set()         # live handler threads only
        self._conn_lock = threading.Lock()
        self._c_conns = _telemetry.counter("serve.net.connections")
        self._c_frames = _telemetry.counter("serve.net.stream_frames")
        self._c_streams = _telemetry.counter("serve.net.streams")
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="mxnet-serve-accept",
            daemon=True)
        self._accept_thread.start()

    @property
    def address(self):
        return "%s:%d" % (self.host, self.port)

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue                  # poll the stop flag
            except OSError:
                break                     # listener closed
            conn.settimeout(None)         # inherit-from-listener trap
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._c_conns.inc()
            with self._conn_lock:
                self._conns.add(conn)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="mxnet-serve-conn", daemon=True)
            with self._conn_lock:
                self._conn_threads.add(t)
            t.start()

    def _serve_conn(self, conn):
        try:
            while not self._stop:
                msg = _recv_msg(conn, "serve_srv_recv")
                if msg is None:           # clean EOF or torn frame
                    break
                reply = self._handle(msg, conn)
                _send_msg(conn, reply, "serve_srv_send")
        except (ConnectionError, OSError) as exc:
            # includes injected FaultInjected severs: this connection
            # is gone, the client's RetryPolicy reconnects and replays
            self._log.debug("serve conn dropped: %s", exc)
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._conn_lock:
                self._conns.discard(conn)
                self._conn_threads.discard(threading.current_thread())

    def _handle(self, msg, conn=None):
        try:
            op, payload = msg
        except (TypeError, ValueError):
            return ("err", "ServeError", "malformed request frame")
        if op == "ping":
            return ("ok", None)
        if op == "hello":
            # registration frame: who/what this server fronts, so a
            # fleet router (serve/router.py) can learn a replica's
            # declared buckets and capabilities at add_replica time
            # instead of carrying them in its own config. Answered
            # from live engine state, never cached.
            try:
                # model_id: generation stamp of the served artifact
                # (export_buckets manifest), None for in-process
                # models. Optional on the wire — old peers that never
                # send/read it keep working (duck-typed frames).
                return ("ok", {
                    "role": getattr(self._engine, "role",
                                    type(self._engine).__name__),
                    "model_id": getattr(self._engine, "model_id", None),
                    "engine": self._engine_state()})
            except Exception as exc:      # noqa: BLE001 — reply = report
                return ("err", "ServeError",
                        "%s: %s" % (type(exc).__name__, exc))
        if op == "warm":
            # re-warm frame: pre-compile every declared bucket (the
            # router calls this on a freshly recycled replica BEFORE
            # readmitting it, so its first live request never pays a
            # cold XLA compile)
            try:
                warmup = getattr(self._engine, "warmup", None)
                if not callable(warmup):
                    return ("err", "ServeError",
                            "engine %s has no warmup()"
                            % type(self._engine).__name__)
                warmup()
                return ("ok", list(getattr(self._engine,
                                           "warmed_buckets", []) or []))
            except _engine.ServeError as exc:
                return ("err", type(exc).__name__, str(exc))
            except Exception as exc:      # noqa: BLE001 — reply = report
                return ("err", "ServeError",
                        "%s: %s" % (type(exc).__name__, exc))
        if op == "evacuate":
            # migration frame: export every active decode session off
            # this replica — each in-flight generate answers with its
            # portable state instead of a row, and the fleet router
            # resumes it on a survivor (docs/robustness.md). Duck-typed
            # like everything else: an engine without evacuate() (a
            # batch ServeEngine, a PrefillEngine, a router) declines
            # typed, and the router falls back to a full drain.
            fn = getattr(self._engine, "evacuate", None)
            if not callable(fn):
                return ("err", "ServeError",
                        "engine %s has no evacuate() — not a "
                        "migratable replica"
                        % type(self._engine).__name__)
            try:
                return ("ok", fn())
            except _engine.ServeError as exc:
                return ("err", type(exc).__name__, str(exc))
            except Exception as exc:      # noqa: BLE001 — reply = report
                self._log.exception("serve: evacuate handling failed")
                return ("err", "ServeError",
                        "%s: %s" % (type(exc).__name__, exc))
        if op == "stats":
            # introspection frame: the telemetry registry snapshot +
            # live engine state (queue depth, warmed buckets). Read by
            # ServeClient.stats() and `tools/telemetry_report.py
            # --stats host:port`.
            try:
                return ("ok", {"telemetry": _telemetry.snapshot(),
                               "engine": self._engine_state()})
            except Exception as exc:      # noqa: BLE001 — reply = report
                return ("err", "ServeError",
                        "%s: %s" % (type(exc).__name__, exc))
        if op in ("prefill", "generate"):
            # disaggregation frames (docs/serving.md §disaggregated
            # prefill), duck-typed like everything else the wire
            # fronts: `prefill` wants an engine with prefill() (a
            # PrefillEngine) and answers {first_token, kv_blob, pos};
            # `generate` wants handle_generate() (a ContinuousDecoder
            # admitting — with the shipped blob when one rode along —
            # or a ServeRouter fanning the whole prefill→decode path
            # out) and answers the full id row.
            attr = "prefill" if op == "prefill" else "handle_generate"
            fn = getattr(self._engine, attr, None)
            if not callable(fn):
                return ("err", "ServeError",
                        "engine %s has no %s() — not a %s-capable "
                        "replica" % (type(self._engine).__name__,
                                     attr, op))
            rtc = _trace.TraceContext.from_wire(payload.get("tc")) \
                if isinstance(payload, dict) else None
            hsp = _trace.start_span("serve.handle", op=op,
                                    parent=rtc) \
                if _trace.enabled() else None
            try:
                kw = {k: v for k, v in payload.items()
                      if k not in ("tc", "stream")}
                if op == "prefill":
                    return ("ok", fn(kw.pop("prompt"), **kw))
                sfn = getattr(self._engine, "handle_generate_stream",
                              None)
                if payload.get("stream") and conn is not None and \
                        callable(sfn):
                    # streamed generate: intermediate ("frame", {seq,
                    # offset, tokens}) frames ride THIS connection
                    # ahead of the ordinary terminal reply (which
                    # still carries the full row — the bitwise cross-
                    # check against the one-shot path). A client that
                    # asked to stream against an engine without the
                    # handler simply gets the one-shot reply: zero
                    # frames is a valid stream.
                    seq = [0]

                    def emit(tokens, offset):
                        _send_msg(conn, ("frame",
                                         {"seq": seq[0],
                                          "offset": int(offset),
                                          "tokens": [int(t)
                                                     for t in tokens]}),
                                  "serve_srv_send")
                        seq[0] += 1
                        self._c_frames.inc()

                    self._c_streams.inc()
                    return ("ok", sfn(kw, emit))
                return ("ok", fn(kw))
            except _engine.ServeError as exc:
                return ("err", type(exc).__name__, str(exc))
            except Exception as exc:      # noqa: BLE001 — the reply
                # IS the error report; the client re-raises it typed
                self._log.exception("serve: %s handling failed", op)
                return ("err", "ServeError",
                        "%s: %s" % (type(exc).__name__, exc))
            finally:
                _trace.end_span(hsp)
        if op != "infer":
            return ("err", "ServeError", "unknown op %r" % (op,))
        # handler span: adopts the remote caller's trace context ("tc"
        # in the payload — an extra key old servers never read) so
        # client and server share one trace_id; the engine's lifecycle
        # spans parent to this handler through submit(tc=).
        rtc = _trace.TraceContext.from_wire(payload.get("tc")) \
            if isinstance(payload, dict) else None
        hsp = _trace.start_span("serve.handle", parent=rtc) \
            if _trace.enabled() else None
        try:
            kw = {"deadline_ms": payload.get("deadline_ms"),
                  "tc": hsp.context() if hsp is not None else rtc}
            if isinstance(payload, dict) and \
                    payload.get("session") is not None:
                # optional routing key (old clients never send it):
                # the fleet router pins it to the replica holding the
                # session's decode state; a plain engine ignores it
                kw["session"] = payload["session"]
            fut = self._engine.submit(*payload["inputs"], **kw)
            return ("ok", fut.result())
        except _engine.ServeError as exc:
            return ("err", type(exc).__name__, str(exc))
        except Exception as exc:          # noqa: BLE001 — the reply IS
            # the error report; the client re-raises it typed
            self._log.exception("serve: request handling failed")
            return ("err", "ServeError",
                    "%s: %s" % (type(exc).__name__, exc))
        finally:
            _trace.end_span(hsp)

    def _engine_state(self):
        """The engine's live state for the ``stats`` frame — duck-typed
        so any forward-capable wrapper with a stats() works."""
        eng = self._engine
        introspect = getattr(eng, "introspect", None)
        if callable(introspect):
            return introspect()
        stats = getattr(eng, "stats", None)
        return dict(stats()) if callable(stats) else {}

    def close(self):
        """Stop accepting, sever open connections, leave the engine to
        its own drain (callers own the engine lifecycle)."""
        self._stop = True
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conn_lock:
            conns = list(self._conns)
            threads = list(self._conn_threads)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        for t in [self._accept_thread] + threads:
            t.join(5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class ServeClient:
    """Blocking request client with reconnect-and-replay.

    Transport faults (drops, torn frames, resets — real or injected)
    are transient: the broken socket is dropped and the request is
    REPLAYED on a fresh connection under the RetryPolicy's
    deterministic backoff. Inference is pure, so replay is safe without
    a dedup table. Typed engine errors arrive as replies and re-raise
    as themselves (fatal: the transport demonstrably works)."""

    def __init__(self, host, port, retry=None, timeout=None,
                 logger=None, fault_points="serve"):
        self._addr = (host, int(port))
        self._retry = retry or RetryPolicy(seed="serve:%s:%d"
                                           % (host, int(port)))
        self._timeout = timeout
        # injection-point family for this client's wire plumbing
        # (resilience.FaultInjector grammar). Default "serve" keeps
        # the documented serve_send/serve_recv points; the fleet
        # router names a family per replica (router<I>/router<I>_ctl)
        # so one replica's transport can be killed deterministically
        # without touching the others.
        self._pt_send = "%s_send" % fault_points
        self._pt_recv = "%s_recv" % fault_points
        self._log = logger or logging.getLogger(__name__)
        self._sock = None
        self._lock = threading.Lock()
        self._c_retries = _telemetry.counter("serve.net.retries")

    def _ensure(self):
        if self._sock is None:
            s = socket.create_connection(self._addr,
                                         timeout=self._timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _on_retry(self, exc, attempt, delay):
        self._c_retries.inc()
        self._log.debug("serve client retry #%d in %.3fs after %s",
                        attempt, delay, exc)
        self._drop()

    _KEEP_TIMEOUT = object()             # sentinel: socket's own

    def _roundtrip(self, frame, describe, pt_send=None, pt_recv=None,
                   read_timeout=_KEEP_TIMEOUT):
        """One framed round trip under the retry policy: transport
        faults drop the socket and replay on a fresh connection, an
        err reply re-raises the engine's typed error. ``pt_send`` /
        ``pt_recv`` override this client's injection-point family
        (the global ``prefill_*`` pair rides here). ``read_timeout``
        overrides the socket timeout for THIS op only (the generate
        frame legitimately blocks for a whole decode — the client's
        io timeout must not misread a long generation as a dead
        replica); restored before the socket returns to normal use."""
        pt_send = pt_send or self._pt_send
        pt_recv = pt_recv or self._pt_recv

        def attempt():
            sock = self._ensure()
            if read_timeout is not self._KEEP_TIMEOUT:
                sock.settimeout(read_timeout)
            try:
                _send_msg(sock, frame, pt_send)
                reply = _recv_msg(sock, pt_recv)
            except Exception:
                self._drop()
                raise
            finally:
                if read_timeout is not self._KEEP_TIMEOUT and \
                        self._sock is sock:
                    sock.settimeout(self._timeout)
            if reply is None:
                self._drop()
                raise ConnectionError(
                    "server closed the connection mid-reply")
            return reply

        with self._lock:
            reply = self._retry.run(attempt, describe=describe,
                                    on_retry=self._on_retry)
        if reply[0] == "ok":
            return reply[1]
        _, kind, msg = reply
        raise _engine.typed_error(kind, msg)

    def request(self, inputs, deadline_ms=None, session=None):
        """One inference round trip; returns the per-request output
        list. Retries transport faults; raises the engine's typed
        error otherwise. ``session``: optional continuous-decode
        session id the fleet router pins to one replica (a plain
        engine accepts and ignores it)."""
        payload = {"inputs": [np.asarray(a) for a in inputs]}
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        if session is not None:
            payload["session"] = session
        # request span + wire trace context: the server's handler span
        # (and the engine's queue/forward lifecycle) joins this trace.
        # Old servers never read the extra "tc" key.
        rsp = _trace.start_span("serve.request",
                                rows=int(payload["inputs"][0].shape[0])
                                if payload["inputs"][0].ndim else 0)
        if rsp is not None:
            payload["tc"] = rsp.context().to_wire()
        try:
            return self._roundtrip(("infer", payload), "serve.infer")
        finally:
            _trace.end_span(rsp)

    def prefill(self, prompt, temperature=0.0, top_k=None, top_p=None,
                seed=0):
        """The ``prefill`` frame: run one sequence's prefill on the
        remote replica and return its handoff dict ``{"first_token",
        "kv_blob", "pos"}``. Injection points are the GLOBAL
        ``prefill_send`` / ``prefill_recv`` pair (not this client's
        family); prefill is pure, so the transport-fault replay is
        safe by construction — a replayed prefill lands the identical
        blob."""
        payload = {"prompt": np.asarray(prompt, np.int64).reshape(-1),
                   "temperature": temperature, "top_k": top_k,
                   "top_p": top_p, "seed": seed}
        rsp = _trace.start_span("serve.prefill.request",
                                tokens=int(payload["prompt"].size))
        if rsp is not None:
            payload["tc"] = rsp.context().to_wire()
        # first contact with a prompt length pays the server-side
        # (B, P) XLA compile — minutes on real hardware, far past a
        # dead-transport io timeout; give the read a compile-sized
        # allowance so a cold prefill is never misread as a dead
        # replica (and replayed into ANOTHER cold compile)
        wire_timeout = None if self._timeout is None \
            else float(self._timeout) + 300.0
        try:
            return self._roundtrip(("prefill", payload),
                                   "serve.prefill",
                                   "prefill_send", "prefill_recv",
                                   read_timeout=wire_timeout)
        finally:
            _trace.end_span(rsp)

    def generate(self, prompt, max_new_tokens, eos_id=None,
                 temperature=0.0, top_k=None, top_p=None, seed=0,
                 session=None, handoff=None, timeout=None,
                 admit_id=None, resume=None, on_token=None,
                 speculative=False):
        """The ``generate`` frame: admit one sequence on the remote
        replica (with its ``handoff`` blob when a remote prefill ran)
        and block for the full id row. Replay caveat: a transport
        fault AFTER the admission landed replays the whole admit —
        without an ``admit_id`` the orphaned first admission still
        decodes to completion and frees its slot, and both admissions
        emit identical tokens (greedy, or the same per-request PRNG
        stream), so the caller still sees exactly one, correct
        response; WITH an ``admit_id`` (the fleet router always sends
        one) the replay rides the original admission outright —
        exactly-once admit, no orphan.

        ``resume``: an evacuated session's ``export_session`` state —
        readmit a migrated sequence mid-decode
        (``ContinuousDecoder.submit(resume=...)``).

        ``speculative``: ask the replica to decode this request with
        draft/verify rounds when it carries a speculative draft
        (docs/serving.md §speculative). A pure performance hint —
        output is byte-identical either way, and a draft-less replica
        ignores it — so failover and replay semantics are unchanged.

        The wire read is bounded by ``timeout`` (plus this client's
        io timeout as slack) when one is given, and UNBOUNDED
        otherwise — a decode lasts as long as its tokens; the
        client's io timeout exists to catch dead transports and must
        not misclassify a healthy long generation. Pass ``timeout``
        to bound a generate against a hung replica.

        ``on_token``: streaming mode — the server emits a frame per
        decode step and ``on_token(tok)`` fires per NEW token, in
        emission order, exactly once each (transport replays re-read
        the stream from offset 0; tokens already delivered are
        verified against the replay, never re-delivered). Streamed
        reads replace the whole-completion deadline with the
        per-frame ``MXNET_STREAM_IDLE_TIMEOUT`` idle bound: a replica
        that stops producing frames fails after one missed gap. The
        returned row is the terminal frame's full result — bitwise
        what the one-shot path returns."""
        payload = {"prompt": np.asarray(prompt, np.int64).reshape(-1),
                   "max_new_tokens": int(max_new_tokens),
                   "eos_id": eos_id, "temperature": temperature,
                   "top_k": top_k, "top_p": top_p, "seed": seed}
        if session is not None:
            payload["session"] = session
        if handoff is not None:
            payload["handoff"] = handoff
        if timeout is not None:
            payload["timeout"] = timeout
        if admit_id is not None:
            payload["admit_id"] = admit_id
        if resume is not None:
            payload["resume"] = resume
        if speculative:
            payload["speculative"] = True
        if on_token is not None:
            payload["stream"] = True
        rsp = _trace.start_span("serve.generate.request",
                                tokens=int(payload["prompt"].size),
                                max_new=payload["max_new_tokens"],
                                stream=bool(on_token))
        if rsp is not None:
            payload["tc"] = rsp.context().to_wire()
        try:
            if on_token is not None:
                return self._stream_roundtrip(payload, on_token)
            wire_timeout = None if timeout is None \
                else float(timeout) + (self._timeout or 30.0)
            return self._roundtrip(("generate", payload),
                                   "serve.generate",
                                   read_timeout=wire_timeout)
        finally:
            _trace.end_span(rsp)

    def _stream_roundtrip(self, payload, on_token):
        """The streamed ``generate`` round trip: read ("frame", {seq,
        offset, tokens}) frames until the terminal ok/err reply, each
        read bounded by the per-frame idle timeout. ``offset`` (the
        emission index of a frame's first token) is what makes replay
        exact: a retry — same socket replay or a fleet failover — re-
        reads the stream from 0; tokens at already-delivered offsets
        must MATCH what was delivered (a mismatch is a determinism
        violation and fails loudly, typed) and only the tail past the
        delivered prefix reaches ``on_token``. No token is ever
        delivered twice or skipped."""
        idle = stream_idle_timeout()
        delivered = []
        first = [True]

        def attempt():
            sock = self._ensure()
            sock.settimeout(idle)
            last_seq = -1
            try:
                _send_msg(sock, ("generate", payload), self._pt_send)
                while True:
                    reply = _recv_msg(sock, self._pt_recv)
                    if reply is None:
                        raise ConnectionError(
                            "server closed the connection mid-stream")
                    if not (isinstance(reply, tuple) and reply and
                            reply[0] == "frame"):
                        return reply      # terminal ok/err
                    fr = reply[1]
                    seq = int(fr.get("seq", -1))
                    if seq != last_seq + 1:
                        raise ConnectionError(
                            "stream frame seq %d after %d — torn "
                            "stream" % (seq, last_seq))
                    last_seq = seq
                    off = int(fr["offset"])
                    if off > len(delivered):
                        raise ConnectionError(
                            "stream offset %d past the delivered "
                            "prefix (%d) — torn stream"
                            % (off, len(delivered)))
                    for i, t in enumerate(fr["tokens"]):
                        self._deliver(off + i, int(t), delivered,
                                      on_token, first)
            except Exception:
                self._drop()
                raise
            finally:
                if self._sock is sock:
                    sock.settimeout(self._timeout)

        with self._lock:
            reply = self._retry.run(attempt,
                                    describe="serve.generate.stream",
                                    on_retry=self._on_retry)
        if reply[0] != "ok":
            _, kind, msg = reply
            raise _engine.typed_error(kind, msg)
        out = reply[1]
        if isinstance(out, dict):
            # an evacuated-session reply: the caller (the fleet
            # router's migration loop) resumes the stream elsewhere —
            # the delivered prefix stands, nothing terminal to check
            return out
        # terminal cross-check: the full row's generated tail must be
        # exactly the streamed tokens (any tail past the last frame —
        # e.g. a non-streaming engine answered — is delivered now)
        gen = [int(t) for t in
               np.asarray(out).reshape(-1)[payload["prompt"].size:]]
        if gen[:len(delivered)] != delivered or len(gen) < \
                len(delivered):
            raise _engine.ServeError(
                "streamed tokens diverge from the terminal row — "
                "determinism violation (%d streamed, row tail %r...)"
                % (len(delivered), gen[:8]))
        for k in range(len(delivered), len(gen)):
            self._deliver(k, gen[k], delivered, on_token, first)
        return out

    def _deliver(self, k, tok, delivered, on_token, first):
        """Deliver emission-index ``k`` exactly once; verify replays."""
        if k < len(delivered):
            if delivered[k] != tok:
                raise _engine.ServeError(
                    "stream replay diverged at token %d: %d then %d "
                    "— determinism violation" % (k, delivered[k], tok))
            return
        delivered.append(tok)
        if first[0]:
            first[0] = False
            if _trace.enabled():
                _trace.instant("serve.stream.first_token", index=k)
        on_token(tok)

    def generate_stream(self, prompt, max_new_tokens, **kw):
        """Iterator twin of ``generate(on_token=...)``: yields each
        new token as its frame arrives; the generator's return value
        (``StopIteration.value``) is the full id row. The round trip
        runs on a helper thread so the caller pulls tokens at its own
        pace without holding the client lock hostage between
        frames."""
        import queue as _qmod
        q = _qmod.Queue()

        def run():
            try:
                row = self.generate(prompt, max_new_tokens,
                                    on_token=lambda t: q.put(("tok", t)),
                                    **kw)
                q.put(("done", row))
            except BaseException as exc:   # noqa: BLE001 — relayed
                q.put(("exc", exc))

        t = threading.Thread(target=run, daemon=True,
                             name="mxnet-serve-stream")
        t.start()
        while True:
            kind, val = q.get()
            if kind == "tok":
                yield val
            elif kind == "done":
                return val
            else:
                raise val

    def ping(self):
        try:
            self._simple_op("ping", "serve.ping")
            return True
        except _engine.ServeError:
            return False

    def stats(self):
        """Server introspection via the ``stats`` frame:
        ``{"telemetry": <registry snapshot>, "engine": <queue depth,
        drain state, buckets warmed, counters>}`` — the remote twin of
        ``telemetry.snapshot()`` + ``ServeEngine.introspect()``."""
        return self._simple_op("stats", "serve.stats")

    def _simple_op(self, op, describe):
        """One no-payload round trip (hello/warm): retried like any
        transport op, typed errors re-raised."""
        return self._roundtrip((op, None), describe)

    def hello(self):
        """The registration frame: ``{"role": ..., "engine": <live
        engine state>}`` — how a fleet router learns a replica's
        declared buckets and capabilities at add_replica time."""
        return self._simple_op("hello", "serve.hello")

    def warm(self):
        """Ask the server to pre-compile every declared bucket
        (``ServeEngine.warmup``); returns the warmed bucket list. The
        router calls this on a freshly recycled replica before
        readmitting it."""
        return self._simple_op("warm", "serve.warm")

    def evacuate(self):
        """The ``evacuate`` frame: export every active decode session
        off the replica — each in-flight generate answers with its
        portable state instead of a row, and queued admissions fail
        for replay. Returns the number of sessions exported. The fleet
        router sends this at the start of a migrating recycle so the
        drain is bounded by export+import cost, not longest-sequence
        completion (docs/robustness.md, fleet failure semantics)."""
        return self._simple_op("evacuate", "serve.evacuate")

    def close(self):
        with self._lock:
            self._drop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
