"""Autoregressive generation — KV-cache decoding for the transformer LM.

New TPU-native capability: the 2017 reference's incremental-inference
story was RNNCell step-wise unrolling (`rnn/rnn_cell.py` begin_state /
__call__ chains); the transformer analogue is a KV cache threaded as
auxiliary state through `models.transformer.get_decode_symbol`'s graph
(`ops/attention.py` `_contrib_CachedAttention`).

Design: two jit specializations, bucketing-style — one for the prefill
chunk (B, P) and one for the single-token step (B, 1) — each a whole
-graph XLA program with the caches as donated-in-spirit aux arrays kept
on device between steps. Sampling (greedy / temperature / top-k) runs
on device too; only the chosen token ids come back to the host.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from . import telemetry as _telemetry
from .executor import _graph_eval_fn
from .models import transformer

__all__ = ["Generator", "kv_blob_nbytes", "replay_key"]


def kv_blob_nbytes(blob):
    """Payload bytes of an :meth:`Generator.export_kv_rows` blob — the
    cache-row arrays only (framing/pickle overhead excluded), the
    figure the ``serve.prefill.blob_bytes`` histogram and the disagg
    bench's int8-vs-bf16 ratio report."""
    return sum(int(a.nbytes) for a in blob["rows"].values())


class Generator:
    """Drives `transformer.get_decode_symbol` with params from a trained
    `transformer.get_symbol` checkpoint (same parameter names).

    Parameters
    ----------
    arg_params : dict name -> array-like (NDArray, np or jnp)
        Trained parameters (e.g. `Module.get_params()[0]` or
        `load_checkpoint`'s arg_params).
    vocab_size, num_layers, num_heads, dim, ffn_hidden :
        Architecture — must match the training symbol.
    max_len : int
        KV-cache capacity (prompt + generated tokens must fit). With
        SSM layers (block_type) the state itself is O(1), but max_len
        still bounds total sequence length — it sizes the attention
        layers of a mixed stack and the learned position table.
    block_type : "attention" (default), "ssm", or per-layer sequence
        — SSM layers hold one (num_heads, head_dim, head_dim) f32
        state blob per slot instead of (max_len, head_dim) KV rows
        (see ops/ssm.py and models/transformer.get_decode_symbol for
        knob composition rules).
    batch_size : int
    dtype : optional compute dtype for params/caches (e.g. "bfloat16").
    mesh : optional jax.sharding.Mesh for multi-chip serving. Params
        place by the TP rule (`parallel.sharding.param_sharding`:
        Megatron column-parallel weights over a 'model' axis, experts
        over 'expert'), KV caches shard heads over 'model' and batch
        over 'data'; GSPMD inserts the collectives.
    """

    def __init__(self, arg_params, vocab_size, max_len, num_layers=2,
                 num_heads=4, dim=128, ffn_hidden=None, batch_size=1,
                 dtype=None, num_experts=0, mesh=None, quantize=None,
                 pos_encoding="learned", attention_window=0,
                 rolling_cache=False, num_kv_heads=None,
                 quantize_kv=False, block_type="attention"):
        from .parallel import sharding as shd

        if quantize not in (None, "int8"):
            raise ValueError("quantize must be None or 'int8', got %r"
                             % (quantize,))
        if quantize_kv and rolling_cache:
            raise ValueError("quantize_kv is not supported with "
                             "rolling_cache")
        self.vocab_size = int(vocab_size)
        if self.vocab_size > 2 ** 24:
            # token ids ride the float32 "data" input convention;
            # integers past 2^24 stop being exactly representable and
            # would silently alias (positions get the same guard in
            # _forward)
            raise ValueError(
                "vocab_size=%d exceeds the float32-exact id range "
                "(2^24); larger vocabularies need integer id plumbing"
                % self.vocab_size)
        self.max_len = int(max_len)
        self.batch_size = int(batch_size)
        self.num_layers = int(num_layers)
        self.mesh = mesh
        self._window = int(attention_window or 0)
        self._rolling = bool(rolling_cache)
        head_dim = dim // num_heads
        kv_heads = int(num_kv_heads or num_heads)
        # block_type validation happens in get_decode_symbol below;
        # the flags steer slot-state accounting and the serving-layer
        # compatibility refusals (speculative drafts, prefill grouping)
        self._btypes = transformer._canon_block_types(block_type,
                                                      num_layers)
        self._has_ssm = "ssm" in self._btypes
        # kept for twin-symbol builders (serve/decode.py rebuilds this
        # graph with per_row_pos=True against the SAME parameters)
        self._decode_opts = dict(
            vocab_size=vocab_size, max_len=max_len,
            num_layers=num_layers, num_heads=num_heads, dim=dim,
            ffn_hidden=ffn_hidden, num_experts=num_experts,
            quantized=quantize is not None,
            compute_dtype=str(dtype) if dtype else None,
            pos_encoding=pos_encoding,
            attention_window=attention_window,
            rolling_cache=rolling_cache, num_kv_heads=num_kv_heads,
            kv_quantize=quantize_kv, block_type=block_type)
        sym = transformer.get_decode_symbol(**self._decode_opts)
        if quantize:
            arg_params = _quantize_weights(
                arg_params, sym.list_arguments())
        self._sym = sym
        eval_fn = _graph_eval_fn(sym, mesh=mesh)
        self._eval_fn = eval_fn
        self._step_fn = jax.jit(
            lambda args, aux, rng: eval_fn(args, aux, rng, False))
        self._loop_cache = {}

        def _raw(name, v):
            arr = jnp.asarray(getattr(v, "_data", v))
            # int8 weights and their f32 scales keep their dtypes (the
            # whole point of quantize= is the int8 HBM footprint)
            if dtype and jnp.issubdtype(arr.dtype, jnp.floating) and \
                    not name.endswith("_scale"):
                arr = arr.astype(dtype)
            if mesh is not None:
                arr = jax.device_put(
                    arr, shd.param_sharding(mesh, name, arr.shape))
            return arr

        wanted = set(sym.list_arguments())
        self._params = {k: _raw(k, v) for k, v in arg_params.items()
                        if k in wanted}
        # cache placement: batch over 'data', heads over 'model'
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            spec = [None, None, None, None]
            if "data" in mesh.axis_names and \
                    batch_size % mesh.shape["data"] == 0:
                spec[0] = "data"
            if "model" in mesh.axis_names and \
                    kv_heads % mesh.shape["model"] == 0:
                spec[1] = "model"
            self._cache_sharding = NamedSharding(mesh, P(*spec))
            self._scale_sharding = NamedSharding(mesh, P(*spec[:3]))
        else:
            self._cache_sharding = None
            self._scale_sharding = None
        missing = wanted - set(self._params) - {
            "data", "positions", "cache_pos"}
        if missing:
            raise ValueError("Generator missing parameters: %s"
                             % sorted(missing))
        self._pos_rows = None
        if pos_encoding == "learned":
            pos_rows = self._params["pos_embed_weight"].shape[0]
            self._pos_rows = int(pos_rows)
            if not self._rolling and pos_rows < self.max_len:
                # the decode symbol's position lookup is
                # take(mode='clip'); without this check, positions past
                # the trained table would silently reuse its last row
                raise ValueError(
                    "max_len=%d exceeds the trained position table "
                    "(%d rows) — generation past it would silently "
                    "clip" % (self.max_len, pos_rows))
        # cache dtype follows the FLOAT params — under quantize="int8"
        # the dict also holds int8 weights, and an int8 cache would
        # silently truncate k/v (cached_attention casts to cache dtype)
        cache_dtype = jnp.dtype(dtype) if dtype else next(
            v.dtype for v in self._params.values()
            if jnp.issubdtype(v.dtype, jnp.floating))
        # GQA: caches hold only the kv heads (the memory win)
        self._cache_shape = (self.batch_size, kv_heads, self.max_len,
                             head_dim)
        self._cache_dtype = cache_dtype
        # SSM layers: one (B, H, hd, hd) recurrent-state blob each,
        # ALWAYS f32 regardless of compute dtype — the bit-identical-
        # state rule (ops/ssm.py) is stated in f32, and the blob is so
        # small (no length axis) that a bf16 diet would save ~nothing
        self._state_shape = (self.batch_size, int(num_heads),
                             head_dim, head_dim)
        # quantize_kv: k/v live int8 with per-token f32 scale caches —
        # halves decode's dominant HBM stream (the cache is re-read
        # every step; each weight only once)
        self._quantize_kv = bool(quantize_kv)
        # static sizing gauge: bytes of decode state one batch row
        # (= one serving slot) owns across the whole aux pytree,
        # whatever its kind — KV rows, int8 KV + scales, or SSM state
        # blobs. ContinuousDecoder re-publishes the same gauge from
        # its live pool, and the MXNET_DECODE_SLOTS sizing hint
        # divides an HBM budget by it (shape math only, no allocation)
        _telemetry.gauge("serve.decode.kv_bytes_per_slot").set(
            self.state_bytes_per_slot())

    def _aux_spec(self, name):
        """(shape, dtype) of one decode-state aux — THE single
        classification _fresh_aux (allocation), kv_cache_bytes
        (sizing) and _aux_row_shape (export/import) read, so the
        gauge/slot math can never drift from what is actually
        allocated."""
        if name.endswith("_state"):
            # SSM recurrent state: fixed-size blob, no length axis
            return self._state_shape, jnp.dtype(jnp.float32)
        if name.endswith(("_k_scale", "_v_scale")):
            # per-token dequant scales for the int8 caches
            return self._cache_shape[:3], jnp.dtype(jnp.float32)
        if self._quantize_kv:
            return self._cache_shape, jnp.dtype(jnp.int8)
        return self._cache_shape, jnp.dtype(self._cache_dtype)

    def _aux_row_shape(self, name, pos):
        """Shape of ONE batch row's exported state for aux ``name`` at
        sequence position ``pos``: length-indexed caches ship their
        ``[:, :pos]`` prefix; SSM state blobs have no length axis and
        ship whole (the O(1)-handoff property — blob bytes constant in
        prompt length). Shared by export_kv_rows and the serving
        side's import validation so the two ends of a handoff can
        never disagree."""
        shape, _ = self._aux_spec(name)
        if name.endswith("_state"):
            return shape[1:]
        return (shape[1], pos) + shape[3:]

    def kv_cache_bytes(self):
        """Total bytes of the decode-state aux pytree (every layer's
        k/v caches plus their per-token f32 scale caches under
        quantize_kv, and/or SSM state blobs) at this Generator's
        (batch_size, max_len) — computed from shapes/dtypes alone."""
        total = 0
        for name in self._sym.list_auxiliary_states():
            shape, dtype = self._aux_spec(name)
            n = 1
            for d in shape:
                n *= int(d)
            total += n * dtype.itemsize
        return total

    def state_bytes_per_slot(self):
        """Bytes of decode state ONE batch row (= one serving slot)
        owns — the state-agnostic number behind the
        ``serve.decode.kv_bytes_per_slot`` gauge (name kept for
        dashboard compatibility), ``describe(hbm_budget=)`` and
        ``MXNET_DECODE_SLOTS=auto`` slot sizing, and
        tools/telemetry_report.py's bytes/slot line. O(max_len) for
        attention layers; O(1) for SSM layers."""
        return self.kv_cache_bytes() // self.batch_size

    def export_kv_rows(self, aux, row, pos):
        """Serialize ONE sequence's decode state out of an aux
        pytree — the portable decode state of the prefill/decode
        disaggregation handoff (docs/serving.md §disaggregated
        prefill; the arXiv 2603.09555 "portable O(1) cache" enabler).

        ``aux``: a state pytree this Generator produced (typically the
        prefill output); ``row``: which batch row to export; ``pos``:
        how many tokens of state that row holds. Length-indexed caches
        contribute their ``[row, :, :pos, ...]`` prefix — the int8 k/v
        rows AND their per-token f32 scale rows under ``quantize_kv``,
        or the bf16/f32 rows otherwise; SSM state blobs contribute
        ``[row]`` WHOLE (no length axis — the blob's bytes are
        constant in ``pos``, which is what makes an SSM handoff O(1)
        on the wire). Everything ships as numpy with the device dtype
        preserved bit-for-bit, so a remote
        :meth:`ContinuousDecoder.import_kv_rows` scatter is
        device-roundtrip-exact. Cache entries past ``pos`` never ship:
        they are unattended garbage by the cache-position mask, and
        the blob is what moves over the wire.

        Returns ``{"v": 1, "pos": pos, "rows": {name: np.ndarray}}``.
        """
        if self._rolling:
            raise ValueError(
                "export_kv_rows does not support rolling caches (a "
                "circular buffer's rows are not position-aligned, so "
                "a prefix slice is not the sequence's state)")
        row, pos = int(row), int(pos)
        if not 0 <= row < self.batch_size:
            raise ValueError("row %d out of range for batch_size=%d"
                             % (row, self.batch_size))
        if not 1 <= pos <= self.max_len:
            raise ValueError("pos %d out of range for max_len=%d"
                             % (pos, self.max_len))
        wanted = set(self._sym.list_auxiliary_states())
        if set(aux) != wanted:
            raise ValueError(
                "aux pytree names %s do not match this Generator's "
                "caches %s" % (sorted(aux), sorted(wanted)))
        # ONE fused slice program per pos (row rides as a traced
        # scalar), then one device_get for the whole pytree — the
        # handoff's export half is a single dispatch, not 2x-per-layer
        # eager slices (measured ~3x cheaper; the handoff budget is
        # docs/serving.md's <=15%-of-one-prefill)
        fn = self._loop_cache.get(("export", pos))
        if fn is None:
            def _one(a, r, n):
                # SSM state blobs have no length axis: ship whole
                # (slicing [:, :pos] would cut the HEAD axis)
                full = jax.lax.dynamic_index_in_dim(
                    a[n], r, axis=0, keepdims=False)
                return full if n.endswith("_state") else full[:, :pos]
            fn = jax.jit(lambda a, r: {n: _one(a, r, n) for n in a})
            self._loop_cache[("export", pos)] = fn
        host = jax.device_get(fn(aux, jnp.int32(row)))
        rows = {}
        for name in sorted(wanted):
            _, dtype = self._aux_spec(name)
            want = self._aux_row_shape(name, pos)
            arr = np.asarray(host[name])
            if arr.dtype != dtype or arr.shape != want:
                raise ValueError(
                    "cache %r is %s%r, expected %s%r — the aux pytree "
                    "does not belong to this Generator"
                    % (name, arr.dtype, arr.shape, dtype, want))
            rows[name] = arr
        return {"v": 1, "pos": pos, "rows": rows}

    @staticmethod
    def _check_sampling(temperature, top_k, top_p):
        """top_k/top_p only act on the sampled path; at temperature<=0
        decoding is greedy and they would be silently ignored — make
        that contract explicit instead."""
        if (top_k or top_p) and not (temperature
                                     and float(temperature) > 0):
            raise ValueError(
                "top_k/top_p require temperature > 0 (temperature<=0 "
                "decodes greedily and would silently ignore them)")

    def _check_prompt(self, prompt, max_new_tokens):
        prompt = np.asarray(prompt)
        if prompt.ndim != 2 or prompt.shape[0] != self.batch_size:
            raise ValueError("prompt must be (batch_size, P), got %r"
                             % (prompt.shape,))
        P = prompt.shape[1]
        if self._rolling:
            # circular cache: generation length is unbounded up to
            # the float32-exact position range, 2^24 (pair with RoPE);
            # the capacity only has to fit one window plus the prefill
            # chunk's in-flight overwrites
            if self._window + P - 1 > self.max_len:
                raise ValueError(
                    "rolling cache capacity max_len=%d must be >= "
                    "window (%d) + prompt (%d) - 1"
                    % (self.max_len, self._window, P))
            if self._pos_rows is not None and \
                    P + max_new_tokens > self._pos_rows:
                raise ValueError(
                    "learned positions cap total length at the table "
                    "(%d rows); use pos_encoding='rope' for unbounded "
                    "rolling generation" % self._pos_rows)
        elif P + max_new_tokens > self.max_len:
            raise ValueError(
                "prompt (%d) + max_new_tokens (%d) exceeds the cache "
                "capacity max_len=%d" % (P, max_new_tokens,
                                         self.max_len))
        return prompt, P

    def _fresh_aux(self):
        aux = {}
        for name in self._sym.list_auxiliary_states():
            shape, dtype = self._aux_spec(name)
            z = jnp.zeros(shape, dtype)
            shard = self._scale_sharding if len(shape) == 3 \
                else self._cache_sharding
            if shard is not None:
                z = jax.device_put(z, shard)
            aux[name] = z
        return aux

    def _forward(self, aux, tokens, pos):
        """tokens: (B, Tnew) int array; pos: python int."""
        tn = tokens.shape[1]
        if pos + tn > 2 ** 24:
            # positions ride the float32 input convention; past 2^24
            # consecutive integers stop being representable (RoPE
            # angles and circular-slot indices would silently corrupt)
            raise ValueError(
                "position %d exceeds the float32-exact range (2^24); "
                "longer rolling generation needs integer position "
                "plumbing" % (pos + tn))
        args = dict(self._params)
        args["data"] = jnp.asarray(tokens, jnp.float32)
        args["positions"] = jnp.arange(pos, pos + tn, dtype=jnp.float32)
        args["cache_pos"] = jnp.full((1,), pos, jnp.float32)
        outs, new_aux = self._step_fn(args, aux, jax.random.PRNGKey(0))
        return outs[0], new_aux     # logits (B, Tnew, V)

    def log_likelihood(self, tokens):
        """Teacher-forcing score: per-row sum of log P(t_{i+1} | t_<=i)
        over the sequence, via one prefill pass. tokens: (B, Tseq) with
        Tseq <= max_len; returns (B,) float64. The serving-side eval
        utility (perplexity = exp(-ll / (Tseq - 1)))."""
        tokens = np.asarray(tokens)
        if tokens.ndim != 2 or tokens.shape[0] != self.batch_size:
            raise ValueError("tokens must be (batch_size, T), got %r"
                             % (tokens.shape,))
        if tokens.shape[1] > self.max_len:
            raise ValueError("sequence length %d exceeds max_len=%d"
                             % (tokens.shape[1], self.max_len))
        if self._pos_rows is not None and \
                tokens.shape[1] > self._pos_rows:
            raise ValueError(
                "sequence length %d exceeds the trained position "
                "table (%d rows) — scoring would silently clip"
                % (tokens.shape[1], self._pos_rows))
        logits, _ = self._forward(self._fresh_aux(), tokens, 0)
        logp = np.asarray(jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1))     # (B, T, V)
        nxt = tokens[:, 1:].astype(np.int64)
        rows = np.arange(self.batch_size)[:, None]
        cols = np.arange(tokens.shape[1] - 1)[None, :]
        return logp[rows, cols, nxt].sum(axis=1).astype(np.float64)

    def beam_search(self, prompt, max_new_tokens, beam_size=4,
                    length_penalty=0.0, eos_id=None):
        """Beam decoding over the same KV-cache graph.

        Beams fold into the batch dimension (caches run at B*W); after
        each step the caches are reordered by the surviving beams'
        parent indices (a gather on the cache batch axis). Returns
        (B, P + n) ids — the highest-scoring beam per row, scores
        normalized by (generated length) ** length_penalty.

        eos_id: a beam that emits eos is frozen (only eos continues it,
        at no score change); search stops early when every beam of
        every row is frozen."""
        prompt, P = self._check_prompt(prompt, max_new_tokens)
        B, W, V = self.batch_size, int(beam_size), self.vocab_size
        if W < 1:
            raise ValueError("beam_size must be >= 1")

        # prefill ONCE at batch B, then tile caches/logits to the
        # B*W beam batch — the prompt forward is the expensive part
        # and all beams share it
        aux = self._fresh_aux()
        logits, aux = self._forward(aux, prompt, 0)
        aux = {k: jnp.repeat(v, W, axis=0) for k, v in aux.items()}
        last = np.repeat(np.asarray(jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32), axis=-1)), W, axis=0)

        # duplicate beams would tie forever: start all but beam 0 at
        # -inf so step 1 picks W DISTINCT first tokens
        scores = np.full((B, W), -np.inf)
        scores[:, 0] = 0.0
        tokens = np.zeros((B, W, 0), np.int64)
        frozen = np.zeros((B, W), bool)

        for t in range(max_new_tokens):
            logp = last.reshape(B, W, V).copy()
            if eos_id is not None:
                # frozen beams: only eos continues, for free
                logp[frozen] = -np.inf
                logp[frozen, eos_id] = 0.0
            cand = scores[:, :, None] + logp           # (B, W, V)
            flat = cand.reshape(B, W * V)
            top = np.argsort(-flat, axis=1)[:, :W]     # (B, W)
            parent = top // V
            tok = top % V
            scores = np.take_along_axis(flat, top, axis=1)
            tokens = np.concatenate(
                [np.take_along_axis(
                    tokens, parent[:, :, None], axis=1),
                 tok[:, :, None]], axis=2)
            if eos_id is not None:
                frozen = np.take_along_axis(frozen, parent, axis=1) \
                    | (tok == eos_id)
                if frozen.all():
                    break
            if t + 1 == max_new_tokens:
                break
            # reorder caches to the surviving beams' parents and feed
            # the chosen tokens
            flat_idx = (np.arange(B)[:, None] * W + parent).reshape(-1)
            idx_dev = jnp.asarray(flat_idx)
            aux = {k: jnp.take(v, idx_dev, axis=0)
                   for k, v in aux.items()}
            logits, aux = self._forward(aux, tok.reshape(-1, 1), P + t)
            last = np.asarray(jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32), axis=-1))

        gen_len = tokens.shape[2]
        if length_penalty:
            # per-beam effective length: up to the first eos (frozen
            # beams pad with free eos tokens that must not count)
            lens = np.full((B, W), gen_len, np.float64)
            if eos_id is not None:
                is_eos = tokens == eos_id              # (B, W, t)
                has = is_eos.any(axis=2)
                lens[has] = is_eos.argmax(axis=2)[has] + 1
            norm = scores / np.maximum(1.0,
                                       lens) ** float(length_penalty)
        else:
            norm = scores
        best = norm.argmax(axis=1)                     # (B,)
        out = tokens[np.arange(B), best]               # (B, gen_len)
        return np.concatenate([prompt.astype(np.int64), out], axis=1)

    def beam_search_on_device(self, prompt, max_new_tokens,
                              beam_size=4, length_penalty=0.0,
                              eos_id=None):
        """beam_search compiled into ONE device program: prefill + a
        lax.scan whose body does the (W*V) top-k, reorders the token
        history AND the KV caches by the surviving beams' parent
        indices (a batch-axis gather), and runs the next forward — no
        per-token host round-trips (the host-loop beam_search pays one
        dispatch per step, which through a remote link is RTT-bound).

        Same selection semantics as beam_search; fixed trip count (eos
        freezes beams — they extend with free eos tokens — but cannot
        early-exit a scan, so the output is always P + n long where the
        host loop may return shorter once every beam froze). Each
        distinct
        (prompt_len, max_new_tokens, beam_size, eos_id) compiles once.
        Returns (B, P + n) ids."""
        prompt, P = self._check_prompt(prompt, max_new_tokens)
        B, W = self.batch_size, int(beam_size)
        if W < 1:
            raise ValueError("beam_size must be >= 1")
        n = int(max_new_tokens)
        if n == 0:
            return np.asarray(prompt, np.int64)
        fn = self._beam_loop(P, n, W,
                             -1 if eos_id is None else int(eos_id))
        tokens, scores = fn(self._params,
                            jnp.asarray(prompt, jnp.float32))
        tokens = np.asarray(tokens)            # (B, W, n)
        scores = np.asarray(scores)            # (B, W)

        # length-penalty + best-beam selection on host, sharing the
        # host beam_search's exact formulation
        if length_penalty:
            lens = np.full((B, W), n, np.float64)
            if eos_id is not None:
                is_eos = tokens == eos_id
                has = is_eos.any(axis=2)
                lens[has] = is_eos.argmax(axis=2)[has] + 1
            norm = scores / np.maximum(1.0,
                                       lens) ** float(length_penalty)
        else:
            norm = scores
        best = norm.argmax(axis=1)
        out = tokens[np.arange(B), best].astype(np.int64)
        return np.concatenate([prompt.astype(np.int64), out], axis=1)

    def _beam_loop(self, P, n, W, eos):
        key_ = ("beam", P, n, W, eos)
        cached = self._loop_cache.get(key_)
        if cached is not None:
            return cached
        eval_fn = self._eval_fn
        B, V = self.batch_size, self.vocab_size

        # params as jit arguments, not closures (see _device_loop)
        def fwd(params, aux, data, pos):
            args = dict(params)
            args["data"] = data.astype(jnp.float32)
            args["positions"] = jnp.full((1,), pos, jnp.float32)
            args["cache_pos"] = jnp.full((1,), pos, jnp.float32)
            outs, aux = eval_fn(args, aux, jax.random.PRNGKey(0),
                                False)
            return jax.nn.log_softmax(
                outs[0][:, -1].astype(jnp.float32), axis=-1), aux

        def select(logp, scores, tokens, frozen, i):
            """One beam step: (W*V) top-k + history reorder."""
            if eos >= 0:
                free = jnp.full((V,), -jnp.inf).at[eos].set(0.0)
                logp = jnp.where(frozen[:, :, None], free[None, None],
                                 logp)
            flat = (scores[:, :, None] + logp).reshape(B, W * V)
            top_scores, top_idx = jax.lax.top_k(flat, W)
            parent = top_idx // V
            tok = top_idx % V
            tokens = jnp.take_along_axis(tokens, parent[:, :, None],
                                         axis=1)
            tokens = tokens.at[:, :, i].set(tok.astype(jnp.int32))
            if eos >= 0:
                frozen = jnp.take_along_axis(frozen, parent, axis=1) \
                    | (tok == eos)
            return top_scores, tokens, frozen, parent, tok

        def run(params, prompt):
            aux = self._fresh_aux()
            args = dict(params)
            args["data"] = prompt
            args["positions"] = jnp.arange(P, dtype=jnp.float32)
            args["cache_pos"] = jnp.zeros((1,), jnp.float32)
            outs, aux = eval_fn(args, aux, jax.random.PRNGKey(0),
                                False)
            logp = jax.nn.log_softmax(
                outs[0][:, -1].astype(jnp.float32), axis=-1)  # (B, V)
            # beams fold into batch: caches at B*W, all sharing the
            # prefill; duplicate beams start at -inf so step 1 picks W
            # distinct first tokens (host beam_search's trick)
            aux = {k: jnp.repeat(v, W, axis=0) for k, v in aux.items()}
            logp = jnp.repeat(logp, W, axis=0).reshape(B, W, V)
            scores = jnp.where(jnp.arange(W) == 0, 0.0,
                               -jnp.inf)[None, :].repeat(B, axis=0)
            tokens = jnp.zeros((B, W, n), jnp.int32)
            frozen = jnp.zeros((B, W), bool)

            def body(carry, i):
                aux, logp, scores, tokens, frozen = carry
                scores, tokens, frozen, parent, tok = select(
                    logp, scores, tokens, frozen, i)
                flat_idx = (jnp.arange(B)[:, None] * W
                            + parent).reshape(-1)
                aux = {k: jnp.take(v, flat_idx, axis=0)
                       for k, v in aux.items()}
                logp, aux = fwd(params, aux, tok.reshape(-1, 1),
                                P + i)
                logp = logp.reshape(B, W, V)
                return (aux, logp, scores, tokens, frozen), None

            # final step needs no forward (host beam_search breaks
            # before its last forward the same way)
            (aux, logp, scores, tokens, frozen), _ = jax.lax.scan(
                body, (aux, logp, scores, tokens, frozen),
                jnp.arange(n - 1))
            scores, tokens, frozen, _, _ = select(
                logp, scores, tokens, frozen, n - 1)
            return tokens, scores

        fn = jax.jit(run)
        self._loop_cache[key_] = fn
        return fn

    def generate_speculative(self, draft, prompt, max_new_tokens,
                             lookahead=4, temperature=0.0, top_k=None,
                             top_p=None, seed=0):
        """Speculative decoding: a small `draft` Generator proposes
        `lookahead` tokens per round; this (target) model verifies
        them in ONE forward and keeps the longest matching prefix plus
        its own next token. Output is EXACTLY this model's own
        ``generate`` continuation for the same sampling args — the
        draft only changes how many target forwards it takes.

        Sampling uses common-random-numbers verification, a
        deterministic specialisation of speculative rejection
        sampling: the token at emission index j is ALWAYS
        ``_pick_token(target_logits_j, sub_j)`` where ``sub_j`` is the
        (j+1)-th split of ``PRNGKey(seed)`` — the exact key discipline
        of ``generate``'s loop (``replay_key``). The draft proposes
        with the SAME ``sub_j`` on its own logits, so a proposal is
        accepted exactly when it equals the target's pick under shared
        noise; acceptance rate tracks how closely the draft's filtered
        distribution matches the target's. Output is therefore
        byte-identical to ``generate(seed=...)`` — trivially
        distribution-exact, and replayable token-for-token (the
        serving fleet's failover contract rides on this).

        Cache rollback is free by construction: `_contrib_
        CachedAttention` writes at `cache_pos` and masks columns
        beyond `pos + row`, so rejected speculative entries are simply
        overwritten by the next append and can never be attended.

        Exactness caveat: "exactly generate()" holds up to XLA kernel
        numerics — the chunked verify forward (Tnew = lookahead+1) and
        the one-token decode forward may differ at the last ulp, so a
        near-exact logit TIE can in principle resolve differently than
        generate() would. Irrelevant for real sampling temperatures
        and not observed in tests; noted for bit-exactness audits.

        draft: a Generator with the same vocab/batch (typically fewer
        layers/dims — :meth:`truncated_draft`). Returns
        (B, P + max_new_tokens) ids. Batch rows advance in lockstep
        (the accepted length each round is the minimum across rows) —
        the serving decoder's per-slot rounds lift that restriction;
        B=1 is the classic setting here."""
        if draft.vocab_size != self.vocab_size or \
                draft.batch_size != self.batch_size:
            raise ValueError("draft must share vocab_size/batch_size "
                             "with the target")
        if self._rolling or getattr(draft, "_rolling", False):
            # rejected speculative slots could alias older positions in
            # a circular buffer (p_s mis-attribution) — not supported
            raise ValueError("speculative decoding is not supported "
                             "with rolling caches")
        if self._has_ssm or getattr(draft, "_has_ssm", False):
            # the recurrent state is mutated by EVERY fed token and
            # has no per-position rows — rejected speculative tokens
            # cannot be rolled back out of it
            raise ValueError(
                "speculative decoding is not supported with ssm "
                "blocks: the recurrent state has no per-position "
                "entries to overwrite, so rejected proposals would "
                "corrupt it (use attention blocks for speculative "
                "serving)")
        self._check_sampling(temperature, top_k, top_p)
        prompt, P = self._check_prompt(prompt, max_new_tokens)
        if P + max_new_tokens > draft.max_len:
            raise ValueError("draft max_len=%d too small for %d tokens"
                             % (draft.max_len, P + max_new_tokens))
        gamma = max(1, int(lookahead))
        sampled = bool(temperature and float(temperature) > 0)
        key = jax.random.PRNGKey(int(seed or 0)) if sampled else None

        # invariant: before each round, both caches hold a VALID prefix
        # covering [0, len(out) - 1) — every round's feeds start at
        # position len(out) - 1 and overwrite any stale speculative
        # entries beyond the accepted boundary
        t_aux = self._fresh_aux()
        d_aux = draft._fresh_aux()
        if P > 1:
            _, t_aux = self._forward(t_aux, prompt[:, :P - 1], 0)
            _, d_aux = draft._forward(d_aux, prompt[:, :P - 1], 0)
        out = prompt.astype(np.int64)

        while out.shape[1] - P < max_new_tokens:
            pos = out.shape[1]
            budget = max_new_tokens - (pos - P)
            g = min(gamma, budget - 1)      # leave room for the bonus
            # peek this round's subs WITHOUT advancing the stream: the
            # draft proposes with the same sub the target will verify
            # with, and the key only advances by what is emitted
            subs, k = [], key
            if sampled:
                for _ in range(g + 1):
                    k, sub = jax.random.split(k)
                    subs.append(sub)
            # draft proposes g tokens, continuing from the last emitted
            cur = out[:, -1]
            props = []
            for i in range(g):
                dl, d_aux = draft._forward(d_aux, cur[:, None],
                                           pos - 1 + i)
                cur = np.asarray(_pick_token(
                    dl[:, -1], temperature, top_k,
                    subs[i] if sampled else None, top_p))
                props.append(cur)
            # ONE target forward scores last_emitted + all proposals:
            # tokens at positions pos-1 .. pos+g-1, logits predicting
            # positions pos .. pos+g
            chunk = np.concatenate(
                [out[:, -1:]] + [p[:, None] for p in props], axis=1)
            tl, t_aux = self._forward(t_aux, chunk, pos - 1)
            picks = np.stack(
                [np.asarray(_pick_token(
                    tl[:, c], temperature, top_k,
                    subs[c] if sampled else None, top_p))
                 for c in range(g + 1)], axis=1)          # (B, g+1)
            # accept while the draft token at pos+i matches the target
            # pick for pos+i; lockstep across the batch
            acc = 0
            while acc < g and bool(
                    (props[acc] == picks[:, acc]).all()):
                acc += 1
            # emit the accepted tokens + the target's own next token
            # (correctly conditioned: its inputs are the accepted
            # prefix — accepted proposals ARE the target's picks, so
            # every emitted token is exactly what generate() picks)
            out = np.concatenate([out, picks[:, :acc + 1]], axis=1)
            if sampled:
                # one split per EMITTED token, whatever path drew it
                for _ in range(acc + 1):
                    key, _ = jax.random.split(key)
            if acc == g and g > 0 and \
                    out.shape[1] - P < max_new_tokens:
                # full acceptance: the draft never ingested its own
                # last proposal's k/v (its loop stops after computing
                # it) — feed it so the invariant holds next round
                # (skipped when the budget is exhausted: one whole
                # dispatch saved on the final round)
                _, d_aux = draft._forward(d_aux, props[-1][:, None],
                                          pos + g - 1)
        return out[:, :P + max_new_tokens]

    def truncated_draft(self, num_layers=1, batch_size=None,
                        max_len=None):
        """A draft Generator that runs only the FIRST ``num_layers``
        transformer blocks of THIS model, sharing its weights — the
        zero-extra-checkpoint speculative draft. Works because
        Generator filters ``arg_params`` down to what its own symbol
        lists: a shallower decode symbol's argument names are a strict
        subset of the full stack's (layer0..k-1 + embed/head), so the
        truncated model is literally the full model with the late
        blocks skipped. Residual connections make that a coarse but
        real approximation; acceptance rate measures how much the
        dropped layers change the pick.

        ``batch_size``/``max_len`` default to this model's (the
        serving decoder wants the same slot-pool shape; give the draft
        a larger max_len only if you need extra lookahead headroom)."""
        o = self._decode_opts
        if o["quantized"]:
            raise ValueError(
                "truncated_draft is not supported on a quantize='int8' "
                "Generator (its stored weights are already int8; build "
                "the draft from the float checkpoint instead)")
        if self._rolling:
            raise ValueError("truncated_draft is not supported with "
                             "rolling caches (speculative decoding "
                             "rejects rolling models outright)")
        if self._has_ssm:
            raise ValueError(
                "truncated_draft is not supported with ssm blocks "
                "(speculative decoding rejects SSM models outright — "
                "the recurrent state has no rollback)")
        nl = int(num_layers)
        if not 1 <= nl <= self.num_layers:
            raise ValueError(
                "truncated_draft num_layers=%d out of range 1..%d"
                % (nl, self.num_layers))
        return Generator(
            self._params, o["vocab_size"],
            int(max_len) if max_len else o["max_len"],
            num_layers=nl, num_heads=o["num_heads"], dim=o["dim"],
            ffn_hidden=o["ffn_hidden"],
            batch_size=int(batch_size) if batch_size
            else self.batch_size,
            dtype=o["compute_dtype"], num_experts=o["num_experts"],
            mesh=self.mesh, pos_encoding=o["pos_encoding"],
            attention_window=o["attention_window"],
            num_kv_heads=o["num_kv_heads"],
            quantize_kv=o["kv_quantize"])

    def generate_speculative_on_device(self, draft, prompt,
                                       max_new_tokens, lookahead=4,
                                       return_rounds=False,
                                       temperature=0.0, top_k=None,
                                       top_p=None, seed=0):
        """generate_speculative compiled into ONE device program: a
        lax.while_loop whose body runs the draft's propose scan, the
        target's single verify forward, the acceptance rule, and the
        emit — both models' parameters and caches live in one XLA
        program, no host dispatches per round. Output is exactly the
        target's own generate() continuation for the same sampling
        args (same common-random-numbers rule as the host loop; pinned
        against it in tests).

        Static-shape discipline: every round proposes the FULL
        `lookahead` and emissions are clamped to the remaining budget,
        so both caches need headroom — max_len >= P + max_new_tokens +
        lookahead on target AND draft (validated here)."""
        if draft.vocab_size != self.vocab_size or \
                draft.batch_size != self.batch_size:
            raise ValueError("draft must share vocab_size/batch_size "
                             "with the target")
        if self._rolling or getattr(draft, "_rolling", False):
            raise ValueError("speculative decoding is not supported "
                             "with rolling caches")
        if self._has_ssm or getattr(draft, "_has_ssm", False):
            raise ValueError(
                "speculative decoding is not supported with ssm "
                "blocks: the recurrent state has no per-position "
                "entries to overwrite, so rejected proposals would "
                "corrupt it (use attention blocks for speculative "
                "serving)")
        self._check_sampling(temperature, top_k, top_p)
        prompt, P = self._check_prompt(prompt, max_new_tokens)
        n = int(max_new_tokens)
        if n == 0:
            toks = np.asarray(prompt, np.int64)
            return (toks, 0) if return_rounds else toks
        g = max(1, int(lookahead))
        need = P + n + g
        for which, who in (("target", self), ("draft", draft)):
            if need > who.max_len:
                raise ValueError(
                    "%s max_len=%d too small: on-device speculative "
                    "needs prompt (%d) + max_new_tokens (%d) + "
                    "lookahead (%d) headroom (fixed-shape rounds may "
                    "overrun the budget by up to lookahead)"
                    % (which, who.max_len, P, n, g))
        temp = float(temperature or 0.0)
        tk = int(top_k) if top_k else 0
        tp = float(top_p) if top_p else 0.0
        key_ = ("spec", P, n, g, temp, tk, tp, id(draft))
        cached = self._loop_cache.get(key_)
        if cached is None:
            fn = self._spec_loop(draft, P, n, g, temp, tk, tp)
            self._loop_cache[key_] = (fn, draft)   # pin draft alive
        else:
            fn = cached[0]
        out, rounds = fn(self._params, draft._params,
                         jnp.asarray(prompt, jnp.float32),
                         jax.random.PRNGKey(int(seed or 0)))
        toks = np.asarray(out[:, :P + n], np.int64)
        if return_rounds:
            # rounds -> acceptance: each round emits acc+1 tokens, so
            # mean accepted draft tokens per round = n/rounds - 1
            return toks, int(rounds)
        return toks

    def _spec_loop(self, draft, P, n, g, temp=0.0, tk=0, tp=0.0):
        B = self.batch_size
        t_eval, d_eval = self._eval_fn, draft._eval_fn
        rng0 = jax.random.PRNGKey(0)
        sampled = temp > 0
        top_k = tk or None
        top_p = tp or None

        def fwd(eval_fn, params, aux, tokens, pos, tn):
            """tokens (B, tn) int32, pos scalar int32."""
            args = dict(params)
            args["data"] = tokens.astype(jnp.float32)
            args["positions"] = (pos + jnp.arange(tn)).astype(
                jnp.float32)
            args["cache_pos"] = pos.astype(jnp.float32)[None]
            outs, aux = eval_fn(args, aux, rng0, False)
            return outs[0], aux

        # both models' params as jit arguments (see _device_loop)
        def run(t_params, d_params, prompt, key):
            t_aux = self._fresh_aux()
            d_aux = draft._fresh_aux()
            prompt_i = prompt.astype(jnp.int32)
            if P > 1:
                _, t_aux = fwd(t_eval, t_params, t_aux,
                               prompt_i[:, :P - 1], jnp.int32(0),
                               P - 1)
                _, d_aux = fwd(d_eval, d_params, d_aux,
                               prompt_i[:, :P - 1], jnp.int32(0),
                               P - 1)
            buf = jnp.zeros((B, P + n + g + 1), jnp.int32)
            buf = buf.at[:, :P].set(prompt_i)
            emitted = jnp.int32(0)

            def cond(carry):
                return carry[3] < n

            def body(carry):
                t_aux, d_aux, buf, emitted, rounds, key = carry
                pos = P + emitted
                last = jnp.take_along_axis(
                    buf, (pos - 1)[None].repeat(B)[:, None],
                    axis=1)[:, 0]                       # (B,)

                # peek the round's g+1 subs without committing: sub_j
                # is the split generate() would use for emission index
                # emitted+j, and keys_after[t] is the key after t
                # emissions — the carry key only advances by `take`
                if sampled:
                    ks, subs, k = [key], [], key
                    for _ in range(g + 1):
                        k, s = jax.random.split(k)
                        ks.append(k)
                        subs.append(s)
                    subs = jnp.stack(subs)          # (g+1, 2)
                    keys_after = jnp.stack(ks)      # (g+2, 2)

                # draft proposes g tokens (ingesting each as it goes;
                # round 1's first step also ingests the prompt's last
                # token, which the prefill deliberately left out)
                def d_step(dc, i):
                    d_aux, cur = dc
                    dl, d_aux = fwd(d_eval, d_params, d_aux,
                                    cur[:, None], pos - 1 + i, 1)
                    if sampled:
                        # common random numbers: the SAME sub the
                        # target will verify emission emitted+i with
                        nxt = _pick_token(
                            dl[:, -1], temp, top_k,
                            jnp.take(subs, i, axis=0),
                            top_p).astype(jnp.int32)
                    else:
                        nxt = jnp.argmax(dl[:, -1], axis=-1).astype(
                            jnp.int32)
                    return (d_aux, nxt), nxt

                (d_aux, _), props = jax.lax.scan(
                    d_step, (d_aux, last), jnp.arange(g))   # (g, B)
                props_t = props.T                            # (B, g)

                # ONE target forward scores last + proposals
                chunk = jnp.concatenate([last[:, None], props_t],
                                        axis=1)              # (B, g+1)
                tl, t_aux = fwd(t_eval, t_params, t_aux, chunk,
                                pos - 1, g + 1)
                if sampled:
                    picks = jnp.stack(
                        [_pick_token(tl[:, c], temp, top_k, subs[c],
                                     top_p)
                         for c in range(g + 1)],
                        axis=1).astype(jnp.int32)            # (B, g+1)
                else:
                    picks = jnp.argmax(tl, axis=-1).astype(
                        jnp.int32)                           # (B, g+1)

                # lockstep acceptance: leading i with batch-unanimous
                # draft/target agreement (under shared noise when
                # sampling, so agreement == the target's own pick)
                match = (props_t == picks[:, :g]).all(axis=0)   # (g,)
                acc = jnp.cumprod(match.astype(jnp.int32)).sum()
                take = jnp.minimum(acc + 1, n - emitted)
                # emit the picks directly: columns < acc equal the
                # accepted proposals, column acc is the target's own
                # next token, columns past `take` hold junk but land
                # in the headroom region or are overwritten by the
                # next round (which starts at pos + take)
                buf = jax.lax.dynamic_update_slice(
                    buf, picks, (0, pos))
                if sampled:
                    # advance one split per EMITTED token
                    key = jnp.take(keys_after, take, axis=0)
                return (t_aux, d_aux, buf, emitted + take,
                        rounds + 1, key)

            _, _, buf, _, rounds, _ = jax.lax.while_loop(
                cond, body, (t_aux, d_aux, buf, emitted,
                             jnp.int32(0), key))
            return buf, rounds

        return jax.jit(run)

    def generate_on_device(self, prompt, max_new_tokens,
                           temperature=0.0, top_k=None, top_p=None,
                           eos_id=None, seed=0):
        """Whole-generation-on-device: prefill + a compiled decode loop
        in ONE XLA program — a single dispatch instead of one per token
        (the production-serving shape; through a remote tunnel the
        per-token loop is round-trip-bound).

        Same sampling semantics as generate(). Without eos_id the loop
        is a lax.scan with a static trip count. With eos_id it becomes
        a lax.while_loop that EXITS as soon as every row has emitted
        eos — the serving early-stop, still in one program; the output
        keeps the static (B, P + max_new_tokens) shape with finished
        rows padded by eos (the host generate() truncates instead —
        same tokens, different tail). Each distinct
        (prompt_len, max_new_tokens, temperature, top_k, top_p,
        eos_id) tuple compiles once."""
        self._check_sampling(temperature, top_k, top_p)
        prompt, P = self._check_prompt(prompt, max_new_tokens)
        if int(max_new_tokens) == 0:
            return np.asarray(prompt, np.int64)
        toks = self._device_loop(P, int(max_new_tokens),
                                 float(temperature),
                                 int(top_k) if top_k else 0,
                                 float(top_p) if top_p else 0.0,
                                 None if eos_id is None
                                 else int(eos_id))(
            self._params,
            jnp.asarray(prompt, jnp.float32),
            jax.random.PRNGKey(seed))
        return np.concatenate([prompt.astype(np.int64),
                               np.asarray(toks)], axis=1)

    def _device_loop(self, P, n_steps, temperature, top_k, top_p=0.0,
                     eos_id=None):
        key_ = (P, n_steps, temperature, top_k, top_p, eos_id)
        cached = self._loop_cache.get(key_)
        if cached is not None:
            return cached
        eval_fn = self._eval_fn
        B = self.batch_size

        # params flow through as jit ARGUMENTS, never closures: a
        # closed-over weight dict would be baked into the lowered
        # program as dense constants — a fresh compile per checkpoint,
        # and a serialized module the size of the model (the axon
        # tunnel's remote_compile rejects those outright, HTTP 413)
        def decode_fwd(params, aux, tok, i, sub):
            args = dict(params)
            args["data"] = tok[:, None].astype(jnp.float32)
            args["positions"] = jnp.full((1,), P + i, jnp.float32)
            args["cache_pos"] = jnp.full((1,), P + i, jnp.float32)
            outs, aux = eval_fn(args, aux, sub, False)
            return outs[0][:, -1], aux

        def prefill(params, prompt, key):
            aux = self._fresh_aux()
            args = dict(params)
            args["data"] = prompt
            args["positions"] = jnp.arange(P, dtype=jnp.float32)
            args["cache_pos"] = jnp.zeros((1,), jnp.float32)
            outs, aux = eval_fn(args, aux, key, False)
            return outs[0][:, -1], aux

        def run_scan(params, prompt, key):
            last, aux = prefill(params, prompt, key)

            def body(carry, i):
                aux, last, key = carry
                key, sub = jax.random.split(key)
                tok = _pick_token(last, temperature, top_k, sub,
                                  top_p)
                last, aux = decode_fwd(params, aux, tok, i, sub)
                return (aux, last, key), tok

            # the scan body samples token i from the PREVIOUS step's
            # logits and then runs a forward — so the n-th token needs
            # only n-1 forwards: run n-1 bodies and sample the final
            # token from the last carry outside the scan (same rng
            # split pattern, one decode forward saved per call)
            (_, last, key), toks = jax.lax.scan(
                body, (aux, last, key), jnp.arange(n_steps - 1))
            _, sub = jax.random.split(key)
            tok_f = _pick_token(last, temperature, top_k, sub, top_p)
            toks = jnp.concatenate([toks, tok_f[None]], axis=0)
            return toks.T                        # (B, n_steps)

        def run_eos(params, prompt, key):
            last, aux = prefill(params, prompt, key)
            buf = jnp.full((B, n_steps), eos_id, jnp.int32)

            def cond(c):
                _aux, _last, _key, _buf, i, done = c
                return (i < n_steps) & ~jnp.all(done)

            def body(c):
                aux, last, key, buf, i, done = c
                key, sub = jax.random.split(key)
                tok = _pick_token(last, temperature, top_k, sub,
                                  top_p).astype(jnp.int32)
                # same emit rule as the host generate(): finished rows
                # keep emitting eos
                tok = jnp.where(done, eos_id, tok)
                done = done | (tok == eos_id)
                buf = jax.lax.dynamic_update_slice(
                    buf, tok[:, None], (0, i))
                # the final iteration's forward is wasted work (its
                # logits are never sampled) — the price of the dynamic
                # exit; everything SKIPPED after all-eos is the win
                last, aux = decode_fwd(params, aux, tok, i, sub)
                return (aux, last, key, buf, i + 1, done)

            c = (aux, last, key, buf, jnp.int32(0),
                 jnp.zeros((B,), bool))
            return jax.lax.while_loop(cond, body, c)[3]

        fn = jax.jit(run_scan if eos_id is None else run_eos)
        self._loop_cache[key_] = fn
        return fn

    def serving_decoder(self, **kwargs):
        """A continuous-batching decoder over this model's weights: a
        fixed slot pool (one slot per batch row) over the on-device KV
        cache, admitting queued prompts the step after a sequence
        finishes (mxnet_tpu/serve/decode.py has the semantics).
        kwargs forward to :class:`~mxnet_tpu.serve.ContinuousDecoder`."""
        from .serve.decode import ContinuousDecoder
        return ContinuousDecoder(self, **kwargs)

    def generate(self, prompt, max_new_tokens, temperature=0.0,
                 top_k=None, top_p=None, eos_id=None, seed=0,
                 on_token=None):
        """Greedy (temperature 0) or sampled continuation.

        prompt: (B, P) int token ids. Returns (B, P + n) ids as numpy
        (n <= max_new_tokens; generation stops early only when every
        row has emitted eos_id). ``on_token``, when given, is called
        with each round's (B,) numpy token array as soon as it is
        picked — the local twin of the serve path's streamed frames
        (the returned rows are exactly the concatenation the callback
        saw, so callers can cross-check stream against one-shot)."""
        self._check_sampling(temperature, top_k, top_p)
        prompt, P = self._check_prompt(prompt, max_new_tokens)
        key = jax.random.PRNGKey(seed)
        aux = self._fresh_aux()
        logits, aux = self._forward(aux, prompt, 0)
        ids = [prompt]
        done = np.zeros((self.batch_size,), bool)
        last = logits[:, -1]
        for i in range(max_new_tokens):
            key, sub = jax.random.split(key)
            nxt = np.asarray(_pick_token(last, temperature, top_k,
                                         sub, top_p))
            if eos_id is not None:
                nxt = np.where(done, eos_id, nxt)
                done |= nxt == eos_id
            ids.append(nxt[:, None])
            if on_token is not None:
                on_token(nxt.copy())
            if eos_id is not None and done.all():
                break
            if i + 1 < max_new_tokens:
                logits, aux = self._forward(aux, nxt[:, None], P + i)
                last = logits[:, -1]
        return np.concatenate(ids, axis=1)


def _quantize_weights(arg_params, decode_args):
    """Weight-only int8: for every quantized layer in the decode graph
    (marked by its "<name>_scale" argument), replace the float
    "<name>_weight" with per-output-channel symmetric int8 + f32 scale.
    Other params (embeddings, norms, biases) pass through."""
    out = {k: v for k, v in arg_params.items()}
    for arg in decode_args:
        if not arg.endswith("_scale"):
            continue
        wname = arg[:-len("_scale")] + "_weight"
        if wname not in out:
            continue
        w = np.asarray(getattr(out[wname], "_data", out[wname]),
                       np.float32)
        scale = np.maximum(np.abs(w).max(axis=1), 1e-12) / 127.0
        out[wname] = np.clip(np.rint(w / scale[:, None]),
                             -127, 127).astype(np.int8)
        out[arg] = scale.astype(np.float32)
    return out


def replay_key(seed, picks):
    """The per-request PRNG key after ``picks`` tokens have been drawn
    from the stream seeded by ``seed``.

    Every sampling path in this module and in the serving decoder
    follows one discipline: ``key = PRNGKey(seed)`` then exactly one
    ``key, sub = split(key)`` per drawn token (a disaggregated
    handoff's remote first token consumed the first split, so
    local-pick and handoff admissions alike sit at ``len(emitted)``
    splits after k emitted tokens). That makes PRNG progress derivable
    state: a migrated session (``ContinuousDecoder.export_session`` /
    ``submit(resume=...)``) re-derives its key here and the resumed
    stream continues bit-exactly."""
    key = jax.random.PRNGKey(int(seed or 0))
    for _ in range(int(picks)):
        key, _ = jax.random.split(key)
    return key


def _pick_token(logits, temperature, top_k, key, top_p=None):
    """logits (B, V) -> (B,) int32, on device."""
    logits = logits.astype(jnp.float32)
    if temperature and float(temperature) > 0:
        logits = logits / float(temperature)
        if top_k:
            # kth-largest threshold via top_k, not a full V-sort — this
            # sits on the per-token decode hot path
            kth = jax.lax.top_k(logits, int(top_k))[0][:, -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p and float(top_p) < 1.0:
            # nucleus: keep the smallest prefix of descending-prob
            # tokens whose mass reaches top_p (the first token past the
            # threshold is included, per the standard formulation)
            srt = jnp.sort(logits, axis=-1)[:, ::-1]
            probs = jax.nn.softmax(srt, axis=-1)
            mass = jnp.cumsum(probs, axis=-1)
            keep = mass - probs < float(top_p)       # (B, V) on sorted
            cut = jnp.where(keep, srt, jnp.inf).min(axis=-1,
                                                    keepdims=True)
            logits = jnp.where(logits < cut, -jnp.inf, logits)
        return jax.random.categorical(key, logits, axis=-1)
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)
