"""Data iterators (reference: python/mxnet/io.py, 932 LoC).

DataIter/DataBatch/DataDesc protocol, NDArrayIter, ResizeIter,
PrefetchingIter. TPU-native notes: batches are built host-side in numpy and
transferred once per batch (one H2D per step keeps HBM traffic clean);
PrefetchingIter overlaps host batch assembly with device compute — the
analogue of the reference's dmlc::ThreadedIter double-buffering
(src/io/iter_prefetcher.h:141).
"""
from __future__ import annotations

import os
import queue
import threading
from collections import namedtuple

import numpy as np

from .base import string_types
from . import ndarray
from .ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "LibSVMIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name + shape (+dtype/layout) of a data source (reference
    io.py:DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    @staticmethod
    def get_batch_axis(layout):
        """Index of the 'N' axis in a layout string (reference
        io.py:DataDesc.get_batch_axis)."""
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """One mini-batch (reference io.py:DataBatch)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        for field, v in (("data", data), ("label", label)):
            if v is not None and not isinstance(v, (list, tuple)):
                raise TypeError("%s must be a list/tuple of NDArrays"
                                % field)
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        return "%s: data %s label %s" % (
            self.__class__.__name__, [d.shape for d in self.data],
            [l.shape for l in self.label] if self.label else None)


class DataIter:
    """Base iterator (reference io.py:DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        """Next DataBatch (default implementation drives iter_next +
        getdata/getlabel/getindex/getpad)."""
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()


class _BatchDelegate:
    """Mixin for wrapper iterators whose getdata/getlabel/... just expose
    fields of the wrapped iterator's last batch."""

    current_batch = None

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class ResizeIter(_BatchDelegate, DataIter):
    """Resize an iterator to `size` batches per epoch, optionally resetting
    the inner iterator on underflow (reference io.py:ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            # epoch underflow: restart the inner iterator mid-"epoch"
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True


class _WorkerError:
    """Carrier for a non-StopIteration worker failure: re-raised in the
    consumer thread instead of starving its queue forever."""

    def __init__(self, exc):
        self.exc = exc


class _PrefetchWorker(threading.Thread):
    """One background thread per wrapped iterator: serves 'next'/'reset'
    commands so batch assembly overlaps device compute. With a
    place_fn, the worker also DISPATCHES the batch's device placement
    (an async H2D) before handing it over — the double-buffer stage:
    batch t+1's transfer is in flight while the consumer's step t
    computes."""

    def __init__(self, it, place_fn=None):
        super().__init__(daemon=True)
        self.it = it
        self.place_fn = place_fn
        self.cmds = queue.Queue()
        self.outs = queue.Queue()
        self.start()

    def run(self):
        while True:
            cmd = self.cmds.get()
            if cmd == "stop":
                return
            if cmd == "reset":
                self.it.reset()
                self.outs.put(None)
            else:  # "next"
                try:
                    item = self.it.next()
                except StopIteration:
                    item = StopIteration
                except Exception as e:  # noqa: BLE001 — surface it
                    item = _WorkerError(e)
                else:
                    # outside the StopIteration guard: a StopIteration
                    # escaping place_fn is a BUG to surface, not an
                    # epoch end (only it.next() may signal that)
                    if self.place_fn is not None:
                        try:
                            item.placed = self.place_fn(item)
                        except Exception as e:  # noqa: BLE001
                            item = _WorkerError(e)
                self.outs.put(item)


class PrefetchingIter(_BatchDelegate, DataIter):
    """Thread-backed prefetcher over one or more iterators (reference
    io.py:PrefetchingIter; C++ analogue iter_prefetcher.h). One worker
    thread per inner iterator; a 'next' command is always in flight so
    the next batch is being assembled while the device computes.

    place_fn (the device-prefetch stage): a callable applied to each
    assembled DataBatch whose result lands on ``batch.placed`` — use
    ``TrainStep.make_placer()`` to shard/place the feed on device. With
    a single inner iterator it runs on the worker thread, so the H2D
    dispatch itself is off the step loop; with multiple inner iterators
    it runs at merge time (the merged batch is what needs placing)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 place_fn=None):
        super().__init__()
        self.iters = iters if isinstance(iters, list) else [iters]
        if not self.iters:
            raise ValueError("need at least one iterator")
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._place_fn = place_fn
        self.batch_size = self.provide_data[0][1][0]
        worker_place = place_fn if len(self.iters) == 1 else None
        self._workers = [_PrefetchWorker(it, worker_place)
                         for it in self.iters]
        self._inflight = False
        self._request()

    def _request(self):
        for w in self._workers:
            w.cmds.put("next")
        self._inflight = True

    def _collect(self):
        self._inflight = False
        return [w.outs.get() for w in self._workers]

    def __del__(self):
        for w in getattr(self, "_workers", []):
            w.cmds.put("stop")

    def _renamed(self, which, renames):
        descs_per_iter = [getattr(it, which) for it in self.iters]
        if renames is None:
            return [d for descs in descs_per_iter for d in descs]
        out = []
        for mapping, descs in zip(renames, descs_per_iter):
            for d in descs:
                d = d if isinstance(d, DataDesc) else DataDesc(*d)
                out.append(DataDesc(mapping[d.name], d.shape, d.dtype))
        return out

    @property
    def provide_data(self):
        return self._renamed("provide_data", self.rename_data)

    @property
    def provide_label(self):
        return self._renamed("provide_label", self.rename_label)

    def reset(self):
        if self._inflight:
            self._collect()     # drain the outstanding 'next'
        for w in self._workers:
            w.cmds.put("reset")
        for w in self._workers:
            w.outs.get()
        self._request()

    def iter_next(self):
        if not self._inflight:
            self._request()
        batches = self._collect()
        for b in batches:
            if isinstance(b, _WorkerError):
                raise b.exc
        ended = [b is StopIteration for b in batches]
        if any(ended):
            if not all(ended):
                raise RuntimeError("inner iterators ended at different "
                                   "batch counts")
            return False
        if len({b.pad for b in batches}) != 1:
            raise RuntimeError("inner iterators disagree on pad")
        self.current_batch = DataBatch(
            [d for b in batches for d in b.data],
            [l for b in batches for l in b.label]
            if batches[0].label is not None else None,
            batches[0].pad, batches[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        if self._place_fn is not None:
            placed = getattr(batches[0], "placed", None) \
                if len(batches) == 1 else None
            self.current_batch.placed = placed if placed is not None \
                else self._place_fn(self.current_batch)
        self._request()          # keep the pipeline primed
        return True


def _init_data(data, allow_empty, default_name):
    """Normalize data input (array | list | dict | None) into a sorted
    [(name, NDArray)] list (reference io.py:_init_data)."""
    if data is None:
        data = {}
    elif isinstance(data, (np.ndarray, NDArray)):
        data = {default_name: data}
    elif isinstance(data, list):
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("data must be an array, a list of arrays, or a "
                        "dict of name->array, got %s" % type(data))
    if not data and not allow_empty:
        raise ValueError("empty %s input" % default_name)

    def as_nd(name, v):
        if isinstance(v, NDArray):
            return v
        try:
            return array(np.asarray(v))
        except Exception:
            raise TypeError("cannot convert %s (%s) to NDArray"
                            % (name, type(v)))
    return sorted((k, as_nd(k, v)) for k, v in data.items())


class NDArrayIter(DataIter):
    """Iterator over in-memory arrays with shuffle + pad/discard/roll-over
    last-batch handling (reference io.py:NDArrayIter, :516)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)

        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        n = self.data[0][1].shape[0]

        def remap(pairs, idx):
            return [(k, array(v.asnumpy()[idx])) for k, v in pairs]

        if shuffle:
            # host-side: one permutation per construction, shared by
            # every data/label source
            perm = np.random.permutation(n)
            self.data, self.label = remap(self.data, perm), \
                remap(self.label, perm)
        if last_batch_handle == "discard":
            # cheap device-side slice; no host round-trip
            keep = n - n % batch_size
            self.data = [(k, v[:keep]) for k, v in self.data]
            self.label = [(k, v[:keep]) for k, v in self.label]

        self.data_list = [v for _, v in self.data + self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.data_list[0].shape[0]
        if self.num_data < batch_size:
            raise ValueError("batch_size %d exceeds data size %d"
                             % (batch_size, self.num_data))
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                     v.dtype)
            for k, v in self.data]

    @property
    def provide_label(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                     v.dtype)
            for k, v in self.label]

    def hard_reset(self):
        """Ignore roll-over; fully reset (reference
        NDArrayIter.hard_reset)."""
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + \
                (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        if self.cursor >= self.num_data:
            raise RuntimeError("iterator exhausted; call reset()")
        if self.cursor + self.batch_size <= self.num_data:
            window = slice(self.cursor, self.cursor + self.batch_size)
            return [v[window] for _, v in data_source]
        # padded last batch wraps to the epoch start: stitch the epoch
        # tail to a head slice (device-side; no full-array host gather)
        pad = self.cursor + self.batch_size - self.num_data
        return [ndarray.concatenate([v[self.cursor:], v[:pad]])
                for _, v in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class MNISTIter(NDArrayIter):
    """MNIST idx-ubyte iterator (reference: registered C++ 'MNISTIter',
    src/io/iter_mnist.cc:259 — same file format, same kwargs)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, silent=False, seed=0,
                 data_name="data", label_name="softmax_label", **kwargs):
        import gzip
        import struct

        def _open(p):
            if os.path.exists(p):
                return open(p, "rb")
            if os.path.exists(p + ".gz"):
                return gzip.open(p + ".gz", "rb")
            raise IOError("MNIST file %s not found" % p)

        with _open(label) as fin:
            _magic, _n = struct.unpack(">II", fin.read(8))
            y = np.frombuffer(fin.read(), dtype=np.uint8).astype(
                np.float32)
        with _open(image) as fin:
            _magic, n, rows, cols = struct.unpack(">IIII", fin.read(16))
            x = np.frombuffer(fin.read(), dtype=np.uint8).astype(
                np.float32) / 255.0
            x = x.reshape(n, rows * cols) if flat else \
                x.reshape(n, 1, rows, cols)
        if shuffle:
            rng = np.random.RandomState(seed)
            idx = rng.permutation(n)
            x, y = x[idx], y[idx]
        super().__init__(data={data_name: x}, label={label_name: y},
                         batch_size=batch_size,
                         last_batch_handle="discard")


class CSVIter(NDArrayIter):
    """CSV iterator (reference: registered C++ 'CSVIter',
    src/io/iter_csv.cc:150)."""

    def __init__(self, data_csv, data_shape, label_csv=None,
                 label_shape=(1,), batch_size=128, round_batch=True,
                 **kwargs):
        data = np.loadtxt(data_csv, delimiter=",",
                          dtype=np.float32, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",",
                               dtype=np.float32, ndmin=1)
            if tuple(label_shape) != (1,):
                label = label.reshape((-1,) + tuple(label_shape))
        super().__init__(data, label, batch_size=batch_size,
                         last_batch_handle="pad" if round_batch
                         else "discard")


def _lazy_image_iters():
    """ImageRecordIter / ImageDetRecordIter live in mxnet_tpu.image (the
    reference registers them from C++, src/io/iter_image_recordio_2.cc);
    re-exported here so `mx.io.ImageRecordIter(...)` keeps working."""
    from .image import ImageRecordIter as _iri
    from .image.detection import ImageDetIter as _idi
    return _iri, _idi


def ImageRecordIter(*args, **kwargs):
    from .image import ImageRecordIter as _impl
    return _impl(*args, **kwargs)


def ImageDetRecordIter(*args, **kwargs):
    from .image.detection import ImageDetIter as _impl
    kwargs.pop("prefetch_buffer", None)
    kwargs.pop("preprocess_threads", None)
    return _impl(*args, **kwargs)


class LibSVMIter(DataIter):
    """Batched reader of LibSVM-format text (``label idx:val ...``) as
    csr batches (reference src/io/iter_libsvm.cc:200). The feature
    matrix stays compressed end-to-end: each batch is a CSRNDArray slice
    of the parsed corpus — no dense (batch, num_features) buffer unless
    the consumer casts. Wrap-around padding matches round_batch=1.
    """

    def __init__(self, data_libsvm, data_shape, batch_size,
                 label_libsvm=None, label_shape=(1,), round_batch=True,
                 **_kwargs):
        super().__init__(batch_size)
        self._data_shape = (int(data_shape[0]) if not
                            isinstance(data_shape, int) else
                            int(data_shape),)
        self._label_shape = ((int(label_shape),) if
                             isinstance(label_shape, int)
                             else tuple(int(d) for d in label_shape))
        vals, cols, indptr, labels = self._parse(data_libsvm)
        self._vals, self._cols, self._indptr = vals, cols, indptr
        self._num = len(indptr) - 1
        if label_libsvm is not None:
            # separate libsvm-format label file: densify each sparse
            # label row to label_shape (reference iter_libsvm.cc
            # label_libsvm + label_shape)
            lv, lc, lptr, _ = self._parse(label_libsvm)
            width = 1
            for d in self._label_shape:
                width *= d
            dense = np.zeros((len(lptr) - 1, width), np.float32)
            for r in range(len(lptr) - 1):
                seg = slice(lptr[r], lptr[r + 1])
                dense[r, lc[seg]] = lv[seg]
            if self._label_shape in ((), (1,)):
                labels = dense.reshape(-1)   # matches provide_label (N,)
            else:
                labels = dense.reshape((-1,) + self._label_shape)
        elif self._label_shape not in ((), (1,)):
            raise ValueError("label_shape %r needs a label_libsvm file "
                             "(the data file's leading token is a single "
                             "scalar label)" % (self._label_shape,))
        self._labels = labels
        self._round_batch = round_batch
        self.data_name, self.label_name = "data", "label"
        self.reset()

    @staticmethod
    def _parse(path):
        vals, cols, indptr, labels = [], [], [0], []
        with open(path) as f:
            for line in f:
                parts = line.split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                for tok in parts[1:]:
                    c, v = tok.split(":")
                    cols.append(int(c))
                    vals.append(float(v))
                indptr.append(len(cols))
        return (np.asarray(vals, np.float32), np.asarray(cols, np.int64),
                np.asarray(indptr, np.int64),
                np.asarray(labels, np.float32))

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self._data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,)
        if self._label_shape not in ((), (1,)):
            shape += self._label_shape
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        self._cursor = 0

    def _rows(self, ids):
        """CSR batch for the given row ids (host slicing of the parsed
        corpus, device arrays in the result)."""
        from .ndarray import sparse
        counts = self._indptr[ids + 1] - self._indptr[ids]
        indptr = np.concatenate([[0], np.cumsum(counts)])
        take = np.concatenate(
            [np.arange(self._indptr[i], self._indptr[i + 1])
             for i in ids]) if len(ids) else np.zeros((0,), np.int64)
        return sparse.CSRNDArray(
            self._vals[take], self._cols[take], indptr,
            (len(ids), self._data_shape[0]))

    def next(self):
        if self._cursor >= self._num:
            raise StopIteration
        end = self._cursor + self.batch_size
        ids = np.arange(self._cursor, min(end, self._num))
        pad = 0
        if end > self._num:
            if not self._round_batch:
                raise StopIteration
            pad = end - self._num
            ids = np.concatenate([ids, np.arange(pad) % self._num])
        self._cursor = end
        from .ndarray import array as _arr
        return DataBatch(
            data=[self._rows(ids)],
            label=[_arr(self._labels[ids])], pad=pad,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
