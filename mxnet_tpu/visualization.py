"""Network visualization (``mx.viz``) — reference:
python/mxnet/visualization.py (print_summary + graphviz plot_network).
"""
from __future__ import annotations

from .symbol.symbol import Symbol, _topo_order

__all__ = ["print_summary", "plot_network"]


def _prod(t):
    out = 1
    for x in t:
        out *= int(x)
    return out


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Layer-table summary with per-layer output shapes and parameter
    counts (reference visualization.py:print_summary)."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    positions = [int(line_length * p) for p in positions]

    shapes = {}
    param_shapes = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shapes = dict(zip(internals.list_outputs(), out_shapes))
        arg_shapes, _, aux_shapes = symbol.infer_shape(**shape)
        param_shapes = dict(zip(symbol.list_arguments(), arg_shapes))
        param_shapes.update(zip(symbol.list_auxiliary_states(),
                                aux_shapes))

    heads = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def row(fields):
        line = ""
        for f, pos in zip(fields, positions):
            line = (line + str(f))[:pos - 1].ljust(pos)
        print(line.rstrip())

    print("=" * line_length)
    row(heads)
    print("=" * line_length)

    total = 0
    data_like = set(shape or ())
    for node in _topo_order(symbol._entries):
        if node.op is None:
            if node.name in data_like:
                row(["%s (null)" % node.name,
                     shapes.get(node.name + "_output",
                                (shape or {}).get(node.name, "")), 0, ""])
            continue
        out_shape = shapes.get("%s_output" % node.name) or \
            shapes.get("%s_output0" % node.name) or ""
        n_params = sum(
            _prod(param_shapes[m.name]) for (m, _i) in node.inputs
            if m.op is None and m.name not in data_like
            and "label" not in m.name and m.name in param_shapes)
        prev = ",".join(m.name for (m, _i) in node.inputs
                        if not (m.op is None and m.name not in data_like))
        row(["%s (%s)" % (node.name, node.op.name), out_shape,
             n_params, prev])
        total += n_params
    print("=" * line_length)
    print("Total params: %d" % total)
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """graphviz Digraph of the symbol (reference
    visualization.py:plot_network). Requires the ``graphviz`` package."""
    try:
        from graphviz import Digraph
    except ImportError as e:                       # pragma: no cover
        raise ImportError(
            "plot_network requires the graphviz python package") from e

    shapes = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        shapes = dict(zip(internals.list_outputs(), out_shapes))

    node_attrs = dict({"shape": "box", "fixedsize": "false"},
                      **(node_attrs or {}))
    dot = Digraph(name=title, format=save_format)
    palette = {"Convolution": "#fb8072", "FullyConnected": "#fb8072",
               "BatchNorm": "#bebada", "Activation": "#ffffb3",
               "Pooling": "#80b1d3", "SoftmaxOutput": "#fccde5"}

    data_like = set(shape or ())
    for node in _topo_order(symbol._entries):
        if node.op is None:
            if node.name in data_like or not hide_weights:
                dot.node(node.name, node.name,
                         _attributes=dict(node_attrs,
                                          fillcolor="#8dd3c7",
                                          style="filled"))
            continue
        label = "%s\n%s" % (node.name, node.op.name)
        out_shape = shapes.get("%s_output" % node.name)
        if out_shape:
            label += "\n%s" % (tuple(out_shape),)
        dot.node(node.name, label,
                 _attributes=dict(node_attrs, style="filled",
                                  fillcolor=palette.get(node.op.name,
                                                        "#d9d9d9")))
        for (m, _i) in node.inputs:
            if m.op is None and hide_weights and m.name not in data_like:
                continue
            dot.edge(m.name, node.name)
    return dot
