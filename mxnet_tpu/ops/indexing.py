"""Indexing ops: Embedding, take, one_hot, pick, gather/scatter.

Reference: src/operator/tensor/indexing_op.* (SURVEY.md N11). Embedding's
backward is a scatter-add over the weight — XLA lowers the gather/scatter
pair onto the TPU natively; no custom kernel needed.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# Embedding backward default, decided by the staged A/B
# (benchmark/bench_embgrad.py at the flagship LM shape; capture:
# bench_out/embgrad.json). scatter-add beat sort+segment-sum 123.9 ms
# vs 129.2 ms (one-hot matmul 300x off) on the only live backend of the
# round (CPU — the TPU tunnel has been down since 2026-08-01); the
# segsum formulation stays one env var away for the next TPU window,
# where the traced ~8x-off-roofline scatter+Adam update
# (bench_out/trace_tlm_summary.txt) is still the open question.
_EMBED_GRAD_DEFAULT = "scatter"


@register("Embedding", arg_names=("data", "weight"), nondiff_inputs=(0,),
          defaults={"input_dim": 0, "output_dim": 0, "dtype": "float32"})
def _embedding(data, weight, **_):
    from .. import config as _config
    choice = _config.get("MXNET_EMBED_GRAD") or _EMBED_GRAD_DEFAULT
    if choice == "segsum":
        # backward as sort + segment-sum instead of autodiff's
        # scatter-add. Same values (duplicate ids accumulate in id
        # order after a stable sort).
        return _embedding_segsum(data, weight)
    if choice != "scatter":
        raise ValueError(
            "MXNET_EMBED_GRAD must be 'scatter', 'segsum' or unset "
            "(measured default: %r), got %r"
            % (_EMBED_GRAD_DEFAULT, choice))
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@jax.custom_vjp
def _embedding_segsum(data, weight):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


def _embedding_segsum_fwd(data, weight):
    return _embedding_segsum(data, weight), (data, weight.shape[0])


def _embedding_segsum_bwd(res, dy):
    data, V = res
    ids = data.astype(jnp.int32).reshape(-1)
    D = dy.shape[-1]
    if ids.shape[0] == 0:        # empty batch: reshape(-1) can't infer
        dw = jnp.zeros((V, D), dy.dtype)
        return jnp.zeros(data.shape, data.dtype), dw
    dy2 = dy.reshape(ids.shape[0], D)
    # stable sort by id, then tell the segment reduce the ids ARE
    # sorted — otherwise it lowers to the very scatter-add this
    # experiment exists to beat. Duplicate-id partials accumulate in
    # f32 here where scatter-add rounds to the weight dtype per step:
    # bit-equal in f32, equal up to (strictly less) rounding in bf16.
    order = jnp.argsort(ids, stable=True)
    dw = jax.ops.segment_sum(
        jnp.take(dy2, order, axis=0).astype(jnp.float32),
        jnp.take(ids, order), num_segments=V,
        indices_are_sorted=True)
    # ids are not differentiable; they ride the float32-input
    # convention, so their cotangent is explicit zeros
    return jnp.zeros(data.shape, data.dtype), dw.astype(dy.dtype)


_embedding_segsum.defvjp(_embedding_segsum_fwd, _embedding_segsum_bwd)


@register("take", arg_names=("a", "indices"), nondiff_inputs=(1,),
          defaults={"axis": 0, "mode": "clip"})
def _take(a, indices, axis=0, mode="clip", **_):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("batch_take", arg_names=("a", "indices"), nondiff_inputs=(1,))
def _batch_take(a, indices, **_):
    idx = indices.astype(jnp.int32)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register("pick", arg_names=("data", "index"), nondiff_inputs=(1,),
          defaults={"axis": -1, "keepdims": False})
def _pick(data, index, axis=-1, keepdims=False, **_):
    idx = index.astype(jnp.int32)
    idx_exp = jnp.expand_dims(idx, axis if axis >= 0 else data.ndim + axis)
    out = jnp.take_along_axis(data, idx_exp, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("one_hot", arg_names=("indices",), differentiable=False,
          defaults={"depth": 0, "on_value": 1.0, "off_value": 0.0,
                    "dtype": "float32"})
def _one_hot(indices, depth=0, on_value=1.0, off_value=0.0,
             dtype="float32", **_):
    from ..base import np_dtype
    idx = indices.astype(jnp.int32)
    oh = jnp.equal(idx[..., None], jnp.arange(depth)).astype(np_dtype(dtype))
    return oh * on_value + (1 - oh) * off_value


@register("gather_nd", arg_names=("data", "indices"), nondiff_inputs=(1,))
def _gather_nd(data, indices, **_):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd", arg_names=("data", "indices"), nondiff_inputs=(1,),
          defaults={"shape": ()})
def _scatter_nd(data, indices, shape=(), **_):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(tuple(shape), data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("_sparse_retain", arg_names=("data", "indices"), nondiff_inputs=(1,))
def _sparse_retain(data, indices, **_):
    idx = indices.astype(jnp.int32)
    mask = jnp.zeros((data.shape[0],), jnp.bool_).at[idx].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0)


@register("_square_sum", arg_names=("data",),
          defaults={"axis": None, "keepdims": False})
def _square_sum(x, axis=None, keepdims=False, **_):
    out = jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims)
    return out.reshape((1,)) if out.ndim == 0 else out
