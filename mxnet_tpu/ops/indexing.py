"""Indexing ops: Embedding, take, one_hot, pick, gather/scatter.

Reference: src/operator/tensor/indexing_op.* (SURVEY.md N11). Embedding's
backward is a scatter-add over the weight — XLA lowers the gather/scatter
pair onto the TPU natively; no custom kernel needed.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


@register("Embedding", arg_names=("data", "weight"), nondiff_inputs=(0,),
          defaults={"input_dim": 0, "output_dim": 0, "dtype": "float32"})
def _embedding(data, weight, **_):
    return jnp.take(weight, data.astype(jnp.int32), axis=0)


@register("take", arg_names=("a", "indices"), nondiff_inputs=(1,),
          defaults={"axis": 0, "mode": "clip"})
def _take(a, indices, axis=0, mode="clip", **_):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, a.shape[axis])
    else:
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    return jnp.take(a, idx, axis=axis)


@register("batch_take", arg_names=("a", "indices"), nondiff_inputs=(1,))
def _batch_take(a, indices, **_):
    idx = indices.astype(jnp.int32)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register("pick", arg_names=("data", "index"), nondiff_inputs=(1,),
          defaults={"axis": -1, "keepdims": False})
def _pick(data, index, axis=-1, keepdims=False, **_):
    idx = index.astype(jnp.int32)
    idx_exp = jnp.expand_dims(idx, axis if axis >= 0 else data.ndim + axis)
    out = jnp.take_along_axis(data, idx_exp, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("one_hot", arg_names=("indices",), differentiable=False,
          defaults={"depth": 0, "on_value": 1.0, "off_value": 0.0,
                    "dtype": "float32"})
def _one_hot(indices, depth=0, on_value=1.0, off_value=0.0,
             dtype="float32", **_):
    from ..base import np_dtype
    idx = indices.astype(jnp.int32)
    oh = jnp.equal(idx[..., None], jnp.arange(depth)).astype(np_dtype(dtype))
    return oh * on_value + (1 - oh) * off_value


@register("gather_nd", arg_names=("data", "indices"), nondiff_inputs=(1,))
def _gather_nd(data, indices, **_):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd", arg_names=("data", "indices"), nondiff_inputs=(1,),
          defaults={"shape": ()})
def _scatter_nd(data, indices, shape=(), **_):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(tuple(shape), data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("_sparse_retain", arg_names=("data", "indices"), nondiff_inputs=(1,))
def _sparse_retain(data, indices, **_):
    idx = indices.astype(jnp.int32)
    mask = jnp.zeros((data.shape[0],), jnp.bool_).at[idx].set(True)
    return jnp.where(mask.reshape((-1,) + (1,) * (data.ndim - 1)), data, 0)


@register("_square_sum", arg_names=("data",),
          defaults={"axis": None, "keepdims": False})
def _square_sum(x, axis=None, keepdims=False, **_):
    out = jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims)
    return out.reshape((1,)) if out.ndim == 0 else out
