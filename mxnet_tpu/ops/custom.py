"""The ``Custom`` operator — user-defined Python ops inside the compiled
graph.

Reference: src/operator/custom/custom.cc + python/mxnet/operator.py. The
reference marshals Python callbacks through the C ABI and runs them with
``ExecType::kLocal``; the TPU-native path is ``jax.pure_callback`` (the
XLA host-callback mechanism) wrapped in ``jax.custom_vjp`` so the user's
``backward`` drives autograd. The callback is a host round-trip by
construction — exactly like the reference, where Custom ops synchronize
with the Python GIL — so it is an escape hatch, not a fast path.

The user-facing classes (CustomOp/CustomOpProp/register) live in
mxnet_tpu/operator.py; this module holds the prop registry and the
registry-op glue so it exists before the nd/sym namespaces are stamped.
"""
from __future__ import annotations

import numpy as np

import jax

from .registry import register

_PROP_REGISTRY: dict[str, type] = {}
_PROP_CACHE: dict[tuple, object] = {}


def register_prop(reg_name, prop_cls):
    _PROP_REGISTRY[reg_name] = prop_cls
    for key in [k for k in _PROP_CACHE if k[0] == reg_name]:
        del _PROP_CACHE[key]


def create_prop(op_type, kwargs):
    """Prop instance for (op_type, kwargs) — cached, since num_outputs /
    shape-inference queries hit this several times per graph node."""
    if op_type not in _PROP_REGISTRY:
        raise KeyError(
            "custom op type %r is not registered — decorate its "
            "CustomOpProp with @mx.operator.register(%r)"
            % (op_type, op_type))
    try:
        key = (op_type, tuple(sorted(kwargs.items())))
        hash(key)
    except TypeError:
        return _PROP_REGISTRY[op_type](**kwargs)
    if key not in _PROP_CACHE:
        _PROP_CACHE[key] = _PROP_REGISTRY[op_type](**kwargs)
    return _PROP_CACHE[key]


def _infer(prop, in_shapes, in_dtypes):
    """Run the prop's shape/type inference; returns (in_shapes,
    out_shapes, in_dtypes, out_dtypes) as plain tuples."""
    shape_res = prop.infer_shape([list(s) for s in in_shapes])
    ishapes, oshapes = shape_res[0], shape_res[1]
    aux = shape_res[2] if len(shape_res) > 2 else []
    if aux:
        raise NotImplementedError(
            "auxiliary states on Custom ops are not supported on the TPU "
            "backend (the functional compiled graph has no mutable slots "
            "for host-managed aux); thread such state through explicit "
            "outputs instead")
    type_res = prop.infer_type(list(in_dtypes))
    itypes, otypes = type_res[0], type_res[1]
    return ([tuple(int(d) for d in s) for s in ishapes],
            [tuple(int(d) for d in s) for s in oshapes],
            list(itypes), list(otypes))


@register("Custom", arg_names=None, takes_is_train=True,
          defaults={"op_type": None})
def _custom(*inputs, op_type=None, is_train=False, **kwargs):
    """Lower one Custom node: forward and backward both run the user's
    Python through pure_callback; custom_vjp stitches them into AD."""
    from .. import ndarray as nd

    prop = create_prop(op_type, kwargs)
    n_out = len(prop.list_outputs())
    in_shapes = [tuple(int(d) for d in x.shape) for x in inputs]
    in_dtypes = [np.dtype(jax.dtypes.canonicalize_dtype(x.dtype))
                 for x in inputs]
    _ishapes, oshapes, itypes, otypes = _infer(prop, in_shapes, in_dtypes)
    out_structs = tuple(jax.ShapeDtypeStruct(s, np.dtype(t))
                        for s, t in zip(oshapes, otypes))
    in_structs = tuple(jax.ShapeDtypeStruct(s, np.dtype(t))
                       for s, t in zip(in_shapes, itypes))
    cop = prop.create_operator(None, _ishapes, itypes)

    def host_forward(*np_ins):
        in_data = [nd.array(a) for a in np_ins]
        out_data = [nd.zeros(s.shape, dtype=s.dtype) for s in out_structs]
        cop.forward(is_train, ["write"] * n_out, in_data, out_data, [])
        return tuple(o.asnumpy().astype(s.dtype, copy=False)
                     for o, s in zip(out_data, out_structs))

    @jax.custom_vjp
    def run(*ins):
        return jax.pure_callback(host_forward, out_structs, *ins,
                                 vmap_method="sequential")

    def run_fwd(*ins):
        outs = run(*ins)
        return outs, (ins, outs)

    def run_bwd(res, gouts):
        ins, outs = res

        def host_backward(*flat):
            np_ins = flat[:len(in_structs)]
            np_outs = flat[len(in_structs):len(in_structs) + n_out]
            np_gouts = flat[len(in_structs) + n_out:]
            in_data = [nd.array(a) for a in np_ins]
            out_data = [nd.array(a) for a in np_outs]
            out_grad = [nd.array(a) for a in np_gouts]
            in_grad = [nd.zeros(s.shape, dtype=s.dtype)
                       for s in in_structs]
            cop.backward(["write"] * len(in_structs), out_grad, in_data,
                         out_data, in_grad, [])
            return tuple(g.asnumpy().astype(s.dtype, copy=False)
                         for g, s in zip(in_grad, in_structs))

        gins = jax.pure_callback(host_backward, in_structs,
                                 *ins, *outs, *gouts,
                                 vmap_method="sequential")
        return tuple(gins)

    run.defvjp(run_fwd, run_bwd)
    outs = run(*inputs)
    return tuple(outs) if n_out > 1 else outs[0]


from .registry import set_param_shapes  # noqa: E402  (after registration)


def custom_num_outputs(attrs):
    """Output count of a Custom node (Symbol num_outputs hook)."""
    kwargs = {k: v for k, v in attrs.items() if k != "op_type"}
    return len(create_prop(attrs.get("op_type"), kwargs).list_outputs())


def custom_param_shapes(shapes, attrs):
    """Backward shape inference: let the prop fill unknown input shapes
    (e.g. an auto-created label variable)."""
    kwargs = {k: v for k, v in attrs.items() if k != "op_type"}
    prop = create_prop(attrs.get("op_type"), kwargs)
    known = [list(s) if s is not None else None for s in shapes]
    if known and known[0] is not None:
        res = prop.infer_shape(known)
        return [tuple(s) if s is not None else None for s in res[0]]
    return shapes


set_param_shapes("Custom", custom_param_shapes)
