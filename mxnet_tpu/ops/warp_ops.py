"""Spatial warping / matching ops: GridGenerator, BilinearSampler,
SpatialTransformer, Correlation.

Reference: src/operator/grid_generator-inl.h, bilinear_sampler-inl.h,
spatial_transformer-inl.h, correlation-inl.h (cuDNN-backed on GPU there;
pure gather/window arithmetic here — XLA fuses the interpolation weights
into the gathers, and gradients w.r.t. both data and grid come from
jax autodiff instead of hand-written backward kernels).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# ---------------------------------------------------------------------------
# GridGenerator
# ---------------------------------------------------------------------------

def _affine_grid(theta, H, W):
    """theta (B, 6) row-major 2x3 -> sampling grid (B, 2, H, W) of
    normalized [-1, 1] (x, y) target->source coords."""
    ys = jnp.linspace(-1.0, 1.0, H)
    xs = jnp.linspace(-1.0, 1.0, W)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, H*W)
    mat = theta.reshape(-1, 2, 3)
    out = mat @ base                                          # (B, 2, H*W)
    return out.reshape(-1, 2, H, W)


@register("GridGenerator", arg_names=("data",),
          defaults={"transform_type": "affine", "target_shape": (0, 0)})
def _grid_generator(data, transform_type="affine", target_shape=(0, 0),
                    **_):
    if transform_type == "affine":
        H, W = int(target_shape[0]), int(target_shape[1])
        return _affine_grid(data, H, W)
    if transform_type == "warp":
        # data (B, 2, H, W) pixel-offset flow -> normalized abs coords
        B, _two, H, W = data.shape
        gy, gx = jnp.meshgrid(jnp.arange(H), jnp.arange(W), indexing="ij")
        x = data[:, 0] + gx
        y = data[:, 1] + gy
        xn = 2.0 * x / max(W - 1, 1) - 1.0
        yn = 2.0 * y / max(H - 1, 1) - 1.0
        return jnp.stack([xn, yn], axis=1)
    raise ValueError("unknown transform_type %r" % transform_type)


# ---------------------------------------------------------------------------
# BilinearSampler
# ---------------------------------------------------------------------------

def _bilinear_sample_one(img, grid):
    """img (C, H, W), grid (2, Ho, Wo) normalized -> (C, Ho, Wo); points
    outside [-1,1] contribute zero (reference bilinear_sampler-inl.h
    between() boundary handling)."""
    C, H, W = img.shape
    x = (grid[0] + 1.0) * (W - 1) / 2.0
    y = (grid[1] + 1.0) * (H - 1) / 2.0
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    dx = x - x0
    dy = y - y0

    def corner(yc, xc, w):
        inside = (xc >= 0) & (xc <= W - 1) & (yc >= 0) & (yc <= H - 1)
        xi = jnp.clip(xc, 0, W - 1).astype(jnp.int32)
        yi = jnp.clip(yc, 0, H - 1).astype(jnp.int32)
        val = img[:, yi, xi]                       # (C, Ho, Wo)
        return val * (w * inside)[None]

    out = (corner(y0, x0, (1 - dx) * (1 - dy)) +
           corner(y0, x0 + 1, dx * (1 - dy)) +
           corner(y0 + 1, x0, (1 - dx) * dy) +
           corner(y0 + 1, x0 + 1, dx * dy))
    return out


@register("BilinearSampler", arg_names=("data", "grid"))
def _bilinear_sampler(data, grid, **_):
    return jax.vmap(_bilinear_sample_one)(data, grid)


@register("SpatialTransformer", arg_names=("data", "loc"),
          defaults={"target_shape": (0, 0), "transform_type": "affine",
                    "sampler_type": "bilinear"})
def _spatial_transformer(data, loc, target_shape=(0, 0),
                         transform_type="affine",
                         sampler_type="bilinear", **_):
    """Affine grid + bilinear sampling fused (reference
    spatial_transformer-inl.h); loc is the (B, 6) localisation output."""
    assert transform_type == "affine" and sampler_type == "bilinear"
    H, W = int(target_shape[0]), int(target_shape[1])
    grid = _affine_grid(loc, H, W)
    return jax.vmap(_bilinear_sample_one)(data, grid)


# ---------------------------------------------------------------------------
# Correlation (FlowNet cost volume)
# ---------------------------------------------------------------------------

@register("Correlation", arg_names=("data1", "data2"),
          defaults={"kernel_size": 1, "max_displacement": 1, "stride1": 1,
                    "stride2": 1, "pad_size": 0, "is_multiply": True})
def _correlation(data1, data2, kernel_size=1, max_displacement=1,
                 stride1=1, stride2=1, pad_size=0, is_multiply=True, **_):
    """Cost volume between two feature maps (correlation-inl.h): for each
    displacement (dy, dx) on the stride2 grid, mean over a kernel_size
    patch and channels of data1 * shifted(data2) (or |a-b| when
    is_multiply=False). Output (B, D*D, Ho, Wo)."""
    B, C, H, W = data1.shape
    K = int(kernel_size)
    rad = (K - 1) // 2
    md, s1, s2, pad = (int(max_displacement), int(stride1), int(stride2),
                      int(pad_size))
    d_grid = 2 * (md // s2) + 1
    border = md + rad
    pH, pW = H + 2 * pad, W + 2 * pad
    Ho = -((pH - 2 * border) // -s1)
    Wo = -((pW - 2 * border) // -s1)

    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))

    maps = []
    for i in range(d_grid):
        for j in range(d_grid):
            dy = (i - d_grid // 2) * s2
            dx = (j - d_grid // 2) * s2
            shifted = jnp.roll(p2, shift=(-dy, -dx), axis=(2, 3))
            prod = p1 * shifted if is_multiply else jnp.abs(p1 - shifted)
            summed = prod.sum(axis=1, keepdims=True)        # (B,1,pH,pW)
            if K > 1:
                summed = lax.reduce_window(
                    summed, 0.0, lax.add, (1, 1, K, K), (1, 1, 1, 1),
                    "SAME")
            maps.append(summed[:, 0])
    vol = jnp.stack(maps, axis=1)                           # (B,D²,pH,pW)
    # crop the valid region and apply stride1
    ys = border + jnp.arange(Ho) * s1
    xs = border + jnp.arange(Wo) * s1
    vol = vol[:, :, ys][:, :, :, xs]
    return vol / (K * K * C)
