"""The operator registry — the TPU-native analogue of MXNet's NNVM op registry
plus the imperative dispatch path.

Reference architecture being replaced (see SURVEY.md N1/N6/N7/N17):
  * ``NNVM_REGISTER_OP`` + ``FCompute`` kernels (include/mxnet/op_attr_types.h)
  * ``MXImperativeInvoke`` eager dispatch (src/c_api/c_api_ndarray.cc:491-556)
  * the ThreadedEngine async scheduler (src/engine/threaded_engine.cc)

TPU-native design: every op is ONE pure JAX function ``fn(*arrays, **attrs)``.
Eager calls dispatch through a per-(op, attrs) ``jax.jit`` cache — JAX's async
dispatch *is* the dependency engine (XLA orders work by data dependence, just
as ThreadedVar queues did, but on-device). The same registry entry also backs
the deferred ``Symbol`` graph and autograd, so — exactly like the reference —
imperative and symbolic modes share every kernel.

Each registered op materializes as ``mx.nd.<name>`` and ``mx.sym.<name>``
(reference: python/mxnet/base.py:381 auto-generation).
"""
from __future__ import annotations

import ast
import functools
from dataclasses import dataclass, field
from typing import Callable, Optional, Sequence

import jax
import numpy as np

__all__ = ["OpDef", "register", "get_op", "list_ops", "invoke_eager",
           "canon_attrs", "jitted_op", "set_arg_select", "set_param_shapes"]

_OP_REGISTRY: dict[str, "OpDef"] = {}
_ALIASES: dict[str, str] = {}


@dataclass
class OpDef:
    """One operator.

    fn: pure function ``(*jax_arrays, **attrs) -> array | tuple``. When
        ``needs_rng`` it must also accept a traced ``rng`` keyword (a JAX
        PRNG key); when ``takes_is_train`` it receives ``is_train: bool``
        as a *static* attr.
    arg_names: tensor-input names in order; None => variadic (add_n, Concat).
    num_visible: user-facing outputs (BatchNorm computes 5, exposes 3 —
        mirroring num_visible_outputs in the reference's nnvm registration).
    state_inputs: input indices that receive the trailing fn outputs as
        in-place updates (aux states: BN moving_mean/var; optimizer weight).
    """
    name: str
    fn: Callable
    arg_names: Optional[tuple] = None
    differentiable: bool = True
    needs_rng: bool = False
    takes_is_train: bool = False
    num_visible: Optional[int] = None
    state_inputs: tuple = ()
    nondiff_inputs: tuple = ()   # input indices with no gradient (e.g. indices)
    aliases: Sequence[str] = field(default_factory=tuple)
    defaults: dict = field(default_factory=dict)
    doc: str = ""
    # symbolic-composition hooks (set post-registration, see set_arg_select /
    # set_param_shapes). Reference analogues: OperatorProperty::ListArguments
    # (arg list depends on params, e.g. no_bias drops "bias") and backward
    # shape inference (InferShape fills weight shapes from data shape).
    arg_select: Optional[Callable] = None     # attrs -> tuple of active arg names
    param_shapes: Optional[Callable] = None   # (in_shapes list, attrs) -> list
    # attr names whose values enter the compiled program as TRACED scalars
    # instead of static constants — per-step hyperparams (Adam's
    # bias-corrected lr, schedules) then never trigger recompilation
    traced_attrs: tuple = ()

    @property
    def num_state(self):
        return len(self.state_inputs)

    def active_args(self, attrs):
        """Tensor-argument names active under these attrs."""
        if self.arg_names is None:
            return None
        if self.arg_select is not None:
            return tuple(self.arg_select(attrs))
        return self.arg_names


def set_arg_select(name, fn):
    """Install the ListArguments-style hook: fn(attrs) -> active arg names."""
    get_op(name).arg_select = fn


def set_param_shapes(name, fn):
    """Install backward shape inference: fn(in_shapes, attrs) -> full list of
    input shapes (in_shapes has None for unknown entries)."""
    get_op(name).param_shapes = fn


def register(name, *, arg_names=None, differentiable=True, needs_rng=False,
             takes_is_train=False, num_visible=None, state_inputs=(),
             nondiff_inputs=(), aliases=(), defaults=None, doc="",
             traced_attrs=()):
    """Decorator: register a pure-jax fn as an operator."""
    def deco(fn):
        op = OpDef(name=name, fn=fn,
                   arg_names=tuple(arg_names) if arg_names is not None else None,
                   differentiable=differentiable, needs_rng=needs_rng,
                   takes_is_train=takes_is_train, num_visible=num_visible,
                   state_inputs=tuple(state_inputs),
                   nondiff_inputs=tuple(nondiff_inputs),
                   aliases=tuple(aliases), defaults=dict(defaults or {}),
                   doc=doc or fn.__doc__ or "",
                   traced_attrs=tuple(traced_attrs))
        if name in _OP_REGISTRY:
            raise ValueError("duplicate op registration %r" % name)
        _OP_REGISTRY[name] = op
        for a in op.aliases:
            _ALIASES[a] = name
        return fn
    return deco


def get_op(name) -> OpDef:
    if name in _OP_REGISTRY:
        return _OP_REGISTRY[name]
    if name in _ALIASES:
        return _OP_REGISTRY[_ALIASES[name]]
    raise KeyError("operator %r is not registered" % (name,))


def list_ops():
    return sorted(set(_OP_REGISTRY) | set(_ALIASES))


# ---------------------------------------------------------------------------
# attr canonicalization — attrs arrive as python values or strings (symbol
# JSON round-trip, reference dmlc::Parameter string parsing).
# ---------------------------------------------------------------------------

def _parse_attr_value(v):
    if isinstance(v, str):
        s = v.strip()
        low = s.lower()
        if low in ("true", "false"):
            return low == "true"
        if low in ("none", "null"):
            return None
        try:
            return ast.literal_eval(s)
        except (ValueError, SyntaxError):
            return v
    return v


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    if isinstance(v, np.ndarray):
        return tuple(v.ravel().tolist()) if v.size < 64 else v.tobytes()
    if isinstance(v, np.generic):
        return v.item()
    return v


def canon_attrs(opdef, attrs):
    """Merge defaults, parse string values, make everything hashable."""
    out = dict(opdef.defaults)
    for k, v in attrs.items():
        if v is None and k not in opdef.defaults:
            out[k] = None
            continue
        out[k] = _hashable(_parse_attr_value(v))
    return out


# ---------------------------------------------------------------------------
# jit cache: one compiled callable per (op, static attrs); jax.jit itself
# then caches per input shape/dtype. This is the whole "engine".
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _jitted(name, attr_items, with_rng, traced_names):
    opdef = get_op(name)
    attrs = dict(attr_items)
    if traced_names:
        # traced scalars arrive as a leading tuple argument, so their
        # per-step values never enter the compile cache key
        def call(scals, *rest):
            kw = dict(zip(traced_names, scals))
            if with_rng:
                return opdef.fn(*rest[1:], rng=rest[0], **attrs, **kw)
            return opdef.fn(*rest, **attrs, **kw)
    elif with_rng:
        def call(rng, *arrays):
            return opdef.fn(*arrays, rng=rng, **attrs)
    else:
        def call(*arrays):
            return opdef.fn(*arrays, **attrs)
    return jax.jit(call)


def split_traced(opdef, attrs):
    """Split canonicalized attrs into (static attrs, traced names,
    traced values) per the op's traced_attrs declaration."""
    names = tuple(k for k in opdef.traced_attrs if k in attrs)
    if not names:
        return attrs, (), ()
    static = {k: v for k, v in attrs.items() if k not in opdef.traced_attrs}
    return static, names, tuple(float(attrs[k]) for k in names)


def jitted_op(opdef, attrs):
    """Compiled callable for (op, attrs). attrs must be canonicalized.
    For ops with traced_attrs, use invoke_eager (it routes the scalar
    values); this helper compiles everything statically."""
    return _jitted(opdef.name, tuple(sorted(attrs.items())),
                   opdef.needs_rng, ())


@functools.lru_cache(maxsize=None)
def _vjp_jitted(name, attr_items, with_rng, traced_names):
    """Jitted ``jax.vjp`` per (op, attrs) for the recording path: the
    returned vjp closure is a jax pytree (residual arrays + static
    structure), so it crosses the jit boundary and repeat calls with the
    same shapes skip retracing entirely (~30x on small eager steps).
    Traced scalars are closed over INSIDE the vjp, so they produce no
    cotangents and the tape structure is unchanged."""
    opdef = get_op(name)
    attrs = dict(attr_items)

    def make_pure(kw):
        if with_rng:
            def pure(rng, *arrays):
                return opdef.fn(*arrays, rng=rng, **attrs, **kw)
        else:
            def pure(*arrays):
                return opdef.fn(*arrays, **attrs, **kw)
        return pure

    if traced_names:
        def fwd(scals, *call_args):
            kw = dict(zip(traced_names, scals))
            return jax.vjp(make_pure(kw), *call_args)
    else:
        def fwd(*call_args):
            return jax.vjp(make_pure({}), *call_args)
    return jax.jit(fwd)


# backward application of a recorded vjp closure, jitted once per
# residual-tree structure (the closure is passed as a pytree argument)
@jax.jit
def _apply_vjp(vjp_fn, cts):
    return vjp_fn(cts)


# ---------------------------------------------------------------------------
# eager dispatch
# ---------------------------------------------------------------------------

def invoke_eager(opdef, nd_inputs, attrs, out=None):
    """Imperative invoke (analogue of ImperativeInvokeImpl,
    src/c_api/c_api_ndarray.cc:491): unwrap NDArrays, run the jitted kernel
    (recording an autograd tape node when grad recording is on), wrap
    outputs, apply aux-state writebacks and the ``out=`` destination."""
    from ..ndarray.ndarray import NDArray, _wrap, array  # late: avoid cycle
    from .. import autograd
    from .. import random as mx_random

    arrays = []
    for x in nd_inputs:
        if isinstance(x, NDArray):
            if x._stype != "default":
                # dense kernels would silently read the (nnz, ...) values
                # buffer; only the sparse-dispatch wrappers
                # (ndarray/sparse.py) may route sparse storage
                raise TypeError(
                    "operator %r has no sparse implementation for a %s "
                    "input — cast with tostype('default') first"
                    % (opdef.name, x._stype))
            arrays.append(x._data)
        else:
            arrays.append(array(x)._data)

    attrs = canon_attrs(opdef, attrs)
    if opdef.takes_is_train and "is_train" not in attrs:
        attrs["is_train"] = autograd.is_training()

    recording = autograd.is_recording() and opdef.differentiable

    if opdef.needs_rng:
        rng = mx_random.next_key()
        call_args = (rng,) + tuple(arrays)
    else:
        call_args = tuple(arrays)

    static_attrs, traced_names, traced_vals = split_traced(opdef, attrs)
    static_items = tuple(sorted(static_attrs.items()))
    if recording:
        # vjp at record time: residuals are saved on-device, backward is a
        # direct call of the linearized fn (analogue of AutogradRuntime
        # RecordOp, src/ndarray/autograd.cc — but the "re-symbolized graph"
        # is jax's linearization). Forward+linearize is one cached jitted
        # program per (op, attrs, shapes); applying the closure goes
        # through the jitted _apply_vjp so backward doesn't retrace either.
        fwd = _vjp_jitted(opdef.name, static_items, opdef.needs_rng,
                          traced_names)
        if traced_names:
            raw_out, raw_vjp = fwd(traced_vals, *call_args)
        else:
            raw_out, raw_vjp = fwd(*call_args)
        vjp_fn = functools.partial(_apply_vjp, raw_vjp)
    else:
        fn = _jitted(opdef.name, static_items, opdef.needs_rng,
                     traced_names)
        raw_out = fn(traced_vals, *call_args) if traced_names \
            else fn(*call_args)
        vjp_fn = None

    outs = list(raw_out) if isinstance(raw_out, (tuple, list)) else [raw_out]
    raw_shapes = tuple(o.shape for o in outs)
    raw_dtypes = tuple(o.dtype for o in outs)
    raw_is_tuple = isinstance(raw_out, (tuple, list))

    # aux-state writeback (BatchNorm moving stats, fused optimizer updates)
    n_state = opdef.num_state
    if n_state:
        state_outs = outs[-n_state:]
        outs = outs[:-n_state]
        for idx, val in zip(opdef.state_inputs, state_outs):
            tgt = nd_inputs[idx]
            if isinstance(tgt, NDArray):
                tgt._set_data(val)

    n_vis = opdef.num_visible if opdef.num_visible is not None else len(outs)
    visible = outs[:n_vis]

    nd_outs = [_wrap(o) for o in visible]

    if recording:
        autograd._record_op(opdef, nd_inputs, nd_outs, vjp_fn,
                            raw_shapes=raw_shapes, raw_dtypes=raw_dtypes,
                            raw_is_tuple=raw_is_tuple,
                            rng_offset=1 if opdef.needs_rng else 0)

    if out is not None:
        out_list = out if isinstance(out, (list, tuple)) else [out]
        for dst, src in zip(out_list, nd_outs):
            dst._set_data(src._data)
            # rebind (or clear) the tape entry so a stale node from an
            # earlier recording can't be traversed against new data
            dst._ag_entry = src._ag_entry if recording else None
        return out

    if len(nd_outs) == 1:
        return nd_outs[0]
    return nd_outs


def _timed_invoke(fn):
    """Profile hook: in 'all' mode every eager dispatch is timed into the
    host timeline (reference: engine profiler kAllOperator mode)."""
    @functools.wraps(fn)
    def wrapper(opdef, nd_inputs, attrs, out=None):
        from .. import profiler
        if profiler.is_running() and profiler.mode() == "all":
            with profiler.scope(opdef.name, "operator"):
                return fn(opdef, nd_inputs, attrs, out=out)
        return fn(opdef, nd_inputs, attrs, out=out)
    return wrapper


invoke_eager = _timed_invoke(invoke_eager)
