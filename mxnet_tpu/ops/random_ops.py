"""Random samplers — reference src/operator/random/ (SURVEY.md N11).

All take a traced PRNG key (needs_rng); eager calls split the global stream
(mx.random.seed reproducibility), compiled executors thread an explicit key.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import np_dtype
from .registry import register


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


def _rand(name, sampler, defaults, aliases=()):
    @register(name, arg_names=(), differentiable=False, needs_rng=True,
              aliases=aliases,
              defaults={**defaults, "shape": None, "dtype": "float32",
                        "ctx": None})
    def _f(shape=None, dtype="float32", rng=None, **kw):
        return sampler(rng, _shape(shape), np_dtype(dtype), kw)
    return _f


_rand("_random_uniform",
      lambda rng, s, dt, kw: jax.random.uniform(
          rng, s, dt, minval=kw.get("low", 0.0), maxval=kw.get("high", 1.0)),
      {"low": 0.0, "high": 1.0}, aliases=("uniform", "random_uniform"))

_rand("_random_normal",
      lambda rng, s, dt, kw: kw.get("loc", 0.0) + kw.get("scale", 1.0) *
      jax.random.normal(rng, s, dt),
      {"loc": 0.0, "scale": 1.0}, aliases=("normal", "random_normal",
                                           "randn"))

_rand("_random_exponential",
      lambda rng, s, dt, kw: jax.random.exponential(rng, s, dt) /
      kw.get("lam", 1.0),
      {"lam": 1.0}, aliases=("random_exponential", "exponential"))

_rand("_random_gamma",
      lambda rng, s, dt, kw: jax.random.gamma(
          rng, kw.get("alpha", 1.0), s, dt) * kw.get("beta", 1.0),
      {"alpha": 1.0, "beta": 1.0}, aliases=("random_gamma",))

_rand("_random_poisson",
      lambda rng, s, dt, kw: jax.random.poisson(
          rng, kw.get("lam", 1.0), s).astype(dt),
      {"lam": 1.0}, aliases=("random_poisson", "poisson"))

_rand("_random_negative_binomial",
      lambda rng, s, dt, kw: _neg_binomial(rng, kw.get("k", 1),
                                           kw.get("p", 1.0), s).astype(dt),
      {"k": 1, "p": 1.0}, aliases=("random_negative_binomial",
                                   "negative_binomial"))

_rand("_random_generalized_negative_binomial",
      lambda rng, s, dt, kw: _gen_neg_binomial(
          rng, kw.get("mu", 1.0), kw.get("alpha", 1.0), s).astype(dt),
      {"mu": 1.0, "alpha": 1.0},
      aliases=("random_generalized_negative_binomial",
               "generalized_negative_binomial"))


def _neg_binomial(rng, k, p, shape):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape)


def _gen_neg_binomial(rng, mu, alpha, shape):
    k1, k2 = jax.random.split(rng)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape)


@register("sample_multinomial", arg_names=("data",), differentiable=False,
          needs_rng=True, aliases=("_sample_multinomial",),
          defaults={"shape": None, "get_prob": False, "dtype": "int32"})
def _sample_multinomial(data, shape=None, get_prob=False, dtype="int32",
                        rng=None, **_):
    n = 1
    if shape:
        # static python arithmetic: jnp here would make `n` a tracer under
        # jit and int() of it fails (found by the op sweep)
        n = int(np.prod(_shape(shape)))
    logits = jnp.log(jnp.maximum(data, 1e-20))
    if data.ndim == 1:
        samples = jax.random.categorical(rng, logits, shape=(n,))
        out = samples if shape else samples[0]
    else:
        samples = jax.random.categorical(rng, logits[:, None, :], axis=-1,
                                         shape=(data.shape[0], n))
        out = samples if shape else samples[:, 0]
    out = out.astype(np_dtype(dtype))
    if get_prob:
        if data.ndim == 1:
            lp = jnp.log(jnp.maximum(data[out.astype(jnp.int32)], 1e-20))
        else:
            lp = jnp.log(jnp.maximum(jnp.take_along_axis(
                data, out.astype(jnp.int32).reshape(data.shape[0], -1),
                axis=-1), 1e-20)).reshape(out.shape)
        return out, lp
    return out


def _sample_vec(name, sampler):
    """`_sample_*` ops: per-distribution-parameter draws (reference
    src/operator/random/sample_op.cc multi-distribution samplers)."""
    @register(name, arg_names=None, differentiable=False, needs_rng=True,
              defaults={"shape": None, "dtype": "float32"})
    def _f(*params, shape=None, dtype="float32", rng=None, **_):
        s = _shape(shape)
        dt = np_dtype(dtype)
        p0 = params[0]
        full = p0.shape + s
        draws = sampler(rng, [jnp.broadcast_to(
            p.reshape(p.shape + (1,) * len(s)), full) for p in params],
            full, dt)
        return draws.astype(dt)
    return _f


_sample_vec("_sample_uniform",
            lambda rng, ps, s, dt: jax.random.uniform(rng, s, dt) *
            (ps[1] - ps[0]) + ps[0])
_sample_vec("_sample_normal",
            lambda rng, ps, s, dt: ps[0] +
            ps[1] * jax.random.normal(rng, s, dt))
_sample_vec("_sample_exponential",
            lambda rng, ps, s, dt: jax.random.exponential(rng, s, dt) / ps[0])
_sample_vec("_sample_gamma",
            lambda rng, ps, s, dt: jax.random.gamma(rng, ps[0], s, dt) *
            ps[1])
_sample_vec("_sample_poisson",
            lambda rng, ps, s, dt: jax.random.poisson(
                rng, ps[0], s).astype(dt))
_sample_vec("_sample_negative_binomial",
            lambda rng, ps, s, dt: _neg_binomial_arr(rng, ps[0], ps[1], s))
_sample_vec("_sample_generalized_negative_binomial",
            lambda rng, ps, s, dt: _gen_neg_binomial_arr(rng, ps[0], ps[1],
                                                         s))


def _neg_binomial_arr(rng, k, p, shape):
    k1, k2 = jax.random.split(rng)
    lam = jax.random.gamma(k1, k, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape)


def _gen_neg_binomial_arr(rng, mu, alpha, shape):
    k1, k2 = jax.random.split(rng)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape)


@register("shuffle", arg_names=("data",), differentiable=False,
          needs_rng=True, aliases=("_shuffle",))
def _shuffle(data, rng=None, **_):
    return jax.random.permutation(rng, data, axis=0)
