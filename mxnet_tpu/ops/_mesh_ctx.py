"""Ambient mesh for mesh-aware operators.

The executor announces the mesh it lowers a graph over; ops that can
exploit a mesh axis (e.g. `_contrib_FlashAttention(seq_axis='sp')`
switching to ring attention) read it at TRACE time. A contextvar —
not a threaded argument — so the 350-op registry keeps its pure
``fn(*arrays, **attrs)`` signature and only the ops that care opt in.

Eager calls run with no ambient mesh and fall back to the single-chip
kernel path.
"""
from __future__ import annotations

import contextlib
import contextvars

_AMBIENT_MESH = contextvars.ContextVar("mxnet_tpu_ambient_mesh",
                                       default=None)

__all__ = ["ambient_mesh", "active_mesh_axis", "use_mesh"]


def ambient_mesh():
    """The mesh the surrounding graph is being lowered over, or None."""
    return _AMBIENT_MESH.get()


def active_mesh_axis(axis_name):
    """The ambient mesh if it carries ``axis_name`` with >1 devices,
    else None — the single predicate every mesh-aware op's attr
    (seq_axis, expert_axis, ...) gates on."""
    mesh = _AMBIENT_MESH.get()
    if mesh is not None and axis_name in mesh.axis_names and \
            mesh.shape[axis_name] > 1:
        return mesh
    return None


@contextlib.contextmanager
def use_mesh(mesh):
    tok = _AMBIENT_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _AMBIENT_MESH.reset(tok)
