"""Output/loss ops with custom backward semantics.

Reference: src/operator/softmax_output*.cc, regression_output*.cc,
make_loss.cc, svm_output.cc. These ops' backward passes are NOT the vjp of
their forward (SoftmaxOutput forwards softmax but backprops cross-entropy
gradient) — implemented with ``jax.custom_vjp`` so both the eager tape and
jitted executors get the reference semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import nn as jnn

from .registry import register


def _norm_factor(normalization, label, valid_mask=None):
    if normalization == "batch":
        return float(label.shape[0]) if label.ndim else 1.0
    if normalization == "valid" and valid_mask is not None:
        return jnp.maximum(jnp.sum(valid_mask), 1.0)
    if normalization == "valid":
        return float(label.size)
    return 1.0


@register("SoftmaxOutput", arg_names=("data", "label"), nondiff_inputs=(1,),
          aliases=("Softmax",),
          defaults={"grad_scale": 1.0, "ignore_label": -1.0,
                    "multi_output": False, "use_ignore": False,
                    "preserve_shape": False, "normalization": "null",
                    "out_grad": False, "smooth_alpha": 0.0})
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False,
                    preserve_shape=False, normalization="null",
                    out_grad=False, smooth_alpha=0.0, **_):
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def f(d, l):
        # softmax statistics always in f32 (bf16 compute-dtype inputs
        # would lose probability mass); output back in input dtype
        return jnn.softmax(d.astype(jnp.float32),
                           axis=axis).astype(d.dtype)

    def fwd(d, l):
        p = f(d, l)
        return p, (p, l)

    def bwd(res, g):
        p, l = res
        li = l.astype(jnp.int32)
        nclass = p.shape[axis]
        if multi_output:
            onehot = jnp.moveaxis(
                jnn.one_hot(li, nclass, dtype=p.dtype), -1, 1)
        else:
            onehot = jnn.one_hot(li, nclass, dtype=p.dtype)
            if onehot.shape != p.shape:
                onehot = onehot.reshape(p.shape)
        if smooth_alpha:
            onehot = onehot * (1 - smooth_alpha) + smooth_alpha / nclass
        grad = p - onehot
        valid = None
        if use_ignore:
            keep = (l != ignore_label).astype(p.dtype)
            valid = keep
            kshape = list(l.shape)
            if multi_output:
                keep_b = jnp.expand_dims(keep, 1)
            else:
                keep_b = keep.reshape(kshape + [1] * (p.ndim - l.ndim))
            grad = grad * keep_b
        grad = grad * (grad_scale / _norm_factor(normalization, l, valid))
        return grad.astype(p.dtype), jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


def _regression(name, fwd_fn, grad_fn):
    @register(name, arg_names=("data", "label"), nondiff_inputs=(1,),
              defaults={"grad_scale": 1.0})
    def _f(data, label, grad_scale=1.0, **_):
        @jax.custom_vjp
        def f(d, l):
            return fwd_fn(d)

        def fwd(d, l):
            return fwd_fn(d), (fwd_fn(d), l)

        def bwd(res, g):
            out, l = res
            grad = grad_fn(out, l.reshape(out.shape)) * grad_scale
            return grad.astype(out.dtype), jnp.zeros_like(l)

        f.defvjp(fwd, bwd)
        return f(data, label)
    return _f


_regression("LinearRegressionOutput", lambda d: d, lambda o, l: o - l)
_regression("MAERegressionOutput", lambda d: d, lambda o, l: jnp.sign(o - l))
_regression("LogisticRegressionOutput", jnn.sigmoid, lambda o, l: o - l)


@register("MakeLoss", arg_names=("data",),
          defaults={"grad_scale": 1.0, "valid_thresh": 0.0,
                    "normalization": "null"})
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0,
               normalization="null", **_):
    @jax.custom_vjp
    def f(d):
        return d

    def fwd(d):
        return d, d

    def bwd(d, g):
        if normalization == "batch":
            scale = grad_scale / d.shape[0]
        elif normalization == "valid":
            scale = grad_scale / jnp.maximum(
                jnp.sum((d > valid_thresh).astype(d.dtype)), 1.0)
        else:
            scale = grad_scale
        return (jnp.full_like(d, 1.0) * scale,)

    f.defvjp(fwd, bwd)
    return f(data)


@register("SVMOutput", arg_names=("data", "label"), nondiff_inputs=(1,),
          defaults={"margin": 1.0, "regularization_coefficient": 1.0,
                    "use_linear": False})
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False, **_):
    @jax.custom_vjp
    def f(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        li = l.astype(jnp.int32)
        nclass = d.shape[-1]
        onehot = jnn.one_hot(li, nclass, dtype=d.dtype)
        score_y = jnp.take_along_axis(d, li[:, None], axis=-1)
        viol = (margin - (score_y - d)) > 0
        viol = viol & (onehot == 0)
        if use_linear:
            grad = viol.astype(d.dtype)
        else:
            grad = 2 * jnp.maximum(margin - (score_y - d), 0) * \
                viol.astype(d.dtype)
        grad = grad - onehot * jnp.sum(grad, axis=-1, keepdims=True)
        return grad * regularization_coefficient, jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("IdentityAttachKLSparseReg", arg_names=("data",),
          defaults={"sparseness_target": 0.1, "penalty": 0.001,
                    "momentum": 0.9})
def _identity_kl(data, **_):
    return data
