"""Output/loss ops with custom backward semantics.

Reference: src/operator/softmax_output*.cc, regression_output*.cc,
make_loss.cc, svm_output.cc. These ops' backward passes are NOT the vjp of
their forward (SoftmaxOutput forwards softmax but backprops cross-entropy
gradient) — implemented with ``jax.custom_vjp`` so both the eager tape and
jitted executors get the reference semantics.

Head-grad convention: every head multiplies its emitted gradient by the
incoming cotangent. All framework call sites pass all-ones head grads
(Executor.backward default, TrainStep), so results are unchanged there —
but a scaled cotangent now propagates through the head, which is what
lets dynamic loss scaling (mxnet_tpu/guardrail.py, cotangent =
``full(loss_scale)``) scale the whole low-precision backprop chain and
unscale exactly afterwards.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import nn as jnn

from .registry import register


def _norm_factor(normalization, label, valid_mask=None):
    if normalization == "batch":
        return float(label.shape[0]) if label.ndim else 1.0
    if normalization == "valid" and valid_mask is not None:
        return jnp.maximum(jnp.sum(valid_mask), 1.0)
    if normalization == "valid":
        return float(label.size)
    return 1.0


@register("SoftmaxOutput", arg_names=("data", "label"), nondiff_inputs=(1,),
          aliases=("Softmax",),
          defaults={"grad_scale": 1.0, "ignore_label": -1.0,
                    "multi_output": False, "use_ignore": False,
                    "preserve_shape": False, "normalization": "null",
                    "out_grad": False, "smooth_alpha": 0.0})
def _softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                    multi_output=False, use_ignore=False,
                    preserve_shape=False, normalization="null",
                    out_grad=False, smooth_alpha=0.0, **_):
    axis = 1 if multi_output else -1

    @jax.custom_vjp
    def f(d, l):
        # softmax statistics always in f32 (bf16 compute-dtype inputs
        # would lose probability mass); output back in input dtype
        return jnn.softmax(d.astype(jnp.float32),
                           axis=axis).astype(d.dtype)

    def fwd(d, l):
        p = f(d, l)
        return p, (p, l)

    def bwd(res, g):
        p, l = res
        li = l.astype(jnp.int32)
        nclass = p.shape[axis]
        if multi_output:
            onehot = jnp.moveaxis(
                jnn.one_hot(li, nclass, dtype=p.dtype), -1, 1)
        else:
            onehot = jnn.one_hot(li, nclass, dtype=p.dtype)
            if onehot.shape != p.shape:
                onehot = onehot.reshape(p.shape)
        if smooth_alpha:
            onehot = onehot * (1 - smooth_alpha) + smooth_alpha / nclass
        grad = p - onehot
        valid = None
        if use_ignore:
            keep = (l != ignore_label).astype(p.dtype)
            valid = keep
            kshape = list(l.shape)
            if multi_output:
                keep_b = jnp.expand_dims(keep, 1)
            else:
                keep_b = keep.reshape(kshape + [1] * (p.ndim - l.ndim))
            grad = grad * keep_b
        grad = grad * (grad_scale / _norm_factor(normalization, l, valid))
        grad = grad * g.astype(grad.dtype)
        return grad.astype(p.dtype), jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


def _regression(name, fwd_fn, grad_fn):
    @register(name, arg_names=("data", "label"), nondiff_inputs=(1,),
              defaults={"grad_scale": 1.0})
    def _f(data, label, grad_scale=1.0, **_):
        @jax.custom_vjp
        def f(d, l):
            return fwd_fn(d)

        def fwd(d, l):
            return fwd_fn(d), (fwd_fn(d), l)

        def bwd(res, g):
            out, l = res
            grad = grad_fn(out, l.reshape(out.shape)) * grad_scale
            grad = grad * g.astype(grad.dtype)
            return grad.astype(out.dtype), jnp.zeros_like(l)

        f.defvjp(fwd, bwd)
        return f(data, label)
    return _f


_regression("LinearRegressionOutput", lambda d: d, lambda o, l: o - l)
_regression("MAERegressionOutput", lambda d: d, lambda o, l: jnp.sign(o - l))
_regression("LogisticRegressionOutput", jnn.sigmoid, lambda o, l: o - l)


@register("MakeLoss", arg_names=("data",),
          defaults={"grad_scale": 1.0, "valid_thresh": 0.0,
                    "normalization": "null"})
def _make_loss(data, grad_scale=1.0, valid_thresh=0.0,
               normalization="null", **_):
    @jax.custom_vjp
    def f(d):
        return d

    def fwd(d):
        return d, d

    def bwd(d, g):
        if normalization == "batch":
            scale = grad_scale / d.shape[0]
        elif normalization == "valid":
            scale = grad_scale / jnp.maximum(
                jnp.sum((d > valid_thresh).astype(d.dtype)), 1.0)
        else:
            scale = grad_scale
        return (g.astype(d.dtype) * scale,)

    f.defvjp(fwd, bwd)
    return f(data)


@register("SVMOutput", arg_names=("data", "label"), nondiff_inputs=(1,),
          defaults={"margin": 1.0, "regularization_coefficient": 1.0,
                    "use_linear": False})
def _svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
                use_linear=False, **_):
    @jax.custom_vjp
    def f(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        li = l.astype(jnp.int32)
        nclass = d.shape[-1]
        onehot = jnn.one_hot(li, nclass, dtype=d.dtype)
        score_y = jnp.take_along_axis(d, li[:, None], axis=-1)
        viol = (margin - (score_y - d)) > 0
        viol = viol & (onehot == 0)
        if use_linear:
            grad = viol.astype(d.dtype)
        else:
            grad = 2 * jnp.maximum(margin - (score_y - d), 0) * \
                viol.astype(d.dtype)
        grad = grad - onehot * jnp.sum(grad, axis=-1, keepdims=True)
        grad = grad * regularization_coefficient * g.astype(grad.dtype)
        return grad, jnp.zeros_like(l)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("IdentityAttachKLSparseReg", arg_names=("data",),
          defaults={"sparseness_target": 0.1, "penalty": 0.001,
                    "momentum": 0.9})
def _identity_kl(data, **_):
    return data


@register("_contrib_ChunkedSoftmaxCE",
          arg_names=("data", "weight", "bias", "label"),
          nondiff_inputs=(3,),
          defaults={"chunk": 2048, "grad_scale": 1.0,
                    "ignore_label": -1.0, "use_ignore": False,
                    "normalization": "valid"})
def _chunked_softmax_ce(data, weight, bias, label, chunk=2048,
                        grad_scale=1.0, ignore_label=-1.0,
                        use_ignore=False, normalization="valid", **_):
    """Fused projection + softmax cross-entropy, chunked over rows.

    The monolithic LM head materializes (N, V) logits plus their f32
    softmax — at 64k tokens x 32k vocab that is >8 GB and is what
    OOMs long-context training on one chip (not attention: the flash
    kernels are O(T)). This op never holds more than (chunk, V)
    logits: a checkpointed `lax.map` over row chunks computes
    per-token NLL forward, and the scan backward REPLAYS each chunk's
    projection to form d(logits) locally, accumulating d(weight) in a
    single (V, D) f32 buffer.

    Semantics: MakeLoss-style — the op's output IS the per-token loss
    (already scaled by grad_scale / norm like SoftmaxOutput's
    backward, so head-grad ones gives the same parameter gradients as
    the FullyConnected+SoftmaxOutput head), shaped like `label`.
    No reference analogue (the reference predates LLM-scale vocab
    heads); the seam it replaces is FullyConnected(lm_head) +
    SoftmaxOutput (softmax_output.cc).
    """
    N = data.shape[0]
    V = weight.shape[0]
    chunk = max(1, min(int(chunk), N))
    pad = (-N) % chunk
    xf = data
    lab = label.reshape(-1).astype(jnp.int32)
    if pad:
        xf = jnp.concatenate(
            [xf, jnp.zeros((pad,) + xf.shape[1:], xf.dtype)])
        lab = jnp.concatenate(
            [lab, jnp.full((pad,), int(ignore_label), jnp.int32)])
    keep = jnp.ones_like(lab, jnp.float32)
    if use_ignore:
        keep = (lab != int(ignore_label)).astype(jnp.float32)
    elif pad:
        keep = jnp.concatenate(
            [jnp.ones((N,), jnp.float32), jnp.zeros((pad,),
                                                    jnp.float32)])
    if normalization == "batch":
        norm = float(N)
    elif normalization == "valid":
        norm = jnp.maximum(jnp.sum(keep), 1.0)
    else:
        norm = 1.0
    scale = grad_scale / norm

    K = xf.shape[0] // chunk
    xs = xf.reshape(K, chunk, -1)
    ls = lab.reshape(K, chunk)
    ks = keep.reshape(K, chunk)

    @jax.checkpoint
    def chunk_nll(args):
        x_c, l_c, k_c = args
        # bf16 inputs ride the MXU; f32 accumulate + f32 softmax math
        logits = jnp.dot(x_c, weight.T,
                         preferred_element_type=jnp.float32)
        logits = logits + bias.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, jnp.clip(l_c, 0, V - 1)[:, None], axis=-1)[:, 0]
        return (lse - picked) * k_c * scale

    out = jax.lax.map(chunk_nll, (xs, ls, ks)).reshape(-1)
    return out[:N].astype(jnp.float32)
