"""Fused optimizer update ops — reference src/operator/optimizer_op.*
(SURVEY.md N12): each Python Optimizer step is ONE fused op. Under XLA the
whole update fuses into a single elementwise kernel per parameter (and can
further fuse into the training step when jitted) — the TPU analogue of the
reference's single engine push per update.

Calling convention mirrors the reference: ``sgd_update(w, g, out=w)``
in-place on the weight; optimizer state tensors (momentum, adam mean/var)
are declared ``state_inputs`` so they are updated in place too.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _prep(grad, wd, weight, rescale_grad, clip_gradient):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


_COMMON = {"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0,
           "clip_gradient": -1.0}


@register("sgd_update", traced_attrs=('lr', 'wd', 'rescale_grad'), arg_names=("weight", "grad"), differentiable=False,
          defaults=_COMMON)
def _sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                clip_gradient=-1.0, **_):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    return weight - lr * g


@register("sgd_mom_update", traced_attrs=('lr', 'momentum', 'wd', 'rescale_grad'), arg_names=("weight", "grad", "mom"),
          differentiable=False, state_inputs=(2,),
          defaults={**_COMMON, "momentum": 0.0})
def _sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                    rescale_grad=1.0, clip_gradient=-1.0, **_):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    new_mom = momentum * mom - lr * g
    return weight + new_mom, new_mom


@register("mp_sgd_update", traced_attrs=('lr', 'wd', 'rescale_grad'), arg_names=("weight", "grad", "weight32"),
          differentiable=False, state_inputs=(2,), defaults=_COMMON)
def _mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, **_):
    g = _prep(grad.astype(jnp.float32), wd, weight32, rescale_grad,
              clip_gradient)
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@register("mp_sgd_mom_update", traced_attrs=('lr', 'momentum', 'wd', 'rescale_grad'),
          arg_names=("weight", "grad", "mom", "weight32"),
          differentiable=False, state_inputs=(2, 3),
          defaults={**_COMMON, "momentum": 0.0})
def _mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                       wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **_):
    g = _prep(grad.astype(jnp.float32), wd, weight32, rescale_grad,
              clip_gradient)
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@register("adam_update", traced_attrs=('lr', 'beta1', 'beta2', 'epsilon', 'wd', 'rescale_grad'), arg_names=("weight", "grad", "mean", "var"),
          differentiable=False, state_inputs=(2, 3),
          defaults={**_COMMON, "beta1": 0.9, "beta2": 0.999,
                    "epsilon": 1e-8})
def _adam_update(weight, grad, mean, var, lr=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0, **_):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    new_w = weight - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
    return new_w, new_mean, new_var


@register("rmsprop_update", traced_attrs=('lr', 'gamma1', 'epsilon', 'wd', 'rescale_grad'), arg_names=("weight", "grad", "n"),
          differentiable=False, state_inputs=(2,),
          defaults={**_COMMON, "gamma1": 0.95, "epsilon": 1e-8,
                    "clip_weights": -1.0})
def _rmsprop_update(weight, grad, n, lr=0.01, gamma1=0.95, epsilon=1e-8,
                    wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                    clip_weights=-1.0, **_):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    new_n = (1 - gamma1) * jnp.square(g) + gamma1 * n
    new_w = weight - lr * g / jnp.sqrt(new_n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n


@register("rmspropalex_update", traced_attrs=('lr', 'gamma1', 'gamma2', 'epsilon', 'wd', 'rescale_grad'), arg_names=("weight", "grad", "n", "g",
                                           "delta"),
          differentiable=False, state_inputs=(2, 3, 4),
          defaults={**_COMMON, "gamma1": 0.95, "gamma2": 0.9,
                    "epsilon": 1e-8, "clip_weights": -1.0})
def _rmspropalex_update(weight, grad, n, g, delta, lr=0.01, gamma1=0.95,
                        gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                        clip_gradient=-1.0, clip_weights=-1.0, **_):
    gr = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    new_n = (1 - gamma1) * jnp.square(gr) + gamma1 * n
    new_g = (1 - gamma1) * gr + gamma1 * g
    new_delta = gamma2 * delta - lr * gr / \
        jnp.sqrt(new_n - jnp.square(new_g) + epsilon)
    new_w = weight + new_delta
    if clip_weights is not None and clip_weights > 0:
        new_w = jnp.clip(new_w, -clip_weights, clip_weights)
    return new_w, new_n, new_g, new_delta


@register("ftrl_update", traced_attrs=('lr', 'lamda1', 'beta', 'wd', 'rescale_grad'), arg_names=("weight", "grad", "z", "n"),
          differentiable=False, state_inputs=(2, 3),
          defaults={**_COMMON, "lamda1": 0.01, "beta": 1.0})
def _ftrl_update(weight, grad, z, n, lr=0.01, lamda1=0.01, beta=1.0,
                 wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **_):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * weight
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1, 0.0,
        -(new_z - jnp.sign(new_z) * lamda1) /
        ((beta + jnp.sqrt(new_n)) / lr + wd))
    return new_w, new_z, new_n


@register("signsgd_update", traced_attrs=('lr', 'wd', 'rescale_grad'), arg_names=("weight", "grad"),
          differentiable=False, defaults=_COMMON)
def _signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                    clip_gradient=-1.0, **_):
    g = _prep(grad, wd, weight, rescale_grad, clip_gradient)
    return weight - lr * jnp.sign(g)
