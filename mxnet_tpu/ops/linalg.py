"""Linear-algebra ops — reference src/operator/tensor/la_op.* (SURVEY.md
N11): _linalg_{gemm, gemm2, potrf, potri, trmm, trsm, syrk, gelqf,
sumlogdiag}. Batched via jnp broadcasting / vmap-free matmul semantics.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax
import jax

from .registry import register


def _t(x, transpose):
    return jnp.swapaxes(x, -1, -2) if transpose else x


@register("_linalg_gemm", arg_names=("A", "B", "C"), aliases=("linalg_gemm",),
          defaults={"transpose_a": False, "transpose_b": False,
                    "alpha": 1.0, "beta": 1.0})
def _gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
          beta=1.0, **_):
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b)) + \
        beta * C


@register("_linalg_gemm2", arg_names=("A", "B"), aliases=("linalg_gemm2",),
          defaults={"transpose_a": False, "transpose_b": False,
                    "alpha": 1.0})
def _gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, **_):
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b))


@register("_linalg_potrf", arg_names=("A",), aliases=("linalg_potrf",))
def _potrf(A, **_):
    return jnp.linalg.cholesky(A)


@register("_linalg_potri", arg_names=("A",), aliases=("linalg_potri",))
def _potri(A, **_):
    """Inverse of a SPD matrix given its Cholesky factor A (lower)."""
    ident = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = lax.linalg.triangular_solve(A, ident, left_side=True, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("_linalg_trmm", arg_names=("A", "B"), aliases=("linalg_trmm",),
          defaults={"transpose": False, "rightside": False, "alpha": 1.0})
def _trmm(A, B, transpose=False, rightside=False, alpha=1.0, **_):
    tri = _t(jnp.tril(A), transpose)  # A assumed lower-triangular
    if rightside:
        return alpha * jnp.matmul(B, tri)
    return alpha * jnp.matmul(tri, B)


@register("_linalg_trsm", arg_names=("A", "B"), aliases=("linalg_trsm",),
          defaults={"transpose": False, "rightside": False, "alpha": 1.0})
def _trsm(A, B, transpose=False, rightside=False, alpha=1.0, **_):
    out = lax.linalg.triangular_solve(
        jnp.tril(A), alpha * B, left_side=not rightside, lower=True,
        transpose_a=transpose)
    return out


@register("_linalg_syrk", arg_names=("A",), aliases=("linalg_syrk",),
          defaults={"transpose": False, "alpha": 1.0})
def _syrk(A, transpose=False, alpha=1.0, **_):
    At = _t(A, True)
    if transpose:
        return alpha * jnp.matmul(At, A)
    return alpha * jnp.matmul(A, At)


@register("_linalg_gelqf", arg_names=("A",), aliases=("linalg_gelqf",))
def _gelqf(A, **_):
    """LQ factorization: A = L Q with Q orthonormal rows. Returns (Q, L)
    in the reference's output order (la_op.cc:508 `Q, L = gelqf(A)`)."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(q, -1, -2), jnp.swapaxes(r, -1, -2)


@register("_linalg_sumlogdiag", arg_names=("A",),
          aliases=("linalg_sumlogdiag",))
def _sumlogdiag(A, **_):
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register("khatri_rao", arg_names=None,
          aliases=("_khatri_rao", "_contrib_krprod"))
def _khatri_rao(*args, **_):
    """Column-wise Khatri-Rao product (reference contrib krprod.h)."""
    out = args[0]
    for b in args[1:]:
        out = (out[:, None, :] * b[None, :, :]).reshape(-1, out.shape[-1])
    return out
