"""Contrib op tail: fft/ifft, count_sketch, quantize/dequantize.

Reference: src/operator/contrib/{fft,ifft,count_sketch,quantize,
dequantize}-inl.h. The cuFFT-backed ops become jnp.fft (XLA lowers to
the TPU FFT implementation); count_sketch's scatter-add hashing becomes
one segment_sum; quantization keeps the reference's affine uint8
mapping and min/max plumbing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register


@register("_contrib_fft", arg_names=("data",),
          aliases=("fft",), defaults={"compute_size": 128})
def _fft(data, **_):
    """Real input (..., d) -> (..., 2d) interleaved [re, im] along the
    last axis (reference fft-inl.h layout)."""
    out = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    inter = jnp.stack([out.real, out.imag], axis=-1)
    return inter.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(data.dtype)


@register("_contrib_ifft", arg_names=("data",),
          aliases=("ifft",), defaults={"compute_size": 128})
def _ifft(data, **_):
    """Interleaved (..., 2d) -> real (..., d). Like the reference (cuFFT
    inverse), the result is NOT normalized: ifft(fft(x)) == d * x."""
    d = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (d, 2)).astype(jnp.float32)
    comp = jax.lax.complex(pairs[..., 0], pairs[..., 1])
    return (jnp.fft.ifft(comp, axis=-1).real * d).astype(data.dtype)


@register("_contrib_count_sketch", arg_names=("data", "h", "s"),
          nondiff_inputs=(1, 2),
          defaults={"out_dim": 0, "processing_batch_size": 32})
def _count_sketch(data, h, s, out_dim=0, **_):
    """Count-sketch projection (reference count_sketch-inl.h):
    out[..., h[j]] += s[j] * in[..., j]; h (1, in_dim) hash buckets,
    s (1, in_dim) signs."""
    in_dim = data.shape[-1]
    hh = h.reshape(-1)[:in_dim].astype(jnp.int32)
    ss = s.reshape(-1)[:in_dim].astype(data.dtype)
    flat = data.reshape(-1, in_dim)
    contrib = flat * ss[None, :]
    out = jax.ops.segment_sum(contrib.T, hh,
                              num_segments=int(out_dim)).T
    return out.reshape(data.shape[:-1] + (int(out_dim),))


@register("_contrib_quantize", arg_names=("data", "min_range", "max_range"),
          differentiable=False, aliases=("quantize",),
          defaults={"out_type": "uint8"})
def _quantize(data, min_range, max_range, out_type="uint8", **_):
    """Affine quantization to uint8/int8 (reference quantize-inl.h):
    out = (in - min) * (limit_range / (max - min)) + 0.5; min/max pass
    through as outputs 1/2."""
    lo, hi = (0.0, 255.0) if out_type == "uint8" else (-127.0, 127.0)
    dt = jnp.uint8 if out_type == "uint8" else jnp.int8
    scale = (hi - lo) / (max_range - min_range)
    # floor(v + 0.5): round-half-up on both signs (int8 negatives would
    # truncate toward zero under a bare cast)
    q = jnp.floor((data - min_range) * scale + lo + 0.5)
    return (jnp.clip(q, lo, hi).astype(dt),
            min_range.reshape(()).astype(jnp.float32),
            max_range.reshape(()).astype(jnp.float32))


@register("_contrib_dequantize", arg_names=("data", "min_range",
                                            "max_range"),
          differentiable=False, aliases=("dequantize",),
          defaults={"out_type": "float32"})
def _dequantize(data, min_range, max_range, out_type="float32", **_):
    """Inverse of quantize (reference dequantize-inl.h): for uint8,
    out = in * ((max - min) / 255) + min."""
    if data.dtype == jnp.uint8:
        lo, hi = 0.0, 255.0
    else:                      # int8
        lo, hi = -127.0, 127.0
    scale = (max_range - min_range) / (hi - lo)
    return ((data.astype(jnp.float32) - lo) * scale + min_range) \
        .astype(np.dtype(out_type))


@register("_contrib_QuantizedFullyConnected",
          arg_names=("data", "weight", "scale", "bias"),
          differentiable=False,
          defaults={"num_hidden": 0, "no_bias": False,
                    "flatten": True})
def _quantized_fc(data, weight, scale, bias=None, num_hidden=0,
                  no_bias=False, flatten=True, **_):
    """Weight-only int8 FullyConnected — the TPU serving quantization.

    weight: int8 (num_hidden, in), per-output-channel symmetric;
    scale: f32 (num_hidden,) with w_f32 ~= weight * scale[:, None].
    Decode is HBM-bandwidth-bound (every token streams the full weight
    set), so halving weight bytes directly buys decode throughput; the
    int8->compute-dtype convert fuses into the matmul's operand read.
    The scale applies AFTER the matmul (per output channel — identical
    algebra, O(N*out) instead of O(out*in) multiplies).

    Modernizes the reference's contrib quantize story
    (src/operator/contrib/quantize-inl.h — elementwise affine quantize
    ops, kept as `_contrib_quantize`/`_contrib_dequantize` above) into
    an actual quantized-layer op. Inference-only (not differentiable);
    generation.Generator(quantize="int8") builds on it."""
    cdt = data.dtype
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    w = weight.astype(cdt)
    y = jax.lax.dot_general(
        data, w, (((data.ndim - 1,), (1,)), ((), ())),
        precision=jax.lax.Precision.DEFAULT,
        preferred_element_type=jnp.float32)
    y = (y * scale.astype(jnp.float32)).astype(cdt)
    if not no_bias and bias is not None:
        y = y + bias.astype(cdt)
    return y


@register("_contrib_QuantizedEmbedding",
          arg_names=("data", "weight", "scale"),
          differentiable=False,
          defaults={"input_dim": 0, "output_dim": 0,
                    "dtype": "float32"})
def _quantized_embedding(data, weight, scale, dtype="float32", **_):
    """Weight-only int8 Embedding: weight int8 (V, D) with per-ROW
    symmetric scales (V,) — a token lookup reads one int8 row and one
    f32 scalar. Halves the (often largest) parameter's HBM footprint
    at serving; the gather itself is unchanged. dtype: output dtype
    (same attr convention as Embedding) so a bf16 compute stream is
    not silently promoted to f32."""
    ids = data.astype(jnp.int32)
    rows = jnp.take(weight, ids, axis=0).astype(jnp.float32)
    out = rows * jnp.take(scale, ids, axis=0)[..., None]
    return out.astype(np.dtype(dtype))


@register("_contrib_MoEFFN",
          arg_names=("data", "gate_weight", "expert_w1", "expert_w2"),
          aliases=("_contrib_moe_ffn",),
          defaults={"capacity_factor": 1.25, "expert_axis": None})
def _moe_ffn_op(data, gate_weight, expert_w1, expert_w2,
                capacity_factor=1.25, expert_axis=None, **_):
    """Switch-style top-1 mixture-of-experts FFN (single-program form of
    parallel/moe.py — same routing math, no collectives; under a GSPMD
    mesh the expert dim shards like any other tensor).

    data (B, T, D) or (N, D); gate_weight (D, E); expert_w1 (E, D, H);
    expert_w2 (E, H, D). Tokens beyond an expert's capacity
    (ceil(N * capacity_factor / E)) output zero — pair with a residual.

    expert_axis: mesh-axis name for EXPLICIT expert parallelism. When
    the surrounding graph lowers over a mesh carrying that axis (>1
    devices), experts live sharded on it and tokens exchange via
    all_to_all (parallel/moe.py moe_ffn) instead of relying on GSPMD
    propagation. Inert eagerly / off-mesh — same ambient-mesh contract
    as FlashAttention's seq_axis.
    """
    orig_shape = data.shape
    x = data.reshape(-1, orig_shape[-1])
    if expert_axis:
        from ._mesh_ctx import active_mesh_axis
        mesh = active_mesh_axis(expert_axis)
        if mesh is not None:
            n = mesh.shape[expert_axis]
            if x.shape[0] % n:
                raise ValueError(
                    "expert_axis=%r: token count %d (=prod of %r[:-1]) "
                    "must divide over the %d devices of that mesh axis"
                    % (expert_axis, x.shape[0], orig_shape, n))
            if gate_weight.shape[1] % n:
                raise ValueError(
                    "expert_axis=%r: num_experts %d must divide over "
                    "the %d devices of that mesh axis"
                    % (expert_axis, gate_weight.shape[1], n))
            from ..parallel.moe import moe_ffn
            out = moe_ffn(x, gate_weight, expert_w1, expert_w2, mesh,
                          axis_name=expert_axis,
                          capacity_factor=float(capacity_factor))
            return out.astype(data.dtype).reshape(orig_shape)
    from ..parallel.moe import dense_moe
    out = dense_moe(x, gate_weight, expert_w1, expert_w2,
                    capacity_factor=float(capacity_factor))
    return out.astype(data.dtype).reshape(orig_shape)
