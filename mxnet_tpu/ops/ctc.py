"""Connectionist Temporal Classification loss.

Reference semantics: src/operator/contrib/ctc_loss.cc (warp-ctc backed):
  data (T, B, C) activations (softmax applied internally), label (B, L)
  integer matrix, optional data_lengths/label_lengths (B,) inputs, and
  blank_label in {"first", "last"}:
    first: channel 0 is blank, labels use 1..C-1, label padding value 0
    last:  channel C-1 is blank, labels use 0..C-2, label padding value -1
  output: per-example negative log likelihood (B,).

TPU-native implementation: the alpha-recursion dynamic program runs as a
`lax.scan` inside optax.ctc_loss — fixed shapes, fully differentiable via
autodiff, no host callbacks.
"""
from __future__ import annotations

import jax.numpy as jnp
import optax

from .registry import register, set_arg_select


@register("CTCLoss",
          arg_names=("data", "label", "data_lengths", "label_lengths"),
          aliases=("ctc_loss", "_contrib_CTCLoss", "_contrib_ctc_loss"),
          nondiff_inputs=(1, 2, 3),
          defaults={"use_data_lengths": False, "use_label_lengths": False,
                    "blank_label": "first"})
def _ctc_loss(data, label, *lens, use_data_lengths=False,
              use_label_lengths=False, blank_label="first", **_):
    # optional length inputs arrive positionally in active-arg order
    # (arg_select below drops the inactive ones from the signature)
    lens = list(lens)
    data_lengths = lens.pop(0) if use_data_lengths and lens else None
    label_lengths = lens.pop(0) if use_label_lengths and lens else None
    T, B, C = data.shape
    logits = jnp.transpose(data, (1, 0, 2))          # (B, T, C)

    if use_data_lengths and data_lengths is not None:
        steps = jnp.arange(T)[None, :]
        logit_pad = (steps >= data_lengths[:, None].astype(jnp.int32)
                     ).astype(logits.dtype)
    else:
        logit_pad = jnp.zeros((B, T), logits.dtype)

    lab = label.astype(jnp.int32)
    if blank_label == "first":
        blank_id = 0
        pad_mask_src = lab == 0
    else:
        blank_id = C - 1
        pad_mask_src = lab < 0
        lab = jnp.maximum(lab, 0)

    if use_label_lengths and label_lengths is not None:
        pos = jnp.arange(lab.shape[1])[None, :]
        label_pad = (pos >= label_lengths[:, None].astype(jnp.int32)
                     ).astype(logits.dtype)
    else:
        label_pad = pad_mask_src.astype(logits.dtype)
    lab = jnp.where(label_pad > 0, 0, lab)

    return optax.ctc_loss(logits, logit_pad, lab, label_pad,
                          blank_id=blank_id)


def _ctc_args(attrs):
    names = ["data", "label"]
    if attrs.get("use_data_lengths"):
        names.append("data_lengths")
    if attrs.get("use_label_lengths"):
        names.append("label_lengths")
    return tuple(names)


set_arg_select("CTCLoss", _ctc_args)
