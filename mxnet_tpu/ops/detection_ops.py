"""SSD detection op stack: MultiBoxPrior / MultiBoxTarget /
MultiBoxDetection + ROIPooling.

Reference semantics:
  src/operator/contrib/multibox_prior.cc:35-71 (anchor generation),
  src/operator/contrib/multibox_target.cc:30-280 (bipartite + threshold
    matching, hard-negative mining, loc encoding),
  src/operator/contrib/multibox_detection.cc:44-168 (decode + NMS),
  src/operator/roi_pooling.cc:40-110 (max pooling over ROI bins).

TPU-native design notes: everything is fixed-shape and jittable. The
reference's data-dependent loops become:
  * bipartite matching -> lax.fori_loop over the (static) max-gt count,
    each step a vectorized argmax over the masked IoU matrix;
  * compaction of valid detections -> a full sort by (validity, score);
  * NMS -> lax.fori_loop over sorted rows with a vectorized suppression
    mask per step (O(A) work per step instead of the reference's nested
    scalar loops).
One deliberate deviation: with nms_topk set, rows beyond topk are
suppressed (-1) rather than left holding stale pre-sort content as the
reference's buffer-reuse does.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from .registry import register

_F = jnp.float32


def _box_iou_corner(a, b):
    """IoU between two sets of corner boxes: a (..., Na, 4), b (..., Nb, 4)
    -> (..., Na, Nb). Matches CalculateOverlap (multibox_detection.cc:74)."""
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, axis=-1)           # (..., Na, 1)
    bx1, by1, bx2, by2 = [v[..., None, :, 0] for v in
                          jnp.split(b, 4, axis=-1)]          # (..., 1, Nb)
    iw = jnp.maximum(0.0, jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1))
    ih = jnp.maximum(0.0, jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1))
    inter = iw * ih
    union = (ax2 - ax1) * (ay2 - ay1) + \
        (bx2 - bx1) * (by2 - by1) - inter
    return jnp.where(union <= 0, 0.0, inter / jnp.maximum(union, 1e-12))


# ---------------------------------------------------------------------------
# MultiBoxPrior
# ---------------------------------------------------------------------------
@register("_contrib_MultiBoxPrior", arg_names=("data",),
          differentiable=False,
          aliases=("MultiBoxPrior", "_contrib_multibox_prior"),
          defaults={"sizes": (1.0,), "ratios": (1.0,), "clip": False,
                    "steps": (-1.0, -1.0), "offsets": (0.5, 0.5)})
def _multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                    steps=(-1.0, -1.0), offsets=(0.5, 0.5), **_):
    """Anchors from a feature map: (1, H*W*num_anchors, 4) corner boxes in
    [0,1] image coordinates; num_anchors = len(sizes)-1+len(ratios)."""
    h, w = data.shape[2], data.shape[3]
    sizes = np.atleast_1d(np.asarray(sizes, np.float32))
    ratios = np.atleast_1d(np.asarray(ratios, np.float32))
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w

    cy = (np.arange(h, dtype=np.float32) + offsets[0]) * step_y
    cx = (np.arange(w, dtype=np.float32) + offsets[1]) * step_x

    # per-location half-extents, reference order: all sizes at ratio 1,
    # then ratios[1:] at sizes[0]
    ws, hs = [], []
    for s in sizes:
        ws.append(s * h / w / 2.0)
        hs.append(s / 2.0)
    for r in ratios[1:]:
        sr = np.sqrt(r)
        ws.append(sizes[0] * h / w * sr / 2.0)
        hs.append(sizes[0] / sr / 2.0)
    ws = np.asarray(ws, np.float32)     # (K,)
    hs = np.asarray(hs, np.float32)

    cyg, cxg = np.meshgrid(cy, cx, indexing="ij")     # (h, w)
    cxg = cxg[:, :, None]
    cyg = cyg[:, :, None]
    boxes = np.stack([cxg - ws, cyg - hs, cxg + ws, cyg + hs],
                     axis=-1)                         # (h, w, K, 4)
    boxes = boxes.reshape(1, -1, 4)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    return jnp.asarray(boxes)


# ---------------------------------------------------------------------------
# MultiBoxTarget
# ---------------------------------------------------------------------------
def _encode_loc(anchors, gt_boxes, variances):
    """(gx-ax)/aw/vx ... per multibox_target.cc:30-54. anchors (A,4),
    gt_boxes (A,4) matched per anchor."""
    vx, vy, vw, vh = variances
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    gw = gt_boxes[:, 2] - gt_boxes[:, 0]
    gh = gt_boxes[:, 3] - gt_boxes[:, 1]
    gx = (gt_boxes[:, 0] + gt_boxes[:, 2]) * 0.5
    gy = (gt_boxes[:, 1] + gt_boxes[:, 3]) * 0.5
    # reference quirk kept: x offset divides by aw, y offset by ah
    tx = (gx - ax) / jnp.maximum(aw, 1e-12) / vx
    ty = (gy - ay) / jnp.maximum(ah, 1e-12) / vy
    tw = jnp.log(jnp.maximum(gw / jnp.maximum(aw, 1e-12), 1e-12)) / vw
    th = jnp.log(jnp.maximum(gh / jnp.maximum(ah, 1e-12), 1e-12)) / vh
    return jnp.stack([tx, ty, tw, th], axis=1)


def _target_one(anchors, labels, cls_preds, overlap_threshold,
                ignore_label, negative_mining_ratio,
                negative_mining_thresh, minimum_negative_samples,
                variances):
    """Targets for ONE batch element. anchors (A,4), labels (L,W),
    cls_preds (C, A)."""
    A = anchors.shape[0]
    L = labels.shape[0]

    # valid gt prefix: first column == -1 terminates
    invalid = labels[:, 0] < 0
    num_valid = jnp.argmax(jnp.concatenate(
        [invalid, jnp.array([True])]).astype(jnp.int32))
    gt_valid = jnp.arange(L) < num_valid                   # (L,)

    ious = _box_iou_corner(anchors, labels[:, 1:5])        # (A, L)
    ious = jnp.where(gt_valid[None, :], ious, -1.0)

    # phase 1: greedy bipartite matching, one gt per iteration
    def bip_step(_i, st):
        match_iou, match_gt, a_flag, g_flag = st
        masked = jnp.where((a_flag[:, None] != 1) & (~g_flag[None, :]),
                           ious, -1.0)
        flat = jnp.argmax(masked)
        bi, bk = flat // L, flat % L
        ok = masked[bi, bk] > 1e-6
        match_iou = jnp.where(ok, match_iou.at[bi].set(masked[bi, bk]),
                              match_iou)
        match_gt = jnp.where(ok, match_gt.at[bi].set(bk), match_gt)
        a_flag = jnp.where(ok, a_flag.at[bi].set(1), a_flag)
        g_flag = jnp.where(ok, g_flag.at[bk].set(True), g_flag)
        return match_iou, match_gt, a_flag, g_flag

    st = (jnp.full((A,), -1.0), jnp.full((A,), -1, jnp.int32),
          jnp.full((A,), -1, jnp.int32), jnp.zeros((L,), bool))
    match_iou, match_gt, a_flag, _ = lax.fori_loop(0, L, bip_step, st)

    # phase 2: per-anchor best gt; positive where iou > threshold
    best_gt = jnp.argmax(ious, axis=1)
    best_iou = jnp.max(ious, axis=1)
    un = a_flag != 1
    has_any = best_iou > -1.0
    match_iou = jnp.where(un & has_any, best_iou, match_iou)
    match_gt = jnp.where(un & has_any, best_gt, match_gt)
    if overlap_threshold > 0:
        pos2 = un & (best_iou > overlap_threshold)
        a_flag = jnp.where(pos2, 1, a_flag)

    positive = a_flag == 1
    num_positive = positive.sum()

    if negative_mining_ratio > 0:
        # hard negatives: highest background prob among candidates
        prob_bg = jax.nn.softmax(cls_preds, axis=0)[0]     # (A,)
        cand = (~positive) & (match_iou < negative_mining_thresh)
        score = jnp.where(cand, -prob_bg, -jnp.inf)        # descend: -prob
        order = jnp.argsort(-score)                        # best first
        rank = jnp.argsort(order)
        num_neg = jnp.minimum(
            (num_positive * negative_mining_ratio).astype(jnp.int32),
            A - num_positive)
        num_neg = jnp.maximum(num_neg, minimum_negative_samples)
        negative = cand & (rank < num_neg)
        a_flag = jnp.where(negative, 0, a_flag)
    else:
        a_flag = jnp.where(positive, 1, 0)

    # targets
    safe_gt = jnp.maximum(match_gt, 0)
    gt_cls = labels[safe_gt, 0]
    cls_target = jnp.full((A,), float(ignore_label))
    cls_target = jnp.where(a_flag == 0, 0.0, cls_target)
    cls_target = jnp.where(a_flag == 1, gt_cls + 1.0, cls_target)

    loc = _encode_loc(anchors, labels[safe_gt, 1:5], variances)   # (A,4)
    loc_mask = (a_flag == 1).astype(_F)[:, None] * jnp.ones((1, 4), _F)
    loc_target = loc * loc_mask

    # no valid gt: everything stays at init (loc 0, mask 0, cls ignore)
    none = num_valid == 0
    cls_target = jnp.where(none, float(ignore_label), cls_target)
    loc_target = jnp.where(none, 0.0, loc_target)
    loc_mask = jnp.where(none, 0.0, loc_mask)
    return (loc_target.reshape(-1), loc_mask.reshape(-1), cls_target)


@register("_contrib_MultiBoxTarget",
          arg_names=("anchor", "label", "cls_pred"),
          differentiable=False, num_visible=3,
          aliases=("MultiBoxTarget", "_contrib_multibox_target"),
          defaults={"overlap_threshold": 0.5, "ignore_label": -1.0,
                    "negative_mining_ratio": -1.0,
                    "negative_mining_thresh": 0.5,
                    "minimum_negative_samples": 0,
                    "variances": (0.1, 0.1, 0.2, 0.2)})
def _multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                     ignore_label=-1.0, negative_mining_ratio=-1.0,
                     negative_mining_thresh=0.5,
                     minimum_negative_samples=0,
                     variances=(0.1, 0.1, 0.2, 0.2), **_):
    """anchor (1,A,4), label (B,L,W>=5), cls_pred (B,C,A) ->
    loc_target (B,A*4), loc_mask (B,A*4), cls_target (B,A)."""
    anchors = anchor.reshape(-1, 4)
    f = lambda lab, cp: _target_one(
        anchors, lab, cp, overlap_threshold, ignore_label,
        negative_mining_ratio, negative_mining_thresh,
        minimum_negative_samples, variances)
    loc_t, loc_m, cls_t = jax.vmap(f)(label, cls_pred)
    return loc_t, loc_m, cls_t


# ---------------------------------------------------------------------------
# MultiBoxDetection
# ---------------------------------------------------------------------------
def _decode_boxes(anchors, loc_pred, variances, clip):
    """TransformLocations (multibox_detection.cc:44-70). anchors (A,4),
    loc_pred (A,4) -> corner boxes (A,4)."""
    vx, vy, vw, vh = variances
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    ox = loc_pred[:, 0] * vx * aw + ax
    oy = loc_pred[:, 1] * vy * ah + ay
    ow = jnp.exp(loc_pred[:, 2] * vw) * aw * 0.5
    oh = jnp.exp(loc_pred[:, 3] * vh) * ah * 0.5
    boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _detect_one(cls_prob, loc_pred, anchors, threshold, clip, variances,
                nms_threshold, force_suppress, nms_topk, background_id,
                impl="auto"):
    """One batch element. cls_prob (C,A), loc_pred (A*4,), anchors (A,4)
    -> (A, 6) rows [class_id, score, x1, y1, x2, y2], invalid rows -1.
    Output ids renumber foreground classes with background_id skipped
    (the reference CPU kernel, multibox_detection.cc:107, hardcodes
    background to class 0; honouring background_id generalizes that)."""
    C, A = cls_prob.shape
    fg = jnp.arange(C) != background_id
    masked = jnp.where(fg[:, None], cls_prob, -jnp.inf)
    scores = jnp.max(masked, axis=0)                     # best non-bg
    ids = jnp.argmax(masked, axis=0)
    out_ids = jnp.where(ids > background_id, ids - 1, ids)
    valid = scores >= threshold

    boxes = _decode_boxes(anchors, loc_pred.reshape(A, 4), variances,
                          clip)
    # sort: valid-by-score first (stable, score descending)
    key = jnp.where(valid, scores, -1.0)
    order = jnp.argsort(-key)
    s_valid = valid[order]
    s_rows = jnp.concatenate(
        [out_ids[order].astype(cls_prob.dtype)[:, None],
         scores[order][:, None], boxes[order]], axis=1)
    s_rows = jnp.where(s_valid[:, None], s_rows, -1.0)

    if nms_topk > 0:
        s_valid = s_valid & (jnp.arange(A) < nms_topk)
        s_rows = jnp.where(s_valid[:, None], s_rows, -1.0)

    if not (0 < nms_threshold <= 1):
        return s_rows

    if impl == "auto":
        # resolved at trace time: the Pallas kernel on TPU, the dense
        # XLA path elsewhere (interpret-mode Pallas is a debug mode, not
        # a deployment path). NOTE: the jit cache key is the literal
        # "auto", so changing MXNET_NMS_IMPL after the first call with
        # identical shapes/attrs has no effect — pass impl= explicitly
        # to switch within a process.
        from .. import config as _config
        impl = _config.get("MXNET_NMS_IMPL") or \
            ("pallas" if jax.default_backend() == "tpu" else "xla")
    if impl == "pallas":
        # blocked Pallas kernel: one (block, A) IoU tile in VMEM instead
        # of the dense (A, A) matrix in HBM (ops/nms_pallas.py)
        from .nms_pallas import nms_keep
        keep = nms_keep(s_rows[:, 2:6], s_rows[:, 0], s_valid,
                        nms_threshold, force_suppress)
    else:
        iou = _box_iou_corner(s_rows[:, 2:6], s_rows[:, 2:6])   # (A, A)
        same_cls = s_rows[:, 0][:, None] == s_rows[:, 0][None, :]
        sup_candidate = iou >= nms_threshold
        if not force_suppress:
            sup_candidate = sup_candidate & same_cls

        def nms_step(i, keep_):
            row_alive = keep_[i] & s_valid[i]
            sup = sup_candidate[i] & (jnp.arange(A) > i) & row_alive
            return keep_ & ~sup

        keep = lax.fori_loop(0, A, nms_step, s_valid)
    return jnp.where((keep & s_valid)[:, None], s_rows, -1.0)


@register("_contrib_MultiBoxDetection",
          arg_names=("cls_prob", "loc_pred", "anchor"),
          differentiable=False,
          aliases=("MultiBoxDetection", "_contrib_multibox_detection"),
          defaults={"clip": True, "threshold": 0.01, "background_id": 0,
                    "nms_threshold": 0.5, "force_suppress": False,
                    "variances": (0.1, 0.1, 0.2, 0.2), "nms_topk": -1,
                    "impl": "auto"})
def _multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                        threshold=0.01, background_id=0,
                        nms_threshold=0.5, force_suppress=False,
                        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1,
                        impl="auto", **_):
    """cls_prob (B,C,A), loc_pred (B,A*4), anchor (1,A,4) -> (B,A,6).

    impl: "pallas" (blocked NMS kernel, ops/nms_pallas.py), "xla"
    (dense IoU matrix + fori_loop), or "auto" (default: MXNET_NMS_IMPL
    env if set, else pallas on TPU / xla elsewhere). Explicit impl
    values get distinct jit cache entries, so both paths can coexist
    in one process; "auto" resolves once per shape at trace time."""
    anchors = anchor.reshape(-1, 4)
    f = lambda cp, lp: _detect_one(cp, lp, anchors, threshold, clip,
                                   variances, nms_threshold,
                                   force_suppress, nms_topk,
                                   background_id, impl)
    return jax.vmap(f)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# ROIPooling
# ---------------------------------------------------------------------------
@register("ROIPooling", arg_names=("data", "rois"), nondiff_inputs=(1,),
          aliases=("_contrib_ROIPooling",),
          defaults={"pooled_size": (1, 1), "spatial_scale": 1.0})
def _roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0, **_):
    """data (B,C,H,W), rois (R,5) [batch_idx, x1, y1, x2, y2] in image
    coords -> (R, C, ph, pw) max-pooled. Reference roi_pooling.cc:40-110
    (round-to-int bin edges, empty bins produce 0)."""
    B, C, H, W = data.shape
    ph, pw = pooled_size

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1.0, 1.0)
        rh = jnp.maximum(y2 - y1 + 1.0, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = data[bidx]                                   # (C,H,W)

        hs = jnp.arange(H, dtype=_F)[None, :]              # (1,H)
        wsx = jnp.arange(W, dtype=_F)[None, :]             # (1,W)
        py = jnp.arange(ph, dtype=_F)[:, None]             # (ph,1)
        px = jnp.arange(pw, dtype=_F)[:, None]             # (pw,1)
        hstart = jnp.clip(jnp.floor(py * bin_h) + y1, 0, H)
        hend = jnp.clip(jnp.ceil((py + 1) * bin_h) + y1, 0, H)
        wstart = jnp.clip(jnp.floor(px * bin_w) + x1, 0, W)
        wend = jnp.clip(jnp.ceil((px + 1) * bin_w) + x1, 0, W)
        hmask = (hs >= hstart) & (hs < hend)               # (ph,H)
        wmask = (wsx >= wstart) & (wsx < wend)             # (pw,W)
        mask = hmask[:, None, :, None] & wmask[None, :, None, :]
        vals = jnp.where(mask[None], img[:, None, None, :, :], -jnp.inf)
        pooled = vals.max((3, 4))                          # (C,ph,pw)
        return jnp.where(jnp.isfinite(pooled), pooled, 0.0)

    return jax.vmap(one_roi)(rois)
