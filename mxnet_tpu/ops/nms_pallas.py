"""Blocked greedy NMS as a Pallas TPU kernel.

The XLA path in detection_ops._detect_one materializes the full A x A IoU
matrix before the greedy suppression loop — for SSD's 8732 anchors that is
~300 MB of HBM traffic per sample. This kernel runs the same greedy
algorithm (reference semantics: multibox_detection.cc:107 NMS loop) in
score-sorted block order and only ever holds one (block x A) IoU tile in
VMEM:

  for each block b (sequential Pallas grid):
    1. intra-block: greedy suppression inside the block (fori_loop over
       the block's rows, vectorized across lanes)
    2. inter-block: one (block x A) IoU tile suppresses every later row
       against the block's survivors in a single vector op

Greedy order is preserved because grid steps run sequentially on TPU and
the keep mask is carried across steps via input/output aliasing. On
non-TPU backends the kernel runs in Pallas interpret mode, so numerics
are identical everywhere.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_BLOCK = 128


def _iou_tile(a, b):
    """IoU of corner boxes a (Na,4) vs b (Nb,4) -> (Na,Nb).

    Same formula as detection_ops._box_iou_corner, restated with plain
    indexing: Mosaic rejects jnp.split on the 4-wide minor dimension, so
    the shared helper cannot be reused inside the kernel (a unit test
    pins the two implementations equal)."""
    ax1, ay1, ax2, ay2 = [a[:, i][:, None] for i in range(4)]
    bx1, by1, bx2, by2 = [b[:, i][None, :] for i in range(4)]
    iw = jnp.maximum(0.0, jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1))
    ih = jnp.maximum(0.0, jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1))
    inter = iw * ih
    union = (ax2 - ax1) * (ay2 - ay1) + (bx2 - bx1) * (by2 - by1) - inter
    return jnp.where(union <= 0, 0.0, inter / jnp.maximum(union, 1e-12))


def _nms_kernel(boxes_ref, cls_ref, keep_in_ref, keep_ref, *,
                block, nms_threshold, force_suppress, num_rows):
    bi = pl.program_id(0)
    offs = bi * block

    @pl.when(bi == 0)
    def _seed():
        keep_ref[...] = keep_in_ref[...]

    # All masks live as 0/1 float32: Mosaic cannot vector-truncate wider
    # ints to i1, so boolean-valued selects/reductions are avoided.
    blk_boxes = boxes_ref[pl.ds(offs, block), :]          # (B, 4)
    blk_cls = cls_ref[0, pl.ds(offs, block)]              # (B,)
    blk_keep = keep_ref[0, pl.ds(offs, block)]            # (B,) 0/1 f32

    iou_bb = _iou_tile(blk_boxes, blk_boxes)              # (B, B)
    sup_bb = (iou_bb >= nms_threshold).astype(jnp.float32)
    if not force_suppress:
        sup_bb = sup_bb * (blk_cls[:, None] ==
                           blk_cls[None, :]).astype(jnp.float32)
    col = jax.lax.broadcasted_iota(jnp.int32, (block,), 0)

    def intra(i, k):
        alive = jnp.max(jnp.where(col == i, k, 0.0))
        row = jnp.max(jnp.where(col[:, None] == i, sup_bb, 0.0), axis=0)
        kill = alive * row * (col > i).astype(jnp.float32)
        return k * (1.0 - kill)

    blk_keep = lax.fori_loop(0, block, intra, blk_keep)
    keep_ref[0, pl.ds(offs, block)] = blk_keep

    # survivors of this block suppress every later row in one tile
    all_boxes = boxes_ref[...]                            # (A, 4)
    iou_ba = _iou_tile(blk_boxes, all_boxes)              # (B, A)
    sup_ba = (iou_ba >= nms_threshold).astype(jnp.float32)
    if not force_suppress:
        sup_ba = sup_ba * (blk_cls[:, None] ==
                           cls_ref[0, :][None, :]).astype(jnp.float32)
    hit = jnp.max(blk_keep[:, None] * sup_ba, axis=0)     # (A,) 0/1
    later = (jax.lax.broadcasted_iota(jnp.int32, (num_rows,), 0) >=
             offs + block).astype(jnp.float32)
    keep_ref[0, :] = keep_ref[0, :] * (1.0 - later * hit)


@functools.partial(jax.jit,
                   static_argnames=("nms_threshold", "force_suppress"))
def nms_keep(boxes, cls_ids, valid, nms_threshold, force_suppress=False):
    """Greedy NMS over score-sorted corner boxes.

    boxes (A,4), cls_ids (A,) float class labels, valid (A,) bool.
    Returns the surviving-row bool mask — bit-identical to the dense
    XLA path in detection_ops (tested in tests/test_detection_ops.py).
    """
    A = boxes.shape[0]
    pad = (-A) % _BLOCK
    padded = A + pad
    boxes_p = jnp.pad(boxes.astype(jnp.float32), ((0, pad), (0, 0)),
                      constant_values=-1.0)
    cls_p = jnp.pad(cls_ids.astype(jnp.float32), (0, pad),
                    constant_values=-1.0)[None, :]
    keep0 = jnp.pad(valid.astype(jnp.float32), (0, pad))[None, :]

    kernel = functools.partial(
        _nms_kernel, block=_BLOCK, nms_threshold=nms_threshold,
        force_suppress=force_suppress, num_rows=padded)
    out = pl.pallas_call(
        kernel,
        grid=(padded // _BLOCK,),
        in_specs=[
            pl.BlockSpec((padded, 4), lambda b: (0, 0)),
            pl.BlockSpec((1, padded), lambda b: (0, 0)),
            pl.BlockSpec((1, padded), lambda b: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, padded), lambda b: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, padded), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(boxes_p, cls_p, keep0)
    return out[0, :A] > 0.0
