"""Gated linear-attention / SSM block — both faces of the duality.

The state-space-duality view (PAPERS.md: "Compiler-First State Space
Duality and Portable O(1) Autoregressive Caching", arXiv 2603.09555)
gives one recurrence two execution forms:

    S_t = a_t * S_{t-1} + k_t (x) v_t        (per-head matrix state)
    o_t = q_t . S_t                          (read AFTER the update)

with a data-dependent scalar decay a_t = sigmoid(g_t + gate_bias) in
(0, 1) per head per token.  Training and prefill run the CHUNKED-SCAN
form: the sequence is cut into fixed-width chunks, each chunk combines
an inter-chunk term (carried state, decayed per position) with an
intra-chunk masked-decay attention matrix — parallel over the chunk
on the MXU — and a `jax.lax.scan` threads the (B, H, hd, hd) state
across chunks under the ordinary jit path so XLA fuses it ("Operator
Fusion in XLA", arXiv 2301.13062 for the scan-fusion cost model).
Decode runs the FUSED RECURRENT form: one token in, one rank-1 state
update, one state read — O(1) compute and O(1) memory per step,
independent of how long the sequence has run.  That constant
(B, H, hd, hd) blob is the whole serving prize: a decode slot costs
the same HBM at position 10 and position 100k (vs the (max_len, hd)
KV rows of _contrib_CachedAttention).

BIT-IDENTICAL STATE RULE (the quantization-rule analogue of
attention.py's `_q8_quantize`): every path derives the decay through
`_log_decay` and exponentiates the LOG decay — the fused step uses
a_t = exp(log_sigmoid(g_t + gate_bias)), never sigmoid() directly —
and both forms update state with the same einsum contractions.  A
width-1 chunk's exit state is therefore BITWISE equal to the fused
step's state for the same inputs, which is what lets serving hand a
blob from the chunked prefill form to the recurrent decode form (and
between replicas on migration) with no drift, ever.  (The guarantee
is under jit — the serving condition; op-by-op eager dispatch skips
XLA's fused multiply-adds and can differ from the scan in the last
ulp, which tests/test_ssm.py pins.)

Positions: the recurrence carries its own notion of position (state
already encodes everything before it), so the cached op accepts and
IGNORES `pos`.  A slot pool at ragged decode depths needs no per-row
offsets — the per-row-position "twin" of this op is the op itself.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register


def _log_decay(gate, gate_bias):
    """log a_t = log_sigmoid(g_t + gate_bias), float32.

    THE shared decay rule (see module docstring): both the chunked-scan
    and fused recurrent forms must derive their decay from this exact
    expression and exponentiate it — `exp(log_sigmoid(x))` is NOT
    bitwise `sigmoid(x)`, so a path that called sigmoid directly would
    break the bit-identical-state contract.  gate_bias shifts the init
    toward remembering (bias 4.0 => a ~= 0.982 for zero-init gates);
    log_sigmoid <= 0 keeps every downstream exp() in (0, 1] — no
    overflow anywhere in either form."""
    return jax.nn.log_sigmoid(gate.astype(jnp.float32) + gate_bias)


def _check_ssm_shapes(query, key, value, gate, state=None):
    B, H, T, D = query.shape
    if key.shape != query.shape or value.shape != query.shape:
        raise ValueError(
            "SSM q/k/v must share one (B, H, T, hd) shape: got q=%r "
            "k=%r v=%r" % (query.shape, key.shape, value.shape))
    if gate.shape != (B, H, T):
        raise ValueError(
            "SSM gate must be (B, H, T) per-head per-token decay "
            "logits: got %r for q=%r" % (gate.shape, query.shape))
    if state is not None and state.shape != (B, H, D, D):
        raise ValueError(
            "SSM state must be (B, H, hd, hd) = %r: got %r"
            % ((B, H, D, D), state.shape))


def ssm_chunk_scan(query, key, value, gate, state=None, chunk=64,
                   gate_bias=4.0, scale=None):
    """Chunked-scan (training / prefill) form.

    query/key/value: (B, H, T, hd); gate: (B, H, T) decay logits;
    state: (B, H, hd, hd) f32 carried state or None for zeros.
    Returns (out (B, H, T, hd) in query dtype, new_state f32).

    The sequence is padded to a multiple of the chunk width with
    la=0 (decay 1), k=0, v=0 — exact: padding multiplies the carried
    state by exp(0) and adds a zero outer product, so the exit state
    and the real rows' outputs are untouched.  Within a chunk, row t
    reads the carried state decayed by exp(L_t) plus an intra-chunk
    masked score matrix (q_t.k_s) * exp(L_t - L_s) for s <= t, where
    L is the inclusive cumsum of log decays; the inner where() guard
    zeroes the log-decay BEFORE the exp so masked s > t entries (where
    L_t - L_s can be large and positive) never produce inf * 0."""
    B, H, T, D = query.shape
    _check_ssm_shapes(query, key, value, gate, state)
    if scale is None:
        scale = D ** -0.5
    if state is None:
        state = jnp.zeros((B, H, D, D), jnp.float32)
    state = state.astype(jnp.float32)
    qf = query.astype(jnp.float32) * scale
    kf = key.astype(jnp.float32)
    vf = value.astype(jnp.float32)
    la = _log_decay(gate, gate_bias)                    # (B, H, T)

    W = max(1, min(int(chunk), T))
    nc = -(-T // W)
    pad = nc * W - T
    if pad:
        qf = jnp.pad(qf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        la = jnp.pad(la, ((0, 0), (0, 0), (0, pad)))

    # (nc, B, H, W, .) — scan walks the chunk axis
    qc = jnp.moveaxis(qf.reshape(B, H, nc, W, D), 2, 0)
    kc = jnp.moveaxis(kf.reshape(B, H, nc, W, D), 2, 0)
    vc = jnp.moveaxis(vf.reshape(B, H, nc, W, D), 2, 0)
    lac = jnp.moveaxis(la.reshape(B, H, nc, W), 2, 0)
    mask = jnp.tril(jnp.ones((W, W), bool))             # s <= t

    def _chunk(S, inp):
        q_c, k_c, v_c, la_c = inp
        L = jnp.cumsum(la_c, axis=-1)                   # (B, H, W)
        inter = jnp.exp(L)[..., None] * jnp.einsum(
            "bhtd,bhde->bhte", q_c, S,
            precision=jax.lax.Precision.DEFAULT)
        s_qk = jnp.einsum(
            "bhtd,bhsd->bhts", q_c, k_c,
            precision=jax.lax.Precision.DEFAULT)        # (B, H, W, W)
        decay = L[..., :, None] - L[..., None, :]       # L_t - L_s
        scores = jnp.where(
            mask, s_qk * jnp.exp(jnp.where(mask, decay, 0.0)), 0.0)
        o_c = inter + jnp.einsum(
            "bhts,bhse->bhte", scores, v_c,
            precision=jax.lax.Precision.DEFAULT)
        Llast = L[..., -1]                              # (B, H)
        kd = k_c * jnp.exp(Llast[..., None] - L)[..., None]
        S = jnp.exp(Llast)[..., None, None] * S + jnp.einsum(
            "bhsd,bhse->bhde", kd, v_c,
            precision=jax.lax.Precision.DEFAULT)
        return S, o_c

    state, outs = jax.lax.scan(_chunk, state, (qc, kc, vc, lac))
    out = jnp.moveaxis(outs, 0, 2).reshape(B, H, nc * W, D)[:, :, :T]
    return out.astype(query.dtype), state


def ssm_recurrent_step(query, key, value, gate, state, gate_bias=4.0,
                       scale=None):
    """Fused recurrent (decode) form — Tnew == 1.

    One rank-1 state update and one state read; O(1) in sequence
    length.  Deliberately mirrors `ssm_chunk_scan`'s width-1 chunk
    expression for expression (same `_log_decay`, the exp of the log,
    the same einsum contractions), so its output AND exit state are
    BITWISE what a 1-wide chunk produces — the handoff contract the
    serving stack's export/import and prefill->decode transition rely
    on."""
    B, H, Tn, D = query.shape
    if Tn != 1:
        raise ValueError(
            "ssm_recurrent_step is the single-token fused form (got "
            "Tnew=%d); use ssm_chunk_scan for multi-token prefill"
            % Tn)
    _check_ssm_shapes(query, key, value, gate, state)
    if scale is None:
        scale = D ** -0.5
    state = state.astype(jnp.float32)
    qf = query.astype(jnp.float32) * scale
    kf = key.astype(jnp.float32)
    vf = value.astype(jnp.float32)
    a = jnp.exp(_log_decay(gate, gate_bias))            # (B, H, 1)
    inter = a[..., None] * jnp.einsum(
        "bhtd,bhde->bhte", qf, state,
        precision=jax.lax.Precision.DEFAULT)
    s_qk = jnp.einsum(
        "bhtd,bhsd->bhts", qf, kf,
        precision=jax.lax.Precision.DEFAULT)            # (B, H, 1, 1)
    out = inter + jnp.einsum(
        "bhts,bhse->bhte", s_qk, vf,
        precision=jax.lax.Precision.DEFAULT)
    state = a[..., None] * state + jnp.einsum(
        "bhsd,bhse->bhde", kf, vf,
        precision=jax.lax.Precision.DEFAULT)
    return out.astype(query.dtype), state


@register("_contrib_SSMScan",
          arg_names=("query", "key", "value", "gate"),
          defaults={"scale": None, "gate_bias": 4.0, "chunk": 64})
def _ssm_scan_op(query, key, value, gate, scale=None, gate_bias=4.0,
                 chunk=64, **_):
    """(B, H, T, hd) gated linear-attention over a zero-initialized
    state — the TRAINING form.  Fully differentiable (autodiff
    through the chunk scan); `chunk` trades intra-chunk MXU work
    against scan length and does not change the math."""
    out, _state = ssm_chunk_scan(query, key, value, gate, state=None,
                                 chunk=int(chunk),
                                 gate_bias=float(gate_bias),
                                 scale=scale)
    return out


@register("_contrib_SSMCached",
          arg_names=("query", "key", "value", "gate", "state", "pos"),
          state_inputs=(4,), nondiff_inputs=(5,),
          differentiable=False,
          defaults={"scale": None, "gate_bias": 4.0, "chunk": 64,
                    "max_len": 0})
def _ssm_cached_op(query, key, value, gate, state, pos, scale=None,
                   gate_bias=4.0, chunk=64, **_):
    """Incremental-decode SSM over a carried (B, H, hd, hd) f32 state
    aux (threaded in place by the executor like a KV cache, but with
    NO length axis — the O(1) decode-slot blob).

    Dispatch is STATIC on Tnew = query.shape[2]: prefill (Tnew > 1)
    runs the chunked scan continuing from the carried state; decode
    (Tnew == 1) runs the fused recurrent step.  Both write state under
    the bit-identical rule, so the prefill->decode transition (and any
    export/import of the blob between replicas) is drift-free.

    `pos` is accepted and IGNORED — the recurrence carries its own
    position, so shared-position and per-row-position callers get the
    same graph (there is no capacity contract either: the state never
    fills up; `max_len` is accepted only for attr-parity with the
    cached-attention ops).  Returns (out, new_state)."""
    del pos
    if query.shape[2] == 1:
        return ssm_recurrent_step(query, key, value, gate, state,
                                  gate_bias=float(gate_bias),
                                  scale=scale)
    return ssm_chunk_scan(query, key, value, gate, state=state,
                          chunk=int(chunk),
                          gate_bias=float(gate_bias), scale=scale)
