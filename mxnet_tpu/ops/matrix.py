"""Shape-manipulation + matrix ops.

Reference: src/operator/tensor/matrix_op.* (SURVEY.md N11): reshape,
transpose, slice, clip, repeat, tile, flip, dot, concat, stack, split, pad,
swapaxes, expand_dims, where, cast_storage.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


@register("reshape", arg_names=("data",), aliases=("Reshape",),
          defaults={"shape": (), "reverse": False})
def _reshape(x, shape=(), reverse=False, **_):
    shape = tuple(shape)
    if not shape:
        return x
    # MXNet special codes: 0 copy dim, -1 infer, -2 copy rest, -3 merge two,
    # -4 split (src/operator/tensor/matrix_op-inl.h InferReshapeShape)
    src = list(x.shape[::-1]) if reverse else list(x.shape)
    out = []
    i = 0
    it = iter(range(len(shape)))
    shp = list(shape[::-1]) if reverse else list(shape)
    k = 0
    while k < len(shp):
        s = shp[k]
        if s == 0:
            out.append(src[i]); i += 1
        elif s == -1:
            out.append(-1); i += 1
        elif s == -2:
            out.extend(src[i:]); i = len(src)
        elif s == -3:
            out.append(src[i] * src[i + 1]); i += 2
        elif s == -4:
            a, b = shp[k + 1], shp[k + 2]
            if a == -1:
                a = src[i] // b
            if b == -1:
                b = src[i] // a
            out.extend([a, b]); i += 1; k += 2
        else:
            out.append(s)
            if i < len(src):
                i += 1
        k += 1
    if reverse:
        out = out[::-1]
    return x.reshape(tuple(out))


@register("Flatten", arg_names=("data",), aliases=("flatten",))
def _flatten(x, **_):
    return x.reshape(x.shape[0], -1)


@register("transpose", arg_names=("data",), defaults={"axes": ()})
def _transpose(x, axes=(), **_):
    return jnp.transpose(x, tuple(axes) if axes else None)


@register("SwapAxis", arg_names=("data",), aliases=("swapaxes",),
          defaults={"dim1": 0, "dim2": 0})
def _swapaxes(x, dim1=0, dim2=0, **_):
    return jnp.swapaxes(x, dim1, dim2)


@register("expand_dims", arg_names=("data",), defaults={"axis": 0})
def _expand_dims(x, axis=0, **_):
    return jnp.expand_dims(x, axis)


@register("squeeze", arg_names=("data",), defaults={"axis": None})
def _squeeze(x, axis=None, **_):
    return jnp.squeeze(x, axis=axis)


@register("slice", arg_names=("data",), aliases=("crop",),
          defaults={"begin": (), "end": (), "step": None})
def _slice(x, begin=(), end=(), step=None, **_):
    begin = (begin,) if isinstance(begin, int) else tuple(begin)
    end = (end,) if isinstance(end, int) else tuple(end)
    step = tuple(step) if step else (None,) * len(begin)
    idx = []
    for b, e, s in zip(begin, end, step):
        idx.append(slice(b, e, s))
    return x[tuple(idx)]


@register("slice_axis", arg_names=("data",),
          defaults={"axis": 0, "begin": 0, "end": None})
def _slice_axis(x, axis=0, begin=0, end=None, **_):
    idx = [slice(None)] * x.ndim
    idx[axis] = slice(begin, end)
    return x[tuple(idx)]


@register("slice_like", arg_names=("data", "shape_like"), nondiff_inputs=(1,),
          defaults={"axes": ()})
def _slice_like(x, ref, axes=(), **_):
    axes = tuple(axes) if axes else tuple(range(min(x.ndim, ref.ndim)))
    idx = [slice(None)] * x.ndim
    for a in axes:
        idx[a] = slice(0, ref.shape[a])
    return x[tuple(idx)]


@register("_index", arg_names=("data",), defaults={"index": ()})
def _index_op(x, index=(), **_):
    from ..ndarray.ndarray import _unwrap_index
    return x[_unwrap_index(index)]


@register("_slice_assign", arg_names=("lhs", "rhs"),
          defaults={"begin": (), "end": (), "step": None})
def _slice_assign(lhs, rhs, begin=(), end=(), step=None, **_):
    idx = tuple(slice(b, e) for b, e in zip(begin, end))
    return lhs.at[idx].set(rhs)


@register("_crop_assign_scalar", arg_names=("data",),
          defaults={"begin": (), "end": (), "scalar": 0.0})
def _crop_assign_scalar(x, begin=(), end=(), scalar=0.0, **_):
    idx = tuple(slice(b, e) for b, e in zip(begin, end))
    return x.at[idx].set(scalar)


@register("repeat", arg_names=("data",),
          defaults={"repeats": 1, "axis": None})
def _repeat(x, repeats=1, axis=None, **_):
    if axis is None:
        return jnp.repeat(x.reshape(-1), repeats)
    return jnp.repeat(x, repeats, axis=axis)


@register("tile", arg_names=("data",), defaults={"reps": ()})
def _tile(x, reps=(), **_):
    return jnp.tile(x, tuple(reps))


@register("reverse", arg_names=("data",), aliases=("flip",),
          defaults={"axis": ()})
def _reverse(x, axis=(), **_):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    return jnp.flip(x, axis=axis)


@register("stack", arg_names=None, defaults={"axis": 0, "num_args": 0})
def _stack(*args, axis=0, **_):
    return jnp.stack(args, axis=axis)


@register("Concat", arg_names=None, aliases=("concat",),
          defaults={"dim": 1, "num_args": 0})
def _concat(*args, dim=1, **_):
    return jnp.concatenate(args, axis=dim)


@register("SliceChannel", arg_names=("data",), aliases=("split",),
          defaults={"num_outputs": 1, "axis": 1, "squeeze_axis": False})
def _slice_channel(x, num_outputs=1, axis=1, squeeze_axis=False, **_):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


@register("where", arg_names=("condition", "x", "y"), nondiff_inputs=(0,))
def _where(cond, x, y, **_):
    if cond.shape != x.shape and cond.ndim == 1:
        cond = cond.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(cond != 0, x, y)


@register("Pad", arg_names=("data",), aliases=("pad",),
          defaults={"mode": "constant", "pad_width": (), "constant_value": 0.0})
def _pad(x, mode="constant", pad_width=(), constant_value=0.0, **_):
    pw = tuple(pad_width)
    pairs = tuple((pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2))
    if mode == "constant":
        return jnp.pad(x, pairs, constant_values=constant_value)
    if mode == "edge":
        return jnp.pad(x, pairs, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pairs, mode="reflect")
    raise ValueError("unknown pad mode %r" % mode)


@register("dot", arg_names=("lhs", "rhs"),
          defaults={"transpose_a": False, "transpose_b": False})
def _dot(lhs, rhs, transpose_a=False, transpose_b=False, **_):
    if transpose_a:
        lhs = lhs.T if lhs.ndim == 2 else jnp.moveaxis(lhs, 0, -1)
    if transpose_b:
        rhs = rhs.T if rhs.ndim == 2 else jnp.moveaxis(rhs, -1, 0)
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs).reshape((1,))
    return jnp.tensordot(lhs, rhs, axes=([lhs.ndim - 1], [0]))


@register("batch_dot", arg_names=("lhs", "rhs"),
          defaults={"transpose_a": False, "transpose_b": False})
def _batch_dot(lhs, rhs, transpose_a=False, transpose_b=False, **_):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


@register("cast_storage", arg_names=("data",), defaults={"stype": "default"})
def _cast_storage(x, stype="default", **_):
    # dense compute path: storage casting is a metadata-level operation
    # handled by ndarray.sparse; within jit everything is dense.
    return x


# -- ordering ---------------------------------------------------------------

@register("topk", arg_names=("data",), differentiable=False,
          defaults={"axis": -1, "k": 1, "ret_typ": "indices",
                    "is_ascend": False})
def _topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, **_):
    axis = axis % x.ndim if axis is not None else x.ndim - 1
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = lax.top_k(-xm if is_ascend else xm, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis).astype(jnp.float32)
    if ret_typ == "indices":
        return idx
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return (vals, idx)
    if ret_typ == "mask":
        onehot = jnp.sum(jnp.eye(xm.shape[-1], dtype=x.dtype)[
            jnp.moveaxis(idx, axis, -1).astype(jnp.int32)], axis=-2)
        return jnp.moveaxis(onehot, -1, axis).reshape(x.shape)
    raise ValueError("unknown ret_typ %r" % ret_typ)


@register("sort", arg_names=("data",), differentiable=False,
          defaults={"axis": -1, "is_ascend": True})
def _sort(x, axis=-1, is_ascend=True, **_):
    out = jnp.sort(x, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register("argsort", arg_names=("data",), differentiable=False,
          defaults={"axis": -1, "is_ascend": True})
def _argsort(x, axis=-1, is_ascend=True, **_):
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(jnp.float32)
