"""RCNN / R-FCN operator family: Proposal, MultiProposal, PSROIPooling,
DeformableConvolution, DeformablePSROIPooling.

Reference: src/operator/contrib/{proposal,multi_proposal,psroi_pooling,
deformable_convolution,deformable_psroi_pooling}-inl.h. All kernels are
reformulated static-shape:

- Proposal keeps the reference's anchor arithmetic (proposal-inl.h
  _Transform/_MakeAnchor, BBoxTransformInv in proposal.cc:40-90) but
  emits a FIXED rpn_post_nms_top_n rois per image (greedy NMS as a
  fori_loop over the sorted candidate set, padding by the best box) —
  XLA-compatible where the reference reallocates per image.
- PSROIPooling samples each bin on a sub-grid with bilinear taps (the
  deformable_psroi formulation with zero offsets), keeping every shape
  static; DeformablePSROIPooling adds the learned per-part offsets.
- DeformableConvolution gathers one bilinear-sampled image per kernel
  tap (deformable_im2col semantics) and contracts with the weights in
  one einsum — the MXU does the heavy product.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


# ---------------------------------------------------------------------------
# anchors (host-side, static attrs only)
# ---------------------------------------------------------------------------

def _base_anchors(feature_stride, scales, ratios):
    """(A, 4) corner anchors at cell (0, 0) — proposal-inl.h:213."""
    base = np.array([0, 0, feature_stride - 1.0, feature_stride - 1.0])
    w = base[2] - base[0] + 1.0
    h = base[3] - base[1] + 1.0
    x_ctr = base[0] + 0.5 * (w - 1.0)
    y_ctr = base[1] + 0.5 * (h - 1.0)
    size = w * h
    out = []
    for ratio in ratios:
        size_ratio = np.floor(size / ratio)
        new_w = np.floor(np.sqrt(size_ratio) + 0.5)
        new_h = np.floor(new_w * ratio + 0.5)
        for scale in scales:
            ws, hs = new_w * scale, new_h * scale
            out.append([x_ctr - 0.5 * (ws - 1), y_ctr - 0.5 * (hs - 1),
                        x_ctr + 0.5 * (ws - 1), y_ctr + 0.5 * (hs - 1)])
    return np.asarray(out, np.float32)


def _shifted_anchors(H, W, feature_stride, scales, ratios):
    """(H*W*A, 4) anchors in the reference's h-major, w, a order."""
    base = _base_anchors(feature_stride, scales, ratios)      # (A, 4)
    sx = np.arange(W) * feature_stride
    sy = np.arange(H) * feature_stride
    shift = np.stack(np.meshgrid(sy, sx, indexing="ij"), -1)  # (H, W, 2)
    shift4 = np.concatenate([shift[..., 1:2], shift[..., 0:1]] * 2, -1)
    all_anchors = shift4[:, :, None, :] + base[None, None, :, :]
    return all_anchors.reshape(-1, 4).astype(np.float32)


def _decode_rpn(anchors, deltas, im_h, im_w):
    """BBoxTransformInv (proposal.cc:40-90): deltas (N, 4) on corner
    anchors (N, 4), clipped to the image."""
    widths = anchors[:, 2] - anchors[:, 0] + 1.0
    heights = anchors[:, 3] - anchors[:, 1] + 1.0
    ctr_x = anchors[:, 0] + 0.5 * (widths - 1.0)
    ctr_y = anchors[:, 1] + 0.5 * (heights - 1.0)
    pred_ctr_x = deltas[:, 0] * widths + ctr_x
    pred_ctr_y = deltas[:, 1] * heights + ctr_y
    pred_w = jnp.exp(deltas[:, 2]) * widths
    pred_h = jnp.exp(deltas[:, 3]) * heights
    x1 = jnp.clip(pred_ctr_x - 0.5 * (pred_w - 1.0), 0, im_w - 1.0)
    y1 = jnp.clip(pred_ctr_y - 0.5 * (pred_h - 1.0), 0, im_h - 1.0)
    x2 = jnp.clip(pred_ctr_x + 0.5 * (pred_w - 1.0), 0, im_w - 1.0)
    y2 = jnp.clip(pred_ctr_y + 0.5 * (pred_h - 1.0), 0, im_h - 1.0)
    return jnp.stack([x1, y1, x2, y2], axis=1)


def _greedy_nms_keep(boxes, order_valid, threshold):
    """keep mask over score-sorted boxes (same loop as
    detection_ops._detect_one)."""
    n = boxes.shape[0]
    x1, y1, x2, y2 = (boxes[:, i] for i in range(4))
    area = jnp.maximum(x2 - x1 + 1.0, 0) * jnp.maximum(y2 - y1 + 1.0, 0)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    iw = jnp.maximum(ix2 - ix1 + 1.0, 0)
    ih = jnp.maximum(iy2 - iy1 + 1.0, 0)
    inter = iw * ih
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                              1e-12)
    sup = iou > threshold

    def step(i, keep):
        alive = keep[i] & order_valid[i]
        kill = sup[i] & (jnp.arange(n) > i) & alive
        return keep & ~kill

    return lax.fori_loop(0, n, step, order_valid)


def _proposal_one(scores, deltas, im_info, anchors, pre_n, post_n,
                  threshold, min_size, output_score):
    """One image. scores (N,), deltas (N, 4), anchors (N, 4)."""
    im_h, im_w, im_scale = im_info[0], im_info[1], im_info[2]
    boxes = _decode_rpn(anchors, deltas, im_h, im_w)
    ws = boxes[:, 2] - boxes[:, 0] + 1.0
    hs = boxes[:, 3] - boxes[:, 1] + 1.0
    ms = min_size * im_scale
    valid = (ws >= ms) & (hs >= ms)
    score = jnp.where(valid, scores, -jnp.inf)

    n = score.shape[0]
    k = min(int(pre_n), n)
    top_score, top_idx = lax.top_k(score, k)
    top_boxes = boxes[top_idx]
    keep = _greedy_nms_keep(top_boxes, top_score > -jnp.inf, threshold)

    # stable-select the first post_n kept rows; pad with the best box
    # (also when post_n exceeds the candidate count k)
    sel_key = jnp.where(keep, jnp.arange(k), k + jnp.arange(k))
    order = jnp.argsort(sel_key)[jnp.clip(jnp.arange(post_n), 0, k - 1)]
    n_keep = jnp.minimum(keep.sum(), k)
    pad = jnp.arange(post_n) >= n_keep
    rows = jnp.where(pad[:, None], top_boxes[0][None, :],
                     top_boxes[order])
    row_scores = jnp.where(pad, top_score[0], top_score[order])
    return rows, row_scores


def _multi_proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
                    rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                    scales=(4, 8, 16, 32), ratios=(0.5, 1, 2),
                    feature_stride=16, output_score=False,
                    iou_loss=False, **_):
    B, twoA, H, W = cls_prob.shape
    A = twoA // 2
    anchors = jnp.asarray(_shifted_anchors(H, W, feature_stride,
                                           tuple(scales), tuple(ratios)))
    # reference ordering: index = h*(W*A) + w*A + a
    scores = cls_prob[:, A:, :, :].transpose(0, 2, 3, 1).reshape(B, -1)
    deltas = bbox_pred.reshape(B, A, 4, H, W).transpose(0, 3, 4, 1, 2) \
        .reshape(B, -1, 4)

    def one(s, d, info):
        rows, row_scores = _proposal_one(
            s, d, info, anchors, rpn_pre_nms_top_n, rpn_post_nms_top_n,
            threshold, rpn_min_size, output_score)
        return rows, row_scores

    rows, row_scores = jax.vmap(one)(scores, deltas, im_info)
    batch_idx = jnp.broadcast_to(
        jnp.arange(B, dtype=rows.dtype)[:, None, None],
        (B, rpn_post_nms_top_n, 1))
    rois = jnp.concatenate([batch_idx, rows], axis=2) \
        .reshape(B * rpn_post_nms_top_n, 5)
    if output_score:
        return rois, row_scores.reshape(-1, 1)
    return rois


register("_contrib_MultiProposal",
         arg_names=("cls_prob", "bbox_pred", "im_info"),
         differentiable=False,
         aliases=("MultiProposal", "_contrib_multi_proposal"),
         defaults={"rpn_pre_nms_top_n": 6000, "rpn_post_nms_top_n": 300,
                   "threshold": 0.7, "rpn_min_size": 16,
                   "scales": (4, 8, 16, 32), "ratios": (0.5, 1, 2),
                   "feature_stride": 16, "output_score": False,
                   "iou_loss": False})(_multi_proposal)

register("_contrib_Proposal",
         arg_names=("cls_prob", "bbox_pred", "im_info"),
         differentiable=False,
         aliases=("Proposal", "_contrib_proposal"),
         defaults={"rpn_pre_nms_top_n": 6000, "rpn_post_nms_top_n": 300,
                   "threshold": 0.7, "rpn_min_size": 16,
                   "scales": (4, 8, 16, 32), "ratios": (0.5, 1, 2),
                   "feature_stride": 16, "output_score": False,
                   "iou_loss": False})(_multi_proposal)


# ---------------------------------------------------------------------------
# position-sensitive ROI pooling (R-FCN)
# ---------------------------------------------------------------------------

def _bilinear_tap(img, y, x):
    """img (C, H, W) sampled at scalar grids y, x (...,) — zero padded."""
    C, H, W = img.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    dy = y - y0
    dx = x - x0

    def corner(yc, xc, w):
        inside = (xc >= 0) & (xc <= W - 1) & (yc >= 0) & (yc <= H - 1)
        yi = jnp.clip(yc, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xc, 0, W - 1).astype(jnp.int32)
        return img[:, yi, xi] * (w * inside)[None]

    return (corner(y0, x0, (1 - dy) * (1 - dx)) +
            corner(y0, x0 + 1, (1 - dy) * dx) +
            corner(y0 + 1, x0, dy * (1 - dx)) +
            corner(y0 + 1, x0 + 1, dy * dx))


def _psroi_one(data, roi, trans_row, spatial_scale, output_dim, pooled,
               group_size, sample_per_part, trans_std, part_size):
    """One roi over the whole batch's data (B, C, H, W) — roi[0] picks
    the image; trans_row (2, part, part) holds THIS roi's learned
    offsets (reference indexes bottom_trans by roi ordinal,
    deformable_psroi_pooling-inl.h). Returns (output_dim, pooled,
    pooled)."""
    bidx = roi[0].astype(jnp.int32)
    img = data[bidx]                                    # (C, H, W)
    # deformable_psroi_pooling-inl.h: roi corners scaled with the 0.5
    # offset, clamped min sizes
    x1 = roi[1] * spatial_scale - 0.5
    y1 = roi[2] * spatial_scale - 0.5
    x2 = (roi[3] + 1.0) * spatial_scale - 0.5
    y2 = (roi[4] + 1.0) * spatial_scale - 0.5
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_w = rw / pooled
    bin_h = rh / pooled
    sub_w = bin_w / sample_per_part
    sub_h = bin_h / sample_per_part

    ph = jnp.arange(pooled)
    pw = jnp.arange(pooled)
    gh = jnp.minimum((ph * group_size) // pooled, group_size - 1)
    gw = jnp.minimum((pw * group_size) // pooled, group_size - 1)

    # per-part learned offsets (zero when trans_row is None)
    if trans_row is not None:
        part_h = jnp.minimum((ph * part_size) // pooled, part_size - 1)
        part_w = jnp.minimum((pw * part_size) // pooled, part_size - 1)
        off_y = trans_row[0][part_h[:, None],
                             part_w[None, :]] * trans_std * rh
        off_x = trans_row[1][part_h[:, None],
                             part_w[None, :]] * trans_std * rw
    else:
        off_y = jnp.zeros((pooled, pooled))
        off_x = jnp.zeros((pooled, pooled))

    s = jnp.arange(sample_per_part) + 0.5
    # (pooled, pooled, s, s) sample grids
    yy = (y1 + ph[:, None, None, None] * bin_h +
          s[None, None, :, None] * sub_h + off_y[:, :, None, None])
    xx = (x1 + pw[None, :, None, None] * bin_w +
          s[None, None, None, :] * sub_w + off_x[:, :, None, None])
    yy = jnp.broadcast_to(yy, (pooled, pooled, sample_per_part,
                               sample_per_part))
    xx = jnp.broadcast_to(xx, (pooled, pooled, sample_per_part,
                               sample_per_part))
    sampled = _bilinear_tap(img, yy, xx)   # (C, P, P, s, s)
    avg = sampled.mean(axis=(3, 4))        # (C, P, P)

    # position-sensitive channel select: out[c, i, j] uses input channel
    # c*G*G + gh[i]*G + gw[j]
    C = img.shape[0]
    chan = (jnp.arange(output_dim)[:, None, None] * group_size *
            group_size + gh[None, :, None] * group_size +
            gw[None, None, :])
    ii = jnp.broadcast_to(jnp.arange(pooled)[None, :, None],
                          chan.shape)
    jj = jnp.broadcast_to(jnp.arange(pooled)[None, None, :],
                          chan.shape)
    return avg[chan, ii, jj]


@register("_contrib_PSROIPooling", arg_names=("data", "rois"),
          nondiff_inputs=(1,),
          aliases=("PSROIPooling", "_contrib_psroipooling"),
          defaults={"spatial_scale": 1.0, "output_dim": 0,
                    "pooled_size": 0, "group_size": 0})
def _psroi_pooling(data, rois, spatial_scale=1.0, output_dim=0,
                   pooled_size=0, group_size=0, **_):
    """data (B, output_dim*group², H, W), rois (R, 5) -> (R, output_dim,
    pooled, pooled). psroi_pooling-inl.h via the sampled-bin
    formulation (sample grid 4 per bin axis)."""
    group_size = int(group_size) or int(pooled_size)
    f = lambda roi: _psroi_one(data, roi, None, spatial_scale,
                               int(output_dim), int(pooled_size),
                               group_size, 4, 0.0, group_size)
    return jax.vmap(f)(rois)


@register("_contrib_DeformablePSROIPooling",
          arg_names=("data", "rois", "trans"), nondiff_inputs=(1,),
          aliases=("DeformablePSROIPooling",),
          defaults={"spatial_scale": 1.0, "output_dim": 0,
                    "pooled_size": 0, "group_size": 0, "part_size": 0,
                    "sample_per_part": 4, "trans_std": 0.0,
                    "no_trans": False})
def _deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                              output_dim=0, pooled_size=0, group_size=0,
                              part_size=0, sample_per_part=4,
                              trans_std=0.0, no_trans=False, **_):
    group_size = int(group_size) or int(pooled_size)
    part_size = int(part_size) or int(pooled_size)
    use_trans = trans is not None and not no_trans
    if use_trans:
        # trans (R, 2, part, part): one offset grid per ROI
        f = lambda roi, tr: _psroi_one(
            data, roi, tr, spatial_scale, int(output_dim),
            int(pooled_size), group_size, int(sample_per_part),
            float(trans_std), part_size)
        return jax.vmap(f)(rois, trans.reshape(
            rois.shape[0], -1, part_size, part_size)[:, :2])
    f = lambda roi: _psroi_one(
        data, roi, None, spatial_scale, int(output_dim),
        int(pooled_size), group_size, int(sample_per_part),
        float(trans_std), part_size)
    return jax.vmap(f)(rois)


# ---------------------------------------------------------------------------
# deformable convolution (v1)
# ---------------------------------------------------------------------------

@register("_contrib_DeformableConvolution",
          arg_names=("data", "offset", "weight", "bias"),
          aliases=("DeformableConvolution",),
          defaults={"kernel": (), "stride": (), "dilate": (), "pad": (),
                    "num_filter": 0, "num_group": 1,
                    "num_deformable_group": 1, "no_bias": False,
                    "workspace": 1024})
def _deformable_convolution(data, offset, weight, bias=None, kernel=(),
                            stride=(), dilate=(), pad=(), num_filter=0,
                            num_group=1, num_deformable_group=1,
                            no_bias=False, **_):
    """deformable_im2col semantics (contrib/nn/deformable_im2col.h):
    each kernel tap samples the input at its position + learned offset
    (bilinear); offset channels [dg][2*(ki*kw+kj)] = dy, +1 = dx."""
    B, C, H, W = data.shape
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = (int(stride[0]), int(stride[1])) if stride else (1, 1)
    dh, dw = (int(dilate[0]), int(dilate[1])) if dilate else (1, 1)
    ph, pw = (int(pad[0]), int(pad[1])) if pad else (0, 0)
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    dg = int(num_deformable_group)
    cpg = C // dg

    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw

    def sample_image(img, off):
        """img (C, H, W), off (2*dg*kh*kw, Ho, Wo) ->
        (C, kh*kw, Ho, Wo) sampled taps."""
        taps = []
        for t in range(kh * kw):
            ki, kj = divmod(t, kw)
            per_g = []
            for g in range(dg):
                dy = off[g * 2 * kh * kw + 2 * t]
                dx = off[g * 2 * kh * kw + 2 * t + 1]
                yy = oy[:, None] + ki * dh + dy
                xx = ox[None, :] + kj * dw + dx
                per_g.append(_bilinear_tap(
                    img[g * cpg:(g + 1) * cpg], yy, xx))
            taps.append(jnp.concatenate(per_g, axis=0))
        return jnp.stack(taps, axis=1)      # (C, kh*kw, Ho, Wo)

    patches = jax.vmap(sample_image)(data, offset)  # (B,C,K²,Ho,Wo)
    O = int(num_filter)
    g = int(num_group)
    wg = weight.reshape(g, O // g, C // g, kh * kw)
    pg = patches.reshape(B, g, C // g, kh * kw, Ho, Wo)
    out = jnp.einsum("bgckhw,gock->bgohw", pg, wg)
    out = out.reshape(B, O, Ho, Wo)
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out
