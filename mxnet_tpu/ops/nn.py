"""Neural-network layer ops — the MXU-heavy kernels.

Reference: src/operator/*.{cc,cu,-inl.h} (SURVEY.md N9): Convolution,
FullyConnected, BatchNorm, Pooling, Activation, LeakyReLU, Dropout, LRN,
InstanceNorm, UpSampling, sequence ops…

TPU-native notes:
 * Convolution/FC lower to ``lax.conv_general_dilated``/``dot_general`` —
   XLA tiles these onto the MXU; layouts stay NCHW at the API surface
   (reference compatible) and XLA picks the internal layout.
 * BatchNorm threads its moving stats functionally; the registry writes them
   back into the aux NDArrays (aux-state parity with the reference's
   mutable aux arrays).
 * Dropout takes a traced PRNG key (needs_rng) so compiled graphs stay pure.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, nn as jnn

from .registry import register


def _pair(v, n=2):
    if isinstance(v, (tuple, list)):
        t = tuple(int(x) for x in v)
        return t
    return (int(v),) * n


# ---------------------------------------------------------------------------
# FullyConnected — reference fully_connected-inl.h:112-176 (linalg_gemm)
# ---------------------------------------------------------------------------

@register("FullyConnected", arg_names=("data", "weight", "bias"),
          defaults={"num_hidden": 0, "no_bias": False, "flatten": True})
def _fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False,
                     flatten=True, **_):
    if flatten:
        x = data.reshape(data.shape[0], -1)
    else:
        x = data
    if weight.dtype != x.dtype:
        weight = weight.astype(x.dtype)
    # no preferred_element_type: the MXU accumulates in f32 internally
    # for bf16 operands anyway, and mixed-dtype conv/dot transpose rules
    # reject an f32 cotangent against bf16 residuals
    out = jnp.dot(x, weight.T)
    if not no_bias and bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution — reference convolution-inl.h; NCHW/OIHW like the reference,
# grouped conv via feature_group_count.
# ---------------------------------------------------------------------------

@register("Convolution", arg_names=("data", "weight", "bias"),
          aliases=("Convolution_v1",),
          defaults={"kernel": (), "stride": (), "dilate": (), "pad": (),
                    "num_filter": 0, "num_group": 1, "no_bias": False,
                    "workspace": 1024, "cudnn_tune": None,
                    "cudnn_off": False, "layout": None})
def _convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                 pad=(), num_filter=0, num_group=1, no_bias=False, **_):
    nd = len(kernel) if kernel else data.ndim - 2
    stride = _pair(stride, nd) if stride else (1,) * nd
    dilate = _pair(dilate, nd) if dilate else (1,) * nd
    pad = _pair(pad, nd) if pad else (0,) * nd
    if nd == 1:
        # lift 1D conv to 2D (TPU MXU prefers 2D convs)
        out = _convolution(data[..., None], weight[..., None],
                           bias, kernel=(kernel[0], 1),
                           stride=(stride[0], 1), dilate=(dilate[0], 1),
                           pad=(pad[0], 0), num_filter=num_filter,
                           num_group=num_group, no_bias=True)
        out = out[..., 0]
        if not no_bias and bias is not None:
            out = out + bias.reshape((1, -1, 1))
        return out
    dn_spec = ("NCHW", "OIHW", "NCHW") if nd == 2 else \
        ("NCDHW", "OIDHW", "NCDHW")
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, dn_spec)
    if weight.dtype != data.dtype:
        # mixed-precision tolerance: compute in the activation dtype
        weight = weight.astype(data.dtype)
    out = lax.conv_general_dilated(
        data, weight,
        window_strides=stride,
        padding=tuple((p, p) for p in pad),
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


@register("Deconvolution", arg_names=("data", "weight", "bias"),
          defaults={"kernel": (), "stride": (), "dilate": (), "pad": (),
                    "adj": (), "target_shape": (), "num_filter": 0,
                    "num_group": 1, "no_bias": True, "workspace": 512,
                    "cudnn_tune": None, "cudnn_off": False, "layout": None})
def _deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                   pad=(), adj=(), target_shape=(), num_filter=0,
                   num_group=1, no_bias=True, **_):
    nd = len(kernel) if kernel else 2
    stride = _pair(stride, nd) if stride else (1,) * nd
    dilate = _pair(dilate, nd) if dilate else (1,) * nd
    pad = _pair(pad, nd) if pad else (0,) * nd
    adj = _pair(adj, nd) if adj else (0,) * nd
    if weight.dtype != data.dtype:
        # mixed-precision tolerance (same as Convolution)
        weight = weight.astype(data.dtype)
    # ConvTranspose = grad of conv w.r.t. input: lhs-dilated conv with
    # flipped kernel. weight layout: (in_c, out_c/g, kh, kw) like reference.
    w = jnp.flip(weight, axis=tuple(range(2, 2 + nd)))
    w = jnp.swapaxes(w, 0, 1)  # -> (out_c/g, in_c, kh, kw)
    if num_group > 1:
        # regroup for feature_group_count semantics
        ic = data.shape[1]
        w = weight.reshape(num_group, ic // num_group, -1, *weight.shape[2:])
        w = jnp.flip(w, axis=tuple(range(3, 3 + nd)))
        w = jnp.swapaxes(w, 1, 2).reshape(-1, ic // num_group,
                                          *weight.shape[2:])
    dn_spec = ("NCHW", "OIHW", "NCHW") if nd == 2 else ("NCDHW", "OIDHW", "NCDHW")
    dn = lax.conv_dimension_numbers(data.shape, w.shape, dn_spec)
    padding = tuple(
        (dilate[i] * (kernel[i] - 1) - pad[i],
         dilate[i] * (kernel[i] - 1) - pad[i] + adj[i])
        for i in range(nd))
    out = lax.conv_general_dilated(
        data, w, window_strides=(1,) * nd, padding=padding,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=num_group)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * nd)
    return out


# ---------------------------------------------------------------------------
# Pooling — reference pooling-inl.h; NCHW reduce_window.
# ---------------------------------------------------------------------------

@register("Pooling", arg_names=("data",), aliases=("Pooling_v1",),
          defaults={"kernel": (), "pool_type": "max", "stride": (),
                    "pad": (), "global_pool": False,
                    "pooling_convention": "valid", "cudnn_off": False})
def _pooling(data, kernel=(), pool_type="max", stride=(), pad=(),
             global_pool=False, pooling_convention="valid", **_):
    nd = data.ndim - 2
    if global_pool:
        axes = tuple(range(2, data.ndim))
        if pool_type == "max":
            return jnp.max(data, axis=axes, keepdims=True)
        return jnp.mean(data, axis=axes, keepdims=True)
    kernel = _pair(kernel, nd)
    stride = _pair(stride, nd) if stride else (1,) * nd
    pad = _pair(pad, nd) if pad else (0,) * nd
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad on the high side so ceil division is achieved
        extra = []
        for i in range(nd):
            size = data.shape[2 + i]
            out_f = int(np.ceil((size + 2 * pad[i] - kernel[i]) /
                                float(stride[i]))) + 1
            needed = (out_f - 1) * stride[i] + kernel[i] - size - 2 * pad[i]
            extra.append(max(0, needed))
        padding = ((0, 0), (0, 0)) + tuple(
            (pad[i], pad[i] + extra[i]) for i in range(nd))
    else:
        padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else \
            jnp.iinfo(data.dtype).min
        if nd == 2 and jnp.issubdtype(data.dtype, jnp.floating) and \
                os.environ.get("MXNET_POOL_DENSE_BWD", "0") == "1":
            # OFF by default: measured on a real v5e chip, the kh*kw
            # dense formulation below is 10-12x SLOWER than XLA's
            # SelectAndScatter autodiff at conv-net pool shapes (38 ms
            # vs 3.6 ms fwd+bwd at the ResNet stem, bench_out/
            # pool_micro.jsonl) — each of the 2*kh*kw passes streams
            # the full padded tensor from HBM, swamping whatever the
            # scatter serialization costs. Kept behind the env knob
            # for its tie-SPLITTING subgradient (ties share dy/count;
            # SelectAndScatter picks one winner) and as the A/B
            # harness for benchmark/bench_pool.py. Reverse-mode only
            # (custom_vjp): the default path is also what jvp users
            # get.
            return _max_pool2d_dense_bwd(data, kernel, stride,
                                         padding[2:])
        return lax.reduce_window(data, init, lax.max, window, strides,
                                 padding)
    if pool_type == "avg":
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides,
                                   padding)
        counts = lax.reduce_window(jnp.ones_like(data), 0.0, lax.add,
                                   window, strides, padding)
        return summed / counts
    if pool_type == "sum":
        return lax.reduce_window(data, 0.0, lax.add, window, strides,
                                 padding)
    raise ValueError("unknown pool_type %r" % pool_type)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _max_pool2d_dense_bwd(x, kernel, stride, pad2):
    """2-D max pooling whose BACKWARD avoids SelectAndScatter.

    Forward: the normal reduce_window max. Backward: for each kernel
    offset (a, b), the strided slice of the (-inf padded) input that
    fed the windows is compared against the pooled output; matches
    route dy there via an interior-padded (dilated) dense pad — kh*kw
    fully-vectorized passes instead of XLA's serialized scatter.

    Subgradient choice: a window with TIED maxima SPLITS dy equally
    among them (dy/count each) — magnitude-preserving, so tie-heavy
    data (integer-grid pixels!) trains like the one-winner
    SelectAndScatter; off ties the two are gradient-identical. The
    reference's mshadow x==y routing gave every tie the FULL dy,
    which measurably inflates gradients on quantized inputs (caught
    by the real-digits convergence gate)."""
    return _max_pool2d_fwd_impl(x, kernel, stride, pad2)


def _max_pool2d_fwd_impl(x, kernel, stride, pad2):
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    padding = ((0, 0), (0, 0)) + tuple(pad2)
    return lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                             padding)


def _max_pool2d_fwd(x, kernel, stride, pad2):
    y = _max_pool2d_fwd_impl(x, kernel, stride, pad2)
    return y, (x, y)


def _max_pool2d_bwd(kernel, stride, pad2, res, dy):
    x, y = res
    (kh, kw), (sh, sw) = kernel, stride
    (pt, pb), (pl, pr) = pad2
    OH, OW = y.shape[2], y.shape[3]
    xp = jnp.pad(x.astype(jnp.float32),
                 ((0, 0), (0, 0), (pt, pb), (pl, pr)),
                 constant_values=-jnp.inf)
    yf = y.astype(jnp.float32)
    HP, WP = xp.shape[2], xp.shape[3]

    def window_views():
        for a in range(kh):
            for b in range(kw):
                # windows' (a, b) elements, aligned with the output
                yield a, b, lax.slice(
                    xp, (0, 0, a, b),
                    (xp.shape[0], xp.shape[1],
                     a + sh * (OH - 1) + 1, b + sw * (OW - 1) + 1),
                    (1, 1, sh, sw))

    # pass 1: per-window tie count (== 1 off ties)
    count = jnp.zeros_like(yf)
    for _a, _b, x_ab in window_views():
        count = count + (x_ab == yf).astype(jnp.float32)
    share = dy.astype(jnp.float32) / count
    # pass 2: route dy/count to every maximum — dilate by the stride
    # and place at the offset: a pure pad, no scatter
    dxp = jnp.zeros_like(xp)
    for a, b, x_ab in window_views():
        contrib = jnp.where(x_ab == yf, share, 0.0)
        dxp = dxp + lax.pad(
            contrib, jnp.float32(0),
            ((0, 0, 0), (0, 0, 0),
             (a, HP - a - (sh * (OH - 1) + 1), sh - 1),
             (b, WP - b - (sw * (OW - 1) + 1), sw - 1)))
    dx = dxp[:, :, pt:HP - pb, pl:WP - pr]
    return (dx.astype(x.dtype),)


_max_pool2d_dense_bwd.defvjp(_max_pool2d_fwd, _max_pool2d_bwd)


# ---------------------------------------------------------------------------
# BatchNorm — reference batch_norm-inl.h; aux moving stats are state.
# fn returns (out[, mean, var], new_moving_mean, new_moving_var)
# ---------------------------------------------------------------------------

def _bn_train_core(data, g, beta, eps, red, bshape):
    """Training-mode BN with ONE-PASS statistics and a closed-form
    backward — the HBM-traffic-minimal formulation (this op was
    measured at ~18% of the ResNet-50 step, docs/mfu_analysis.md):

    forward: shifted sums sum(x-c) and sum((x-c)^2) are SIBLING
    reductions over the same bf16 input (XLA fuses them into one loop
    with f32 accumulators; jnp.var's E[(x-mean)^2] would chain two
    dependent passes), then one read+write apply pass — 2 reads +
    1 write total. The per-channel shift c (the first sample's channel
    mean — a 1/N-of-the-data reduction, then an in-pass broadcast
    subtract) removes the catastrophic cancellation of the naive
    E[x^2]-E[x]^2 form when |mean| >> std: variance is
    translation-invariant, and with c drawn from the batch itself the
    shifted mean is O(std), giving two-pass-grade accuracy at
    one-pass HBM cost (advisor r4).

    backward: the textbook closed form
        dx = (g*inv/m) * (m*dy - sum(dy) - xhat*sum(dy*xhat))
    needs only the sibling pair sum(dy), sum(dy*xhat) (one pass over
    dy,x) plus the dx pass — autodiff of the two-pass forward chains
    dvar/dmean passes on top. The mean/var outputs' own cotangents
    (nonzero when a graph differentiates through output_mean_var)
    enter via d mean/dx = 1/m and d var/dx = 2(x-mean)/m, fused into
    the same dx pass; training graphs pass zeros there and XLA folds
    the terms away.

    Returns (y, mean, var); callers thread moving stats outside (the
    custom_vjp boundary must not capture them)."""

    @jax.custom_vjp
    def f(x, g, b):
        y, mean, var, _inv = fwd_impl(x, g, b)
        return y, mean, var

    def fwd_impl(x, g, b):
        m = 1
        for i in red:
            m *= x.shape[i]
        xf = x.astype(jnp.float32)
        # per-channel shift: the FIRST SAMPLE's channel mean — a
        # reduction over 1/N of the data, so near-free next to the two
        # main sums, but robust where a single anchor pixel is not
        # (e.g. a zero-padded corner in a large-mean channel would
        # reintroduce the very cancellation the shift removes)
        x0 = lax.index_in_dim(xf, 0, red[0], keepdims=True)
        cb = lax.stop_gradient(
            jnp.mean(x0, axis=red, keepdims=True))   # bshape
        c = cb.reshape(-1)                           # (C,)
        s1 = jnp.sum(xf - cb, axis=red)
        s2 = jnp.sum(jnp.square(xf - cb), axis=red)
        mean_s = s1 / m
        mean = c + mean_s
        var = jnp.maximum(s2 / m - jnp.square(mean_s), 0.0)
        inv = lax.rsqrt(var + eps)
        y = ((xf - mean.reshape(bshape))
             * (inv.reshape(bshape)
                * g.reshape(bshape).astype(jnp.float32))
             + b.reshape(bshape).astype(jnp.float32)).astype(x.dtype)
        return y, mean, var, inv

    def fwd(x, g, b):
        y, mean, var, inv = fwd_impl(x, g, b)
        return (y, mean, var), (x, g, mean, inv)

    def bwd(res, cts):
        dy = cts[0].astype(jnp.float32)
        dmean = cts[1].astype(jnp.float32)
        dvar = cts[2].astype(jnp.float32)
        x, g, mean, inv = res
        m = 1
        for i in red:
            m *= x.shape[i]
        xc = x.astype(jnp.float32) - mean.reshape(bshape)
        db = jnp.sum(dy, axis=red)                     # sibling pair:
        dgx = jnp.sum(dy * xc, axis=red) * inv         # one pass
        k = (g.astype(jnp.float32) * inv) / m
        dx = (k.reshape(bshape)
              * (m * dy - db.reshape(bshape)
                 - xc * (inv * dgx).reshape(bshape))
              # mean/var output cotangents (zero in training graphs)
              + (dmean / m).reshape(bshape)
              + (2.0 / m) * xc * dvar.reshape(bshape)).astype(x.dtype)
        return dx, dgx.astype(g.dtype), db.astype(beta.dtype)

    f.defvjp(fwd, bwd)
    return f(data, g, beta)


@register("BatchNorm", arg_names=("data", "gamma", "beta", "moving_mean",
                                  "moving_var"),
          aliases=("BatchNorm_v1",), takes_is_train=True,
          state_inputs=(3, 4),
          defaults={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                    "use_global_stats": False, "output_mean_var": False,
                    "axis": 1, "cudnn_off": False})
def _batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                momentum=0.9, fix_gamma=True, use_global_stats=False,
                output_mean_var=False, axis=1, is_train=False, **_):
    axis = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != axis)
    bshape = tuple(data.shape[axis] if i == axis else 1
                   for i in range(data.ndim))
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    # statistics in float32 regardless of compute dtype (mixed-precision
    # discipline: bf16 activations, f32 batch stats), output back in the
    # input dtype so downstream convs see one dtype
    if is_train and not use_global_stats:
        # fix_gamma: g is ones_like(gamma), so no gradient reaches
        # gamma through the core (ones_like is a constant), matching
        # the reference's zeroed fixed-gamma grad
        import os as _os
        if _os.environ.get("MXNET_BN_PALLAS") == "1" and \
                data.ndim == 4 and axis == 1:
            # below-XLA experiment: explicit-pass Pallas kernels
            # (ops/bn_pallas.py) — same math, guaranteed 2-read
            # forward / 2-read backward structure
            from .bn_pallas import bn_train_pallas
            out, mean, var = bn_train_pallas(data, g, beta,
                                             float(eps))
        elif _os.environ.get("MXNET_BN_IMPL") == "onepass":
            # the r4 one-pass/closed-form custom_vjp rewrite — kept as
            # an experiment, NOT the default: measured on a real v5e
            # it is never faster than the plain autodiff form below
            # and falls off a cliff at the ResNet stem shape (1831 ms
            # vs 3.1 ms fwd+bwd at (128,64,112,112), bench_out/
            # bn_micro.jsonl) — the custom_vjp boundary blocks the
            # surrounding fusion the "one pass" was meant to buy
            out, mean, var = _bn_train_core(data, g, beta, float(eps),
                                            red, bshape)
        else:
            # default: plain two-pass statistics, autodiff backward —
            # no custom_vjp boundary, so XLA fuses BN into the
            # neighboring convs' epilogues freely. On-chip microbench
            # and whole-model A/B both prefer this over the one-pass
            # rewrite (bench_out/{bn_micro,ab_regression}.jsonl).
            #
            # MXNET_BN_STATS=dot|auto: statistics as MXU contractions
            # (sum_nx x = ones-vector einsum, sum_nx x^2 = self inner
            # product; bf16 x bf16 products are exact in the f32
            # accumulator). The live micro A/B
            # (bench_out/bn_stats_micro.jsonl) shows the VPU reduce
            # wins at early-net shapes but LOSES at deep-stage shapes
            # (C large, HW small: 1.8x at 1024x14^2) — 'auto' applies
            # the contraction only there (C >= 2*H*W). One-pass
            # E[x^2]-E[x]^2 in f32: fine for post-conv activations,
            # degrades when |mean|/std > ~3e3 (the two-pass default
            # has no such limit).
            stats = _os.environ.get("MXNET_BN_STATS", "")
            dot_ok = (stats in ("dot", "auto") and data.ndim == 4
                      and axis == 1)
            if dot_ok and stats == "auto":
                # gate to the one measured crossover class (the
                # 1024x14^2 row of bn_stats_micro.jsonl, 1.8x): big C
                # with a not-tiny spatial extent. 2048x7^2 also has
                # C >= 2*HW but measured 0.94x, hence the HW floor.
                hw = data.shape[2] * data.shape[3]
                dot_ok = data.shape[1] >= 2 * hw and hw >= 128
            xf = data.astype(jnp.float32)
            if dot_ok:
                N, C, H, W = data.shape
                m = N * H * W
                x3 = data.reshape(N, C, H * W)
                ones = jnp.ones((N, H * W), data.dtype)
                s1 = jnp.einsum("ncx,nx->c", x3, ones,
                                preferred_element_type=jnp.float32)
                s2 = jnp.einsum("ncx,ncx->c", x3, x3,
                                preferred_element_type=jnp.float32)
                mean = s1 / m
                var = jnp.maximum(s2 / m - jnp.square(mean), 0.0)
            else:
                mean = jnp.mean(xf, axis=red)
                var = jnp.var(xf, axis=red)
            inv = lax.rsqrt(var.reshape(bshape) + eps)
            out = ((xf - mean.reshape(bshape)) * inv
                   * g.reshape(bshape).astype(jnp.float32)
                   + beta.reshape(bshape).astype(jnp.float32)
                   ).astype(data.dtype)
        new_mm = moving_mean * momentum + mean * (1 - momentum)
        new_mv = moving_var * momentum + var * (1 - momentum)
        use_mean, use_var = mean, var
    else:
        xf = data.astype(jnp.float32)
        mean = moving_mean.astype(jnp.float32)
        var = moving_var.astype(jnp.float32)
        new_mm, new_mv = moving_mean, moving_var
        use_mean, use_var = mean, var
        inv = lax.rsqrt(use_var.reshape(bshape) + eps)
        out = ((xf - use_mean.reshape(bshape)) * inv *
               g.reshape(bshape).astype(jnp.float32) +
               beta.reshape(bshape).astype(jnp.float32)).astype(
            data.dtype)
    if output_mean_var:
        return (out, use_mean, lax.rsqrt(use_var + eps),
                lax.stop_gradient(new_mm), lax.stop_gradient(new_mv))
    return (out, lax.stop_gradient(new_mm), lax.stop_gradient(new_mv))


@register("InstanceNorm", arg_names=("data", "gamma", "beta"),
          defaults={"eps": 1e-3})
def _instance_norm(data, gamma, beta, eps=1e-3, **_):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * lax.rsqrt(var + eps) * gamma.reshape(bshape) + \
        beta.reshape(bshape)


@register("LayerNorm", arg_names=("data", "gamma", "beta"),
          defaults={"axis": -1, "eps": 1e-5, "output_mean_var": False})
def _layer_norm(data, gamma, beta, axis=-1, eps=1e-5,
                output_mean_var=False, **_):
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * lax.rsqrt(var + eps)
    bshape = [1] * data.ndim
    bshape[axis] = data.shape[axis]
    out = out * gamma.reshape(bshape) + beta.reshape(bshape)
    if output_mean_var:
        return out, jnp.squeeze(mean, axis), jnp.squeeze(var, axis)
    return out


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------

@register("Activation", arg_names=("data",),
          defaults={"act_type": "relu"})
def _activation(data, act_type="relu", **_):
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jnn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jnn.softplus(data)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise ValueError("unknown act_type %r" % act_type)


@register("LeakyReLU", arg_names=("data", "gamma"), needs_rng=True,
          takes_is_train=True,
          defaults={"act_type": "leaky", "slope": 0.25,
                    "lower_bound": 0.125, "upper_bound": 0.334})
def _leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
                lower_bound=0.125, upper_bound=0.334, is_train=False,
                rng=None, **_):
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) \
            if gamma.ndim == 1 and data.ndim > 1 else gamma
        return jnp.where(data >= 0, data, g * data)
    if act_type == "rrelu":
        if is_train:
            import jax
            s = jax.random.uniform(rng, data.shape, data.dtype,
                                   lower_bound, upper_bound)
        else:
            s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, s * data)
    raise ValueError("unknown act_type %r" % act_type)


@register("SoftmaxActivation", arg_names=("data",),
          defaults={"mode": "instance"})
def _softmax_activation(data, mode="instance", **_):
    if mode == "channel":
        return jnn.softmax(data, axis=1)
    return jnn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(
        data.shape)


# ---------------------------------------------------------------------------
# Dropout — traced PRNG key keeps jitted training steps pure.
# ---------------------------------------------------------------------------

@register("Dropout", arg_names=("data",), needs_rng=True,
          takes_is_train=True,
          defaults={"p": 0.5, "mode": "training"})
def _dropout(data, p=0.5, mode="training", is_train=False, rng=None, **_):
    import jax
    if p <= 0 or (not is_train and mode != "always"):
        return data
    keep = 1.0 - p
    mask = jax.random.bernoulli(rng, keep, data.shape)
    return jnp.where(mask, data / keep, 0).astype(data.dtype)


# ---------------------------------------------------------------------------
# LRN — reference lrn-inl.h
# ---------------------------------------------------------------------------

@register("LRN", arg_names=("data",),
          defaults={"alpha": 1e-4, "beta": 0.75, "knorm": 2.0, "nsize": 5})
def _lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **_):
    sq = jnp.square(data)
    half = nsize // 2
    sq_pad = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (data.ndim - 2))
    window = jnp.zeros_like(sq)
    for i in range(nsize):
        window = window + lax.dynamic_slice_in_dim(sq_pad, i, data.shape[1],
                                                   axis=1)
    return data / jnp.power(knorm + alpha / nsize * window, beta)


# ---------------------------------------------------------------------------
# UpSampling / Crop
# ---------------------------------------------------------------------------

@register("UpSampling", arg_names=None,
          defaults={"scale": 1, "sample_type": "nearest", "num_args": 1,
                    "num_filter": 0, "multi_input_mode": "concat",
                    "workspace": 512})
def _upsampling(*args, scale=1, sample_type="nearest",
                multi_input_mode="concat", **_):
    import jax
    outs = []
    data = args[0]
    h, w = data.shape[2] * scale, data.shape[3] * scale
    for x in (args if sample_type == "nearest" else args[:1]):
        if sample_type == "nearest":
            out = jnp.repeat(jnp.repeat(x, scale, axis=2), scale, axis=3)
        else:
            out = jax.image.resize(x.astype(jnp.float32),
                                   x.shape[:2] + (h, w),
                                   method="bilinear").astype(x.dtype)
        outs.append(out)
    if len(outs) == 1:
        return outs[0]
    if multi_input_mode == "sum":
        return sum(outs)
    return jnp.concatenate(outs, axis=1)


@register("Crop", arg_names=None,
          defaults={"num_args": 1, "offset": (0, 0), "h_w": (0, 0),
                    "center_crop": False})
def _crop(*args, offset=(0, 0), h_w=(0, 0), center_crop=False, **_):
    data = args[0]
    if len(args) == 2:
        h, w = args[1].shape[2], args[1].shape[3]
    else:
        h, w = h_w
    if center_crop:
        oy = (data.shape[2] - h) // 2
        ox = (data.shape[3] - w) // 2
    else:
        oy, ox = offset
    return data[:, :, oy:oy + h, ox:ox + w]


# ---------------------------------------------------------------------------
# Sequence ops — reference src/operator/sequence_*.cc
# ---------------------------------------------------------------------------

@register("SequenceMask", arg_names=("data", "sequence_length"),
          nondiff_inputs=(1,),
          defaults={"use_sequence_length": False, "value": 0.0, "axis": 0})
def _sequence_mask(data, sequence_length=None, use_sequence_length=False,
                   value=0.0, axis=0, **_):
    if not use_sequence_length or sequence_length is None:
        return data
    maxlen = data.shape[axis]
    steps = jnp.arange(maxlen)
    mask = steps[:, None] < sequence_length[None, :].astype(jnp.int32)
    if axis == 1:
        mask = mask.T
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, value)


@register("SequenceLast", arg_names=("data", "sequence_length"),
          nondiff_inputs=(1,),
          defaults={"use_sequence_length": False, "axis": 0})
def _sequence_last(data, sequence_length=None, use_sequence_length=False,
                   axis=0, **_):
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    idx = (sequence_length.astype(jnp.int32) - 1)
    batch = jnp.arange(data.shape[1 - axis])
    if axis == 0:
        return data[idx, batch]
    return data[batch, idx]


@register("SequenceReverse", arg_names=("data", "sequence_length"),
          nondiff_inputs=(1,),
          defaults={"use_sequence_length": False, "axis": 0})
def _sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                      axis=0, **_):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    maxlen = data.shape[0]
    lens = sequence_length.astype(jnp.int32)
    steps = jnp.arange(maxlen)[:, None]
    rev_idx = jnp.where(steps < lens[None, :], lens[None, :] - 1 - steps,
                        steps)
    batch = jnp.arange(data.shape[1])[None, :]
    return data[rev_idx, batch]
