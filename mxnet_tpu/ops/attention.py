"""Fused scaled-dot-product attention — Pallas flash kernel.

New TPU-native capability (the 2017 reference predates attention; this
is the hot op the framework's long-context story is built on — see
mxnet_tpu/parallel/ring.py for the sequence-parallel ring variant).

Design: classic flash attention. Grid (batch*heads, q_blocks, k_blocks)
with the k axis innermost ("arbitrary" semantics); online-softmax
running max/denominator/accumulator live in VMEM scratch; each
(block_q, d) @ (d, block_k) product lands on the MXU with float32
accumulation. O(T) memory instead of the naive (T, T) score matrix.

Backward recomputes probabilities blockwise in jnp under remat-friendly
form (one (block, T) strip at a time via the saved row statistics) —
XLA fuses it; the forward kernel is where flash wins (no score
materialization) and stays Pallas.

Off-TPU (CPU tests, axon-less runs) the same kernel executes in
interpreter mode, so numerics are identical everywhere.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import register

_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
                      *, scale, causal, block_q, block_k, num_kb, seq_k):
    kb = pl.program_id(2)
    qb = pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _block():
        q = q_ref[0]                          # (bq, d)
        k = k_ref[0]                          # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        cols = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        valid = cols < seq_k        # ragged tail: padded keys masked out
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            valid = valid & (rows >= cols)
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                # (bq, bk)
        alpha = jnp.exp(m_prev - m_new)       # (bq, 1)
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=1, keepdims=True)
        # padded tail rows of V must be zeroed, not just down-weighted:
        # 0 * garbage (NaN-filled pad in interpret mode) would poison acc
        v_rows = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)
        v_blk = jnp.where(v_rows < seq_k, v_ref[0], 0)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        # whole block above the diagonal: skip (saves ~half the FLOPs)
        pl.when(qb * block_q + block_q - 1 >= kb * block_k)(_block)
    else:
        _block()

    @pl.when(kb == num_kb - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret):
    BH, T, D = q.shape
    Tk = k.shape[1]
    block_q = min(block_q, T)
    block_k = min(block_k, Tk)
    nq = -(-T // block_q)
    nk = -(-Tk // block_k)

    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_kb=nk, seq_k=Tk)
    # under a vma-checking shard_map (e.g. a pipeline stage) the output
    # aval must declare how it varies over mesh axes — the union of the
    # inputs' variance (q may be replicated while k/v rotate, or vice
    # versa). jax<0.9 has neither typeof nor vma; skip there.
    typeof = getattr(jax, "typeof", None)
    out_vma = None
    if typeof is not None:
        vmas = [getattr(typeof(x), "vma", None) for x in (q, k, v)]
        vmas = [v_ for v_ in vmas if v_]
        out_vma = frozenset().union(*vmas) if vmas else None
    out_shape = jax.ShapeDtypeStruct(q.shape, q.dtype, vma=out_vma) \
        if out_vma else jax.ShapeDtypeStruct(q.shape, q.dtype)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _attn_reference(q, k, v, scale, causal):
    """Plain jnp attention (oracle + backward building block)."""
    s = jnp.einsum("bqd,bkd->bqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        T, Tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(T)[:, None] >= jnp.arange(Tk)[None, :]
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, scale, causal, block_q, block_k):
    interpret = jax.default_backend() != "tpu"
    return _flash_forward(q, k, v, scale, causal, block_q, block_k,
                          interpret)


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k):
    o = _flash(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v)


def _flash_bwd_rule(scale, causal, block_q, block_k, res, do):
    q, k, v = res
    # standard attention gradients with probability recompute; wrapped in
    # checkpoint so XLA rematerializes strips instead of caching (T,T)
    def f(q_, k_, v_):
        return _attn_reference(q_, k_, v_, scale, causal)
    _, vjp = jax.vjp(jax.checkpoint(f), q, k, v)
    return vjp(do)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(query, key, value, scale=None, causal=False,
                    block_q=128, block_k=128):
    """Fused attention over (B, H, T, D) or (BH, T, D) inputs."""
    q4 = query.ndim == 4
    if q4:
        B, H, T, D = query.shape
        query = query.reshape(B * H, T, D)
        key = key.reshape(B * H, key.shape[2], D)
        value = value.reshape(B * H, value.shape[2], D)
    if scale is None:
        scale = query.shape[-1] ** -0.5
    out = _flash(query, key, value, float(scale), bool(causal),
                 int(block_q), int(block_k))
    if q4:
        out = out.reshape(B, H, T, D)
    return out


@register("_contrib_FlashAttention",
          arg_names=("query", "key", "value"),
          aliases=("_contrib_flash_attention",),
          defaults={"scale": None, "causal": False, "block_q": 128,
                    "block_k": 128, "seq_axis": None})
def _flash_attention_op(query, key, value, scale=None, causal=False,
                        block_q=128, block_k=128, seq_axis=None, **_):
    """(B, H, T, D) fused attention; returns same shape.

    seq_axis: name of a mesh axis to sequence-parallelize over. When the
    surrounding graph is lowered over a mesh carrying that axis (>1
    devices), the op runs RING attention — q stays put, k/v blocks
    rotate via ppermute, each device holds T/n of the sequence
    (parallel/ring.py; the symbol-level long-context path). Otherwise
    (eager, no mesh, or axis absent/size-1) it is the single-chip
    Pallas flash kernel. Inputs must be 4-D (B, H, T, D) for the ring
    path."""
    if seq_axis:
        from ._mesh_ctx import active_mesh_axis
        mesh = active_mesh_axis(seq_axis)
        if mesh is not None:
            if query.ndim != 4:
                raise ValueError(
                    "seq_axis ring attention needs (B, H, T, D) inputs, "
                    "got ndim=%d" % query.ndim)
            from ..parallel.ring import ring_attention
            return ring_attention(query, key, value, mesh, seq_axis,
                                  causal=bool(causal), scale=scale)
    return flash_attention(query, key, value, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k)
