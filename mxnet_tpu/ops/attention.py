"""Fused scaled-dot-product attention — Pallas flash kernel.

New TPU-native capability (the 2017 reference predates attention; this
is the hot op the framework's long-context story is built on — see
mxnet_tpu/parallel/ring.py for the sequence-parallel ring variant).

Design: classic flash attention. Grid (batch*heads, q_blocks, k_blocks)
with the k axis innermost ("arbitrary" semantics); online-softmax
running max/denominator/accumulator live in VMEM scratch; each
(block_q, d) @ (d, block_k) product lands on the MXU with float32
accumulation. O(T) memory instead of the naive (T, T) score matrix.

Backward is the FlashAttention-2 split: the forward additionally emits
the per-row logsumexp; the backward runs two Pallas kernels — a dq pass
(grid over q blocks, k innermost) and a dk/dv pass (grid over k blocks,
q innermost) — plus a cheap jnp delta = rowsum(do * o) precompute.
Nothing ever materializes a (T, T) score tensor, so the backward stays
HBM-light at long context (the dense-recompute alternative cost ~60 ms
/step on the v5e transformer bench from (BH, T, T) f32 traffic alone).

Off-TPU (CPU tests, axon-less runs) the same kernel executes in
interpreter mode, so numerics are identical everywhere.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .registry import register

_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                      scale, causal, block_q, block_k, num_kb, seq_k,
                      want_lse, window=0, band_offset=0):
    # the lse output only exists under differentiation (want_lse);
    # forward-only calls skip its ~BH*T*128 f32 HBM writes entirely
    if want_lse:
        lse_ref, m_ref, l_ref, acc_ref = rest
    else:
        m_ref, l_ref, acc_ref = rest
    kb = pl.program_id(2)
    qb = pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _block():
        q = q_ref[0]                          # (bq, d)
        k = k_ref[0]                          # (bk, d)
        # precision is pinned to DEFAULT: native-dtype MXU passes with f32
        # accumulation (preferred_element_type) — the flash numerics
        # contract. Inheriting the ambient jax_default_matmul_precision
        # (MXNET_MATMUL_PRECISION=highest sets float32 globally) would ask
        # Mosaic for an fp32-contract bf16 matmul, which it rejects
        # ("Bad lhs type") — the global knob is an XLA-lowering policy for
        # f32 arrays, not a Pallas one.
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32) * scale   # (bq, bk)
        cols = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        valid = cols < seq_k        # ragged tail: padded keys masked out
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            valid = _band_valid(valid, rows, cols, window, band_offset)
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                # (bq, bk)
        alpha = jnp.exp(m_prev - m_new)       # (bq, 1)
        l_ref[:] = l_ref[:] * alpha + p.sum(axis=1, keepdims=True)
        # padded tail rows of V must be zeroed, not just down-weighted:
        # 0 * garbage (NaN-filled pad in interpret mode) would poison acc
        v_blk = _masked_block(v_ref, kb * block_k, seq_k, block_k)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        # whole block outside the band: skip (half the FLOPs for plain
        # causal; O(T*window) total with a window)
        pl.when(_band_run(qb, kb, block_q, block_k, window,
                          band_offset))(_block)
    else:
        _block()

    @pl.when(kb == num_kb - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        # logsumexp per row — the backward's softmax recompute key,
        # replicated across the 128-lane minor dim (Mosaic's block rules
        # want the last dim %128; a (BH, T) layout would put a size-1
        # sublane dim in the block — same trick as jax's own TPU flash
        # kernel's l/m residuals). Fully-masked (padded) rows have
        # l == 0; the max() keeps their lse finite so the backward's
        # exp() stays NaN-free (their contributions are masked there).
        if want_lse:
            lse_ref[0] = jnp.broadcast_to(m_ref[:] + jnp.log(denom),
                                          lse_ref.shape[1:])




def _band_valid(valid, rows, cols, window, offset=0):
    """Causal + optional sliding-window mask shared by all kernels.

    offset: static amount by which q GLOBAL positions lead the k
    positions (rows + offset is the true position of row `rows`) — the
    windowed-ring case, where the visiting k block sits `offset`
    positions earlier in the sequence than the local q block. offset=0
    is the ordinary same-block band."""
    valid = valid & (rows + offset >= cols)
    if window:
        valid = valid & (rows + offset - cols < window)
    return valid


def _band_run(qb, kb, block_q, block_k, window, offset=0):
    """Block participates iff the (q-block x k-block) rectangle meets
    the causal band: below-or-on diagonal, and (with a window) not
    entirely below it. Shared by the fwd/dq/dkv kernels."""
    run = qb * block_q + block_q - 1 + offset >= kb * block_k
    if window:
        run = run & (kb * block_k + block_k - 1
                     > qb * block_q + offset - window)
    return run


_LANES = 128   # minor-dim replication for per-row stats


def _snap_blocks(T, Tk, block_q, block_k, interpret):
    """Clamp blocks to the sequence and, on the compiled TPU path, snap
    them to Mosaic's sublane rule (second-to-last block dim divisible
    by 8, or equal to the array dim). Interpret mode keeps arbitrary
    requests, giving tests coverage of odd blockings."""
    block_q = min(block_q, T)
    block_k = min(block_k, Tk)
    if not interpret:
        if block_q < T and block_q % 8:
            block_q = min(T, max(8, (block_q // 8) * 8))
        if block_k < Tk and block_k % 8:
            block_k = min(Tk, max(8, (block_k // 8) * 8))
    return block_q, block_k


def _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret,
                   want_lse, window=0, band_offset=0):
    q, k, v = _uniform_vma(q, k, v)
    BH, T, D = q.shape
    Tk = k.shape[1]
    block_q, block_k = _snap_blocks(T, Tk, block_q, block_k, interpret)
    nq = -(-T // block_q)
    nk = -(-Tk // block_k)

    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, num_kb=nk, seq_k=Tk, want_lse=want_lse,
        window=window, band_offset=band_offset)
    shapes = [jax.ShapeDtypeStruct(q.shape, q.dtype)]              # o
    out_specs = [pl.BlockSpec((1, block_q, D),
                              lambda b, i, j: (b, i, 0))]
    if want_lse:
        shapes.append(
            jax.ShapeDtypeStruct((BH, T, _LANES), jnp.float32))
        out_specs.append(pl.BlockSpec((1, block_q, _LANES),
                                      lambda b, i, j: (b, i, 0)))
    outs = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=out_specs,
        out_shape=_with_vma(shapes, (q, k, v)),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return outs if want_lse else (outs[0], None)


def _with_vma(shapes, operands):
    """Attach varying-over-mesh-axes info to output avals.

    Under a vma-checking shard_map (e.g. a pipeline stage) the output
    aval must declare how it varies over mesh axes — the union of the
    inputs' variance (q may be replicated while k/v rotate, or vice
    versa). jax<0.9 has neither typeof nor vma; skip there."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return shapes
    vmas = [getattr(typeof(x), "vma", None) for x in operands]
    vmas = [v_ for v_ in vmas if v_ is not None]
    if not vmas:
        return shapes
    # an empty union is still attached: under a vma-checking shard_map
    # with fully-replicated operands the out aval must SAY replicated —
    # omitting vma entirely is only correct outside shard_map (where
    # typeof reports no vma at all)
    vma = frozenset().union(*vmas)
    return [jax.ShapeDtypeStruct(s.shape, s.dtype, vma=vma)
            for s in shapes]


def _uniform_vma(*operands):
    """Broadcast every operand to the union of their mesh variances.

    A pallas_call cannot mix replicated and axis-varying inputs (its
    internal loads trip shard_map's vma check); pvary-ing the
    replicated ones up to the union is a free device-local broadcast,
    and _narrow_vma psums the corresponding cotangents back down."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return operands
    vmas = [getattr(typeof(x), "vma", None) or frozenset()
            for x in operands]
    union = frozenset().union(*vmas)
    if not union:
        return operands
    from ..parallel._compat import pvary
    return tuple(
        pvary(x, tuple(sorted(union - v))) if union - v else x
        for x, v in zip(operands, vmas))


def _dense_with_lse(q, k, v, scale, causal, window=0, band_offset=0):
    """Dense (o, lse) oracle — the single implementation behind
    _attn_reference and the interpret-mode fallbacks."""
    s = jnp.einsum("bqd,bkd->bqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        T, Tk = s.shape[-2], s.shape[-1]
        rows = jnp.arange(T)[:, None] + band_offset
        cols = jnp.arange(Tk)[None, :]
        mask = rows >= cols
        if window:
            mask = mask & (rows - cols < window)
        s = jnp.where(mask, s, _NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    o = jnp.einsum("bqk,bkd->bqd", p.astype(v.dtype), v)
    return o.astype(q.dtype), lse


def _attn_reference(q, k, v, scale, causal):
    """Plain jnp attention (oracle + backward building block)."""
    return _dense_with_lse(q, k, v, scale, causal)[0]


def _masked_block(ref, rows_base, limit, block_rows):
    """Load a (1, block, D) ref, zeroing rows past ``limit`` (the padded
    ragged tail is garbage in interpret mode; 0 * NaN would poison the
    MXU accumulators)."""
    rows = rows_base + jax.lax.broadcasted_iota(
        jnp.int32, (block_rows, 1), 0)
    return jnp.where(rows < limit, ref[0], 0)


def _flash_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dq_ref, dq_acc, *, scale, causal, block_q, block_k,
                     num_kb, seq_q, seq_k, window=0, band_offset=0):
    qb, kb = pl.program_id(1), pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _block():
        q = q_ref[0]
        k = _masked_block(k_ref, kb * block_k, seq_k, block_k)
        v = _masked_block(v_ref, kb * block_k, seq_k, block_k)
        do = do_ref[0]
        lse = lse_ref[0][:, :1]              # (bq, 1)
        delta = delta_ref[0][:, :1]          # (bq, 1)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32) * scale
        cols = kb * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        valid = cols < seq_k
        if causal:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 0)
            valid = _band_valid(valid, rows, cols, window, band_offset)
        p = jnp.where(valid, jnp.exp(s - lse), 0)       # (bq, bk)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)         # (bq, bk)
        # the where() must wrap the whole product: p is already 0 at
        # masked slots, but 0 * (dp - NaN-padded delta) would be NaN
        ds = jnp.where(valid, p * (dp - delta) * scale,
                       0).astype(k.dtype)
        dq_acc[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(_band_run(qb, kb, block_q, block_k, window,
                          band_offset))(_block)
    else:
        _block()

    @pl.when(kb == num_kb - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dk_ref, dv_ref, dk_acc, dv_acc,
                      *, scale, causal, block_q, block_k, num_qb,
                      seq_q, seq_k, window=0, band_offset=0):
    kb, qb = pl.program_id(1), pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _block():
        q = _masked_block(q_ref, qb * block_q, seq_q, block_q)
        do = _masked_block(do_ref, qb * block_q, seq_q, block_q)
        k = k_ref[0]
        v = v_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        rows = qb * block_q + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 0)
        valid = rows < seq_q
        if causal:
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, s.shape, 1)
            valid = _band_valid(valid, rows, cols, window, band_offset)
        p = jnp.where(valid, jnp.exp(s - lse), 0)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)          # (bk, d)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)          # (bq, bk)
        # see dq kernel: NaN-padded delta rows must not reach the MXU
        ds = jnp.where(valid, p * (dp - delta) * scale,
                       0).astype(q.dtype)
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.DEFAULT,
            preferred_element_type=jnp.float32)          # (bk, d)

    if causal:
        # k block outside the band contributes 0
        pl.when(_band_run(qb, kb, block_q, block_k, window,
                          band_offset))(_block)
    else:
        _block()

    @pl.when(qb == num_qb - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, do, scale, causal, block_q,
                    block_k, interpret, dlse=None, window=0,
                    band_offset=0):
    if dlse is None:
        q, k, v, o, lse, do = _uniform_vma(q, k, v, o, lse, do)
    else:
        q, k, v, o, lse, do, dlse = _uniform_vma(q, k, v, o, lse, do,
                                                 dlse)
    BH, T, D = q.shape
    Tk = k.shape[1]
    block_q, block_k = _snap_blocks(T, Tk, block_q, block_k, interpret)
    nq = -(-T // block_q)
    nk = -(-Tk // block_k)

    # delta_i = rowsum(do_i * o_i): one cheap fused elementwise+reduce,
    # lane-replicated like lse (see _flash_fwd_kernel). When the lse
    # output itself carries a cotangent (the ring-merge path), its
    # whole contribution folds into this term: ds_ij = p_ij * (dp_ij -
    # delta_i + dlse_i), since d lse_i / d s_ij = p_ij — so the kernels
    # run unchanged on delta' = delta - dlse.
    delta2 = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                     axis=-1, keepdims=True)
    if dlse is not None:
        delta2 = delta2 - dlse.astype(jnp.float32)[..., None]
    delta = jnp.broadcast_to(delta2, (BH, T, _LANES))
    # the residual stores one lane; re-broadcast transiently for the
    # kernels' (1, block_q, _LANES) stat blocks
    lse = jnp.broadcast_to(lse[..., None], (BH, T, _LANES))

    q_spec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    k_spec = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    r_spec = pl.BlockSpec((1, block_q, _LANES),
                          lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(
            _flash_dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, num_kb=nk,
            seq_q=T, seq_k=Tk, window=window,
            band_offset=band_offset),
        grid=(BH, nq, nk),
        in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
        out_specs=q_spec,
        out_shape=_with_vma(
            [jax.ShapeDtypeStruct(q.shape, q.dtype)], (q, k, v, do))[0],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: grid's middle axis walks k blocks, inner axis q blocks
    q_spec2 = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, j, 0))
    k_spec2 = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0))
    r_spec2 = pl.BlockSpec((1, block_q, _LANES),
                           lambda b, i, j: (b, j, 0))
    kv_shapes = [jax.ShapeDtypeStruct(k.shape, k.dtype),
                 jax.ShapeDtypeStruct(v.shape, v.dtype)]
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, num_qb=nq,
            seq_q=T, seq_k=Tk, window=window,
            band_offset=band_offset),
        grid=(BH, nk, nq),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, r_spec2, r_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=_with_vma(kv_shapes, (q, k, v, do)),
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _dense_fallback(q, k, v, scale, causal, window=0):
    """Pallas's interpret mode cannot execute with mesh-varying
    operands (its internal block loads mix varying data with replicated
    grid indices, tripping shard_map's vma check). Compiled TPU
    execution is an opaque custom call and unaffected — so only the
    CPU-mesh test path takes this dense recompute, wrapped in
    checkpoint so strips rematerialize instead of caching (T, T)."""
    return jax.checkpoint(
        lambda a, b, c: _dense_with_lse(a, b, c, scale, causal,
                                        window)[0]
    )(q, k, v)


def _interpret_needs_fallback(*xs):
    if jax.default_backend() == "tpu":
        return False
    typeof = getattr(jax, "typeof", None)
    return typeof is not None and any(
        getattr(typeof(x), "vma", None) for x in xs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, window=0):
    if _interpret_needs_fallback(q, k, v):
        return _dense_fallback(q, k, v, scale, causal,
                               window).astype(q.dtype)
    interpret = jax.default_backend() != "tpu"
    o, _ = _flash_forward(q, k, v, scale, causal, block_q, block_k,
                          interpret, want_lse=False, window=window)
    return o


def _flash_fwd_rule(q, k, v, scale, causal, block_q, block_k,
                    window=0):
    if _interpret_needs_fallback(q, k, v):
        o = _dense_fallback(q, k, v, scale, causal,
                            window).astype(q.dtype)
        return o, (q, k, v, None, None)
    interpret = jax.default_backend() != "tpu"
    o, lse = _flash_forward(q, k, v, scale, causal, block_q, block_k,
                            interpret, want_lse=True, window=window)
    # residual keeps ONE lane — the 128-lane replication is a Mosaic
    # block-layout need of the backward kernels' INPUT, re-broadcast
    # transiently there, not worth holding across the whole forward
    return o, (q, k, v, o, lse[..., 0])


def _narrow_vma(ct, primal):
    """Reduce a cotangent to its primal's mesh variance.

    The backward kernels stamp every output with the union of the
    inputs' vma (_with_vma). Under a vma-checking shard_map with mixed
    variance (e.g. q replicated while k/v rotate) the correct adjoint
    of the implicit broadcast is a psum over the extra axes."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return ct
    ct_vma = getattr(typeof(ct), "vma", None) or frozenset()
    p_vma = getattr(typeof(primal), "vma", None) or frozenset()
    extra = tuple(sorted(set(ct_vma) - set(p_vma)))
    return jax.lax.psum(ct, extra) if extra else ct


def _flash_bwd_rule(scale, causal, block_q, block_k, window, res, do):
    q, k, v, o, lse = res
    if lse is None:          # dense interpret-mode fallback (see above)
        _, vjp = jax.vjp(
            lambda a, b, c: _dense_fallback(
                a, b, c, scale, causal, window).astype(q.dtype),
            q, k, v)
        return vjp(do)
    interpret = jax.default_backend() != "tpu"
    dq, dk, dv = _flash_backward(q, k, v, o, lse, do, scale, causal,
                                 block_q, block_k, interpret,
                                 window=window)
    return _narrow_vma(dq, q), _narrow_vma(dk, k), _narrow_vma(dv, v)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_lse(q, k, v, scale, causal, block_q, block_k, window=0,
               band_offset=0):
    """Flash attention that also returns the per-row logsumexp, with
    real gradient flow through BOTH outputs. The ring-attention merge
    consumes (o, lse) pairs per visiting KV block.

    window/band_offset: static banded mask over GLOBAL positions
    (q row r sits at r + band_offset) — the windowed-ring case, where
    the visiting k block is band_offset positions earlier than the
    local q block. Defaults preserve the classic behavior exactly."""
    if _interpret_needs_fallback(q, k, v):
        return _dense_with_lse(q, k, v, scale, causal, window,
                               band_offset)
    interpret = jax.default_backend() != "tpu"
    o, lse3 = _flash_forward(q, k, v, scale, causal, block_q, block_k,
                             interpret, want_lse=True, window=window,
                             band_offset=band_offset)
    return o, lse3[..., 0]


def _flash_lse_fwd_rule(q, k, v, scale, causal, block_q, block_k,
                        window=0, band_offset=0):
    if _interpret_needs_fallback(q, k, v):
        o, lse = _dense_with_lse(q, k, v, scale, causal, window,
                                 band_offset)
        return (o, lse), (q, k, v, None, None)
    interpret = jax.default_backend() != "tpu"
    o, lse3 = _flash_forward(q, k, v, scale, causal, block_q, block_k,
                             interpret, want_lse=True, window=window,
                             band_offset=band_offset)
    lse = lse3[..., 0]
    return (o, lse), (q, k, v, o, lse)   # single-lane residual


def _flash_lse_bwd_rule(scale, causal, block_q, block_k, window,
                        band_offset, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    if lse is None:          # dense interpret-mode fallback (see above)
        _, vjp = jax.vjp(
            lambda a, b, c: _dense_with_lse(a, b, c, scale, causal,
                                            window, band_offset),
            q, k, v)
        return vjp((do, dlse))
    interpret = jax.default_backend() != "tpu"
    dq, dk, dv = _flash_backward(q, k, v, o, lse, do, scale, causal,
                                 block_q, block_k, interpret,
                                 dlse=dlse, window=window,
                                 band_offset=band_offset)
    return _narrow_vma(dq, q), _narrow_vma(dk, k), _narrow_vma(dv, v)


_flash_lse.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)


def flash_attention_with_lse(query, key, value, scale=None,
                             causal=False, block_q=512, block_k=512,
                             window=0, band_offset=0):
    """(o, lse) over (BH, T, D) inputs — both differentiable; the
    building block for ring attention's block merge. window/band_offset
    select a banded mask over global positions (see _flash_lse)."""
    if scale is None:
        scale = query.shape[-1] ** -0.5
    return _flash_lse(query, key, value, float(scale), bool(causal),
                      int(block_q), int(block_k), int(window or 0),
                      int(band_offset or 0))


def flash_attention(query, key, value, scale=None, causal=False,
                    block_q=512, block_k=512, window=None):
    """Fused attention over (B, H, T, D) or (BH, T, D) inputs.

    window: sliding-window width W (causal only): row t attends
    [t-W+1, t]. Compute AND memory become O(T*W); blocks fully outside
    the band are skipped on the grid."""
    if window and not causal:
        raise ValueError("window attention requires causal=True")
    q4 = query.ndim == 4
    if q4:
        B, H, T, D = query.shape
        query = query.reshape(B * H, T, D)
        key = key.reshape(B * H, key.shape[2], D)
        value = value.reshape(B * H, value.shape[2], D)
    if scale is None:
        scale = query.shape[-1] ** -0.5
    out = _flash(query, key, value, float(scale), bool(causal),
                 int(block_q), int(block_k), int(window or 0))
    if q4:
        out = out.reshape(B, H, T, D)
    return out


def cached_attention(query, key, value, k_cache, v_cache, pos,
                     scale=None, window=0):
    """Incremental-decode attention over a KV cache.

    query/key/value: (B, H, Tnew, hd) — projections of the Tnew tokens
    being appended (Tnew = prompt length at prefill, 1 per step after).
    k_cache/v_cache: (B, H, Tmax, hd) rolling caches. pos: (1,) int —
    number of tokens already cached; the new keys land at
    [pos, pos+Tnew) and query row r may attend cache columns <= pos+r.

    CAPACITY CONTRACT: pos + Tnew must be <= Tmax. Past it,
    dynamic_update_slice CLAMPS the start index rather than raising, so
    an overrun silently overwrites the most recent cache rows (and the
    causal mask then attends corrupted history). `Generator` guards
    this on the host; direct users of the op (get_decode_symbol /
    _contrib_CachedAttention) must enforce it themselves. Under
    `jax.disable_jit()` — this framework's NaiveEngine-style debug mode
    — pos is concrete and the op raises on violation.

    PER-ROW POSITIONS (continuous batching): pos may instead be (B,) —
    one cache position per batch row. Each row's new k/v land at its
    own offset and its causal window masks against its own position,
    which is what lets a serving slot pool hold sequences at different
    decode depths in ONE compiled step (mxnet_tpu/serve/decode.py).
    A (1,) pos keeps the shared-position fast path bit-for-bit.

    Decode is bandwidth-bound (one (Tnew, Tmax) strip per head), so
    this is a plain jnp composition — XLA fuses the mask+softmax; the
    MXU-dense training path stays with the Pallas flash kernel.
    Returns (out, new_k_cache, new_v_cache)."""
    B, H, Tn, D = query.shape
    Hkv = k_cache.shape[1]
    if H % Hkv:
        raise ValueError(
            "query heads (%d) must be a multiple of cache kv heads "
            "(%d) — grouped-query attention groups q heads over kv "
            "heads" % (H, Hkv))
    G = H // Hkv
    if scale is None:
        scale = D ** -0.5
    pos = jnp.asarray(pos)
    if pos.ndim >= 1 and pos.size > 1:
        if pos.size != B:
            raise ValueError(
                "per-row pos must have one entry per batch row: got "
                "%r for batch %d" % (pos.shape, B))
        return _cached_attention_per_row(
            query, key, value, k_cache, v_cache,
            jnp.reshape(pos, (B,)), float(scale), int(window or 0))
    p0 = jnp.reshape(pos, ()).astype(jnp.int32)
    if not isinstance(p0, jax.core.Tracer) and \
            int(p0) + Tn > k_cache.shape[2]:
        raise ValueError(
            "cached_attention overrun: pos (%d) + Tnew (%d) exceeds "
            "cache capacity Tmax=%d — dynamic_update_slice would clamp "
            "and silently corrupt the cache"
            % (int(p0), Tn, k_cache.shape[2]))
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, key.astype(k_cache.dtype), (0, 0, p0, 0))
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, value.astype(v_cache.dtype), (0, 0, p0, 0))
    # grouped einsum: q reshaped (B, Hkv, G, Tn, D) against the
    # (B, Hkv, Tmax, D) cache — each cache head is READ ONCE for its
    # whole q-head group (the GQA decode-bandwidth win; a repeat would
    # materialize G copies)
    qg = query.reshape(B, Hkv, G, Tn, D)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k_cache,
                   precision=jax.lax.Precision.DEFAULT,
                   preferred_element_type=jnp.float32) * scale
    cols = jnp.arange(k_cache.shape[2])[None, :]
    rows = jnp.arange(Tn)[:, None]
    valid = cols <= p0 + rows
    if window:
        valid = valid & (p0 + rows - cols < window)
    s = jnp.where(valid, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(v_cache.dtype),
                     v_cache,
                     precision=jax.lax.Precision.DEFAULT)
    return (out.reshape(B, H, Tn, D).astype(query.dtype),
            k_cache, v_cache)


def _cached_attention_per_row(query, key, value, k_cache, v_cache, pb,
                              scale, window):
    """cached_attention's per-row-position core: pb (B,) int — row b's
    new tokens land at [pb[b], pb[b]+Tn) and mask against pb[b]. The
    write is a vmapped dynamic_update_slice (one per-row offset each);
    same capacity contract as the scalar path, enforced per row."""
    B, H, Tn, D = query.shape
    Hkv = k_cache.shape[1]
    G = H // Hkv
    C = k_cache.shape[2]
    pb = pb.astype(jnp.int32)
    if not isinstance(pb, jax.core.Tracer):
        import numpy as _np
        worst = int(_np.asarray(pb).max())
        if worst + Tn > C:
            raise ValueError(
                "cached_attention overrun: row pos (%d) + Tnew (%d) "
                "exceeds cache capacity Tmax=%d" % (worst, Tn, C))

    def _upd(cache, new, p):
        return jax.lax.dynamic_update_slice(cache, new, (0, p, 0))

    k_cache = jax.vmap(_upd)(k_cache, key.astype(k_cache.dtype), pb)
    v_cache = jax.vmap(_upd)(v_cache, value.astype(v_cache.dtype), pb)
    qg = query.reshape(B, Hkv, G, Tn, D)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k_cache,
                   precision=jax.lax.Precision.DEFAULT,
                   preferred_element_type=jnp.float32) * scale
    cols = jnp.arange(C)[None, None, :]            # (1, 1, C)
    rows = jnp.arange(Tn)[None, :, None]           # (1, Tn, 1)
    prow = pb[:, None, None]                       # (B, 1, 1)
    valid = cols <= prow + rows                    # (B, Tn, C)
    if window:
        valid = valid & (prow + rows - cols < window)
    s = jnp.where(valid[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(v_cache.dtype),
                     v_cache,
                     precision=jax.lax.Precision.DEFAULT)
    return (out.reshape(B, H, Tn, D).astype(query.dtype),
            k_cache, v_cache)


def rope(x, positions, base=10000.0):
    """Rotary position embedding over (B, H, T, hd).

    positions: (T,) absolute position ids shared across the batch, or
    (B, T) per-row ids (the continuous-batching decode path, where
    each serving slot sits at its own depth). HALF-SPLIT pairing (GPT
    -NeoX convention): (x[i], x[i+hd/2]) rotate together by
    pos * base^(-2i/hd) — NOT the interleaved (x[2i], x[2i+1])
    RoFormer/LLaMA layout; checkpoints crossing implementations must
    repack. Relative-position attention with no learned table and
    graceful length extrapolation (RoFormer, Su et al. 2021). Applied
    to q AND k before attention; cached keys are stored rotated, so
    incremental decode needs only the new tokens' positions."""
    B, H, T, D = x.shape
    half = D // 2
    freqs = jnp.power(
        float(base), -jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[..., None] * freqs
    if ang.ndim == 2:                         # shared (T, half)
        cos = jnp.cos(ang)[None, None]        # (1, 1, T, half)
        sin = jnp.sin(ang)[None, None]
    else:                                     # per-row (B, T, half)
        cos = jnp.cos(ang)[:, None]           # (B, 1, T, half)
        sin = jnp.sin(ang)[:, None]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out = jnp.concatenate([x1 * cos - x2 * sin,
                           x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


@register("_contrib_RoPE", arg_names=("data", "positions"),
          nondiff_inputs=(1,), defaults={"base": 10000.0})
def _rope_op(data, positions, base=10000.0, **_):
    """(B, H, T, hd) rotary position embedding; positions (T,)."""
    return rope(data, positions, base=float(base))


def rolling_cached_attention(query, key, value, k_cache, v_cache, pos,
                             window, scale=None):
    """Sliding-window decode attention over a CIRCULAR cache.

    Caches have fixed capacity C = k_cache.shape[2]; position p lives
    in slot p % C, so memory stays O(C) however long generation runs
    (pair with RoPE — a learned position table would still bound
    absolute positions). Correctness needs C >= window + Tnew - 1:
    appending Tnew tokens may overwrite up to Tnew-1 older slots, and
    every new row must still find its full window (the Generator
    checks this against the prefill length).

    Masking derives each slot's ABSOLUTE position in closed form:
    after appending through pos_end, slot s holds
    p_s = pos_end - ((pos_end - s) mod C) — the newest position
    congruent to s. Valid for query row r iff 0 <= p_s <= p0+r and
    p0+r - p_s < window."""
    B, H, Tn, D = query.shape
    Hkv = k_cache.shape[1]
    if H % Hkv:
        raise ValueError(
            "query heads (%d) must be a multiple of cache kv heads "
            "(%d)" % (H, Hkv))
    G = H // Hkv
    C = k_cache.shape[2]
    if scale is None:
        scale = D ** -0.5
    p0 = jnp.reshape(pos, ()).astype(jnp.int32)
    slots = (p0 + jnp.arange(Tn)) % C
    k_cache = k_cache.at[:, :, slots].set(key.astype(k_cache.dtype))
    v_cache = v_cache.at[:, :, slots].set(value.astype(v_cache.dtype))
    qg = query.reshape(B, Hkv, G, Tn, D)    # GQA: see cached_attention
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, k_cache,
                   precision=jax.lax.Precision.DEFAULT,
                   preferred_element_type=jnp.float32) * scale
    pos_end = p0 + Tn - 1
    slot_ids = jnp.arange(C)[None, :]
    p_s = pos_end - ((pos_end - slot_ids) % C)      # (1, C)
    rows = p0 + jnp.arange(Tn)[:, None]             # (Tn, 1)
    valid = (p_s >= 0) & (p_s <= rows) & (rows - p_s < window)
    s = jnp.where(valid, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bhkd->bhgqd", p.astype(v_cache.dtype),
                     v_cache, precision=jax.lax.Precision.DEFAULT)
    return (out.reshape(B, H, Tn, D).astype(query.dtype),
            k_cache, v_cache)


@register("_contrib_RollingCachedAttention",
          arg_names=("query", "key", "value", "k_cache", "v_cache",
                     "pos"),
          state_inputs=(3, 4), nondiff_inputs=(5,),
          differentiable=False,
          defaults={"scale": None, "max_len": 0, "window": 0})
def _rolling_cached_attention_op(query, key, value, k_cache, v_cache,
                                 pos, scale=None, window=0, **_):
    """Circular-buffer twin of _contrib_CachedAttention for sliding-
    window models; max_len is the cache CAPACITY here, not a sequence
    bound."""
    if not window:
        raise ValueError("_contrib_RollingCachedAttention needs "
                         "window > 0")
    return rolling_cached_attention(query, key, value, k_cache,
                                    v_cache, pos, int(window),
                                    scale=scale)


@register("_contrib_CachedAttention",
          arg_names=("query", "key", "value", "k_cache", "v_cache",
                     "pos"),
          state_inputs=(3, 4), nondiff_inputs=(5,),
          differentiable=False,
          defaults={"scale": None, "max_len": 0, "window": 0})
def _cached_attention_op(query, key, value, k_cache, v_cache, pos,
                         scale=None, window=0, **_):
    """(B, H, Tnew, hd) decode attention; k_cache/v_cache are aux
    states updated in place (the executor threads them like BN moving
    stats — but unconditionally, since appending to the cache is the
    op's purpose at inference)."""
    return cached_attention(query, key, value, k_cache, v_cache, pos,
                            scale=scale, window=int(window or 0))


def _q8_quantize(x):
    """Per-token-per-head symmetric int8: absmax/127 scale over the
    head dim. The 1e-8 clamp stores an all-zero k/v row as zeros, not
    NaNs. Shared by the shared-position and per-row cache writers so
    both paths store BIT-IDENTICAL cache entries for the same row."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.round(xf / s[..., None]).astype(jnp.int8)
    return q, s


def cached_attention_q8(query, key, value, k_cache, v_cache, k_scale,
                        v_scale, pos, scale=None, window=0):
    """cached_attention with INT8 caches — the KV-bandwidth half of
    serving quantization (weight-only int8 covers parameters; at long
    prompts the CACHE dominates decode HBM traffic, and it is read
    every step while each weight is read once).

    k_cache/v_cache: (B, Hkv, Tmax, hd) int8. k_scale/v_scale:
    (B, Hkv, Tmax) f32 per-token-per-head absmax/127 scales — written
    once when the token's k/v enters the cache, so quantization is
    independent of later reads (a token's cache entry never changes).
    Dequantize happens tile-wise inside the einsum's operand read (an
    int8→f32 convert + scale multiply XLA fuses into the matmul loop),
    so HBM moves ~half the bytes of the bf16 cache (+1.6% for scales
    at hd=128). Scales clamp at 1e-8: an all-zero k/v row stores
    zeros, not NaNs.

    PER-ROW POSITIONS (continuous batching): like cached_attention,
    pos may be (B,) — row b's new int8 rows AND its f32 scale rows
    land at pb[b], and its causal/window mask reads against pb[b].
    This is what lets the serving slot pool run int8 caches: one
    compiled (B, 1) step whatever depths the slots sit at
    (mxnet_tpu/serve/decode.py). A (1,) pos keeps the shared-position
    path below bit-for-bit.

    Same capacity contract and GQA grouping as cached_attention.
    Returns (out, k_cache, v_cache, k_scale, v_scale)."""
    B, H, Tn, D = query.shape
    Hkv = k_cache.shape[1]
    if H % Hkv:
        raise ValueError(
            "query heads (%d) must be a multiple of cache kv heads "
            "(%d)" % (H, Hkv))
    G = H // Hkv
    if scale is None:
        scale = D ** -0.5
    pos = jnp.asarray(pos)
    if pos.ndim >= 1 and pos.size > 1:
        if pos.size != B:
            raise ValueError(
                "per-row pos must have one entry per batch row: got "
                "%r for batch %d" % (pos.shape, B))
        return _cached_attention_q8_per_row(
            query, key, value, k_cache, v_cache, k_scale, v_scale,
            jnp.reshape(pos, (B,)), float(scale), int(window or 0))
    p0 = jnp.reshape(pos, ()).astype(jnp.int32)
    if not isinstance(p0, jax.core.Tracer) and \
            int(p0) + Tn > k_cache.shape[2]:
        raise ValueError(
            "cached_attention_q8 overrun: pos (%d) + Tnew (%d) "
            "exceeds cache capacity Tmax=%d"
            % (int(p0), Tn, k_cache.shape[2]))

    kq, ks = _q8_quantize(key)
    vq, vs = _q8_quantize(value)
    k_cache = jax.lax.dynamic_update_slice(k_cache, kq, (0, 0, p0, 0))
    v_cache = jax.lax.dynamic_update_slice(v_cache, vq, (0, 0, p0, 0))
    k_scale = jax.lax.dynamic_update_slice(k_scale, ks, (0, 0, p0))
    v_scale = jax.lax.dynamic_update_slice(v_scale, vs, (0, 0, p0))

    # dequantized views — producers XLA fuses into the einsum reads
    kf = k_cache.astype(jnp.float32) * k_scale[..., None]
    vf = v_cache.astype(jnp.float32) * v_scale[..., None]
    qg = query.reshape(B, Hkv, G, Tn, D)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg.astype(jnp.float32), kf,
                   precision=jax.lax.Precision.DEFAULT,
                   preferred_element_type=jnp.float32) * scale
    cols = jnp.arange(k_cache.shape[2])[None, :]
    rows = jnp.arange(Tn)[:, None]
    valid = cols <= p0 + rows
    if window:
        valid = valid & (p0 + rows - cols < window)
    s = jnp.where(valid, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bhkd->bhgqd", p, vf,
                     precision=jax.lax.Precision.DEFAULT)
    return (out.reshape(B, H, Tn, D).astype(query.dtype),
            k_cache, v_cache, k_scale, v_scale)


def _cached_attention_q8_per_row(query, key, value, k_cache, v_cache,
                                 k_scale, v_scale, pb, scale, window):
    """cached_attention_q8's per-row-position core: the int8 k/v rows
    AND their per-token f32 scale rows scatter at each row's own
    offset (vmapped dynamic_update_slice — one per-row start index
    each), and each row masks against its own position. Quantization
    is _q8_quantize, the exact shared-path rule, so the stored cache
    entry for a row is independent of which path wrote it. Same
    capacity contract as the scalar path, enforced per row."""
    B, H, Tn, D = query.shape
    Hkv = k_cache.shape[1]
    G = H // Hkv
    C = k_cache.shape[2]
    pb = pb.astype(jnp.int32)
    if not isinstance(pb, jax.core.Tracer):
        import numpy as _np
        worst = int(_np.asarray(pb).max())
        if worst + Tn > C:
            raise ValueError(
                "cached_attention_q8 overrun: row pos (%d) + Tnew "
                "(%d) exceeds cache capacity Tmax=%d" % (worst, Tn, C))

    kq, ks = _q8_quantize(key)       # (B, Hkv, Tn, D), (B, Hkv, Tn)
    vq, vs = _q8_quantize(value)

    def _upd(cache, new, p):
        return jax.lax.dynamic_update_slice(cache, new, (0, p, 0))

    def _upd_scale(cache, new, p):
        return jax.lax.dynamic_update_slice(cache, new, (0, p))

    k_cache = jax.vmap(_upd)(k_cache, kq, pb)
    v_cache = jax.vmap(_upd)(v_cache, vq, pb)
    k_scale = jax.vmap(_upd_scale)(k_scale, ks, pb)
    v_scale = jax.vmap(_upd_scale)(v_scale, vs, pb)

    # dequantized views — producers XLA fuses into the einsum reads,
    # same formulation as the shared-position path
    kf = k_cache.astype(jnp.float32) * k_scale[..., None]
    vf = v_cache.astype(jnp.float32) * v_scale[..., None]
    qg = query.reshape(B, Hkv, G, Tn, D)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg.astype(jnp.float32), kf,
                   precision=jax.lax.Precision.DEFAULT,
                   preferred_element_type=jnp.float32) * scale
    cols = jnp.arange(C)[None, None, :]            # (1, 1, C)
    rows = jnp.arange(Tn)[None, :, None]           # (1, Tn, 1)
    prow = pb[:, None, None]                       # (B, 1, 1)
    valid = cols <= prow + rows                    # (B, Tn, C)
    if window:
        valid = valid & (prow + rows - cols < window)
    s = jnp.where(valid[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhgqk,bhkd->bhgqd", p, vf,
                     precision=jax.lax.Precision.DEFAULT)
    return (out.reshape(B, H, Tn, D).astype(query.dtype),
            k_cache, v_cache, k_scale, v_scale)


@register("_contrib_CachedAttentionQ8",
          arg_names=("query", "key", "value", "k_cache", "v_cache",
                     "k_scale", "v_scale", "pos"),
          state_inputs=(3, 4, 5, 6), nondiff_inputs=(7,),
          differentiable=False,
          defaults={"scale": None, "max_len": 0, "window": 0})
def _cached_attention_q8_op(query, key, value, k_cache, v_cache,
                            k_scale, v_scale, pos, scale=None,
                            window=0, **_):
    """Int8-cache decode attention; caches AND their per-token scales
    are aux states threaded by the executor."""
    return cached_attention_q8(query, key, value, k_cache, v_cache,
                               k_scale, v_scale, pos, scale=scale,
                               window=int(window or 0))


@register("_contrib_FlashAttention",
          arg_names=("query", "key", "value"),
          aliases=("_contrib_flash_attention",),
          defaults={"scale": None, "causal": False, "block_q": 512,
                    "block_k": 512, "seq_axis": None, "window": 0})
def _flash_attention_op(query, key, value, scale=None, causal=False,
                        block_q=512, block_k=512, seq_axis=None,
                        window=0, **_):
    """(B, H, T, D) fused attention; returns same shape.

    seq_axis: name of a mesh axis to sequence-parallelize over. When the
    surrounding graph is lowered over a mesh carrying that axis (>1
    devices), the op runs RING attention — q stays put, k/v blocks
    rotate via ppermute, each device holds T/n of the sequence
    (parallel/ring.py; the symbol-level long-context path). Otherwise
    (eager, no mesh, or axis absent/size-1) it is the single-chip
    Pallas flash kernel. Inputs must be 4-D (B, H, T, D) for the ring
    path.

    Grouped-query attention: k/v may carry FEWER heads than q (Hkv
    dividing H); they are broadcast to the q-head count here, before
    the kernel. Training compute is MXU-bound so the repeat costs
    little; the GQA win is the decode cache (cached_attention keeps
    Hkv heads and never materializes the repeat)."""
    if query.ndim == 4 and key.shape[1] != query.shape[1]:
        H, Hkv = query.shape[1], key.shape[1]
        if H % Hkv:
            raise ValueError("query heads (%d) must be a multiple of "
                             "kv heads (%d)" % (H, Hkv))
        key = jnp.repeat(key, H // Hkv, axis=1)
        value = jnp.repeat(value, H // Hkv, axis=1)
    if seq_axis:
        from ._mesh_ctx import active_mesh_axis
        mesh = active_mesh_axis(seq_axis)
        if mesh is not None:
            if query.ndim != 4:
                raise ValueError(
                    "seq_axis ring attention needs (B, H, T, D) inputs, "
                    "got ndim=%d" % query.ndim)
            from ..parallel.ring import ring_attention
            return ring_attention(query, key, value, mesh, seq_axis,
                                  causal=bool(causal), scale=scale,
                                  window=int(window or 0))
    return flash_attention(query, key, value, scale=scale, causal=causal,
                           block_q=block_q, block_k=block_k,
                           window=int(window or 0) or None)
