"""Elementwise unary/binary/scalar/logic ops.

Reference: src/operator/tensor/elemwise_* + mshadow_op.h functor zoo
(SURVEY.md N11). One pure-jnp fn per op; XLA fuses chains of these into
single HBM-bandwidth-bound kernels, which is the TPU replacement for
mshadow expression templates.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register


def _u(name, fn, aliases=(), differentiable=True):
    @register(name, arg_names=("data",), aliases=aliases,
              differentiable=differentiable, doc="elementwise %s" % name)
    def _f(x, **_):
        return fn(x)
    return _f


# -- unary math (Appendix A list) -------------------------------------------
_u("abs", jnp.abs)
_u("sign", jnp.sign)
_u("negative", jnp.negative)
_u("reciprocal", lambda x: 1.0 / x)
_u("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_u("cbrt", jnp.cbrt)
_u("sqrt", jnp.sqrt)
_u("rsqrt", lambda x: lax.rsqrt(x))
_u("square", jnp.square)
_u("exp", jnp.exp)
_u("expm1", jnp.expm1)
_u("log", jnp.log)
_u("log10", jnp.log10)
_u("log1p", jnp.log1p)
_u("log2", jnp.log2)
_u("sin", jnp.sin)
_u("cos", jnp.cos)
_u("tan", jnp.tan)
_u("sinh", jnp.sinh)
_u("cosh", jnp.cosh)
_u("tanh", jnp.tanh)
_u("arcsin", jnp.arcsin)
_u("arccos", jnp.arccos)
_u("arctan", jnp.arctan)
_u("arcsinh", jnp.arcsinh)
_u("arccosh", jnp.arccosh)
_u("arctanh", jnp.arctanh)
_u("degrees", jnp.degrees)
_u("radians", jnp.radians)
_u("gamma", lambda x: jnp.exp(lax.lgamma(x)))
_u("gammaln", lambda x: lax.lgamma(x))
_u("relu", lambda x: jnp.maximum(x, 0))
_u("sigmoid", lambda x: jnp.where(x >= 0, 1.0 / (1.0 + jnp.exp(-x)),
                                  jnp.exp(x) / (1.0 + jnp.exp(x))))
_u("softsign", lambda x: x / (1.0 + jnp.abs(x)))
_u("ceil", jnp.ceil, differentiable=False)
_u("floor", jnp.floor, differentiable=False)
_u("rint", jnp.rint, differentiable=False)
_u("round", jnp.round, differentiable=False)
_u("fix", jnp.trunc, differentiable=False)
_u("trunc", jnp.trunc, differentiable=False)
_u("erf", lax.erf)
_u("logical_not", lambda x: (x == 0).astype(x.dtype), differentiable=False)


@register("_copy", arg_names=("data",), aliases=("identity",))
def _copy(x, **_):
    return x


@register("BlockGrad", arg_names=("data",), aliases=("stop_gradient",))
def _block_grad(x, **_):
    return lax.stop_gradient(x)


@register("make_loss", arg_names=("data",))
def _make_loss_t(x, **_):
    return x


@register("_identity_with_attr_like_rhs", arg_names=("lhs", "rhs"),
          nondiff_inputs=(1,))
def _identity_like_rhs(lhs, rhs, **_):
    return lhs


@register("Cast", arg_names=("data",), aliases=("cast",))
def _cast(x, dtype="float32", **_):
    from ..base import np_dtype
    return x.astype(np_dtype(dtype))


# -- binary broadcasting -----------------------------------------------------

def _b(name, fn, aliases=(), differentiable=True):
    @register(name, arg_names=("lhs", "rhs"), aliases=aliases,
              differentiable=differentiable, doc="broadcasting %s" % name)
    def _f(lhs, rhs, **_):
        return fn(lhs, rhs)
    return _f


_b("broadcast_add", jnp.add, aliases=("broadcast_plus", "elemwise_add",
                                      "_plus", "_Plus"))
_b("broadcast_sub", jnp.subtract, aliases=("broadcast_minus", "elemwise_sub",
                                           "_minus", "_Minus", "_sub"))
_b("broadcast_mul", jnp.multiply, aliases=("elemwise_mul", "_mul", "_Mul"))
_b("broadcast_div", jnp.divide, aliases=("elemwise_div", "_div", "_Div"))
_b("broadcast_mod", lambda l, r: jnp.where(r != 0, jnp.fmod(l, r), 0),
   aliases=("_mod",))
_b("broadcast_power", jnp.power, aliases=("_power", "_Power", "pow"))
_b("broadcast_maximum", jnp.maximum, aliases=("_maximum", "_Maximum",
                                              "maximum"))
_b("broadcast_minimum", jnp.minimum, aliases=("_minimum", "_Minimum",
                                              "minimum"))
# plain `hypot` is a same-shape elemwise op in the reference
# (src/operator/tensor/elemwise_binary_op.cc); the broadcasting form is
# a strict superset, so it aliases here like maximum/minimum do
_b("broadcast_hypot", jnp.hypot, aliases=("_hypot", "hypot"))
_b("_grad_add", jnp.add)

# public names (mx.nd.equal & co) match the reference's registrations in
# src/operator/tensor/elemwise_binary_broadcast_op_logic.cc
_b("broadcast_equal", lambda l, r: (l == r).astype(l.dtype),
   aliases=("_equal", "equal"), differentiable=False)
_b("broadcast_not_equal", lambda l, r: (l != r).astype(l.dtype),
   aliases=("_not_equal", "not_equal"), differentiable=False)
_b("broadcast_greater", lambda l, r: (l > r).astype(l.dtype),
   aliases=("_greater", "greater"), differentiable=False)
_b("broadcast_greater_equal", lambda l, r: (l >= r).astype(l.dtype),
   aliases=("_greater_equal", "greater_equal"), differentiable=False)
_b("broadcast_lesser", lambda l, r: (l < r).astype(l.dtype),
   aliases=("_lesser", "lesser"), differentiable=False)
_b("broadcast_lesser_equal", lambda l, r: (l <= r).astype(l.dtype),
   aliases=("_lesser_equal", "lesser_equal"), differentiable=False)
_b("broadcast_logical_and", lambda l, r: ((l != 0) & (r != 0)).astype(l.dtype),
   differentiable=False)
_b("broadcast_logical_or", lambda l, r: ((l != 0) | (r != 0)).astype(l.dtype),
   differentiable=False)
_b("broadcast_logical_xor", lambda l, r: ((l != 0) ^ (r != 0)).astype(l.dtype),
   differentiable=False)


@register("add_n", aliases=("ElementWiseSum", "_sum"), arg_names=None)
def _add_n(*args, **_):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


# -- scalar ops --------------------------------------------------------------

def _s(name, fn, aliases=(), differentiable=True):
    @register(name, arg_names=("data",), aliases=aliases,
              differentiable=differentiable, defaults={"scalar": 0.0})
    def _f(x, scalar=0.0, **_):
        s = jnp.asarray(scalar, x.dtype) if jnp.issubdtype(
            jnp.asarray(x).dtype, jnp.number) else scalar
        return fn(x, s)
    return _f


_s("_plus_scalar", jnp.add, aliases=("_PlusScalar",))
_s("_minus_scalar", jnp.subtract, aliases=("_MinusScalar",))
_s("_rminus_scalar", lambda x, s: s - x, aliases=("_RMinusScalar",))
_s("_mul_scalar", jnp.multiply, aliases=("_MulScalar",))
_s("_div_scalar", jnp.divide, aliases=("_DivScalar",))
_s("_rdiv_scalar", lambda x, s: s / x, aliases=("_RDivScalar",))
_s("_mod_scalar", jnp.fmod, aliases=("_ModScalar",))
_s("_rmod_scalar", lambda x, s: jnp.fmod(s, x), aliases=("_RModScalar",))
_s("_power_scalar", jnp.power, aliases=("_PowerScalar",))
_s("_rpower_scalar", lambda x, s: jnp.power(s, x), aliases=("_RPowerScalar",))
_s("_maximum_scalar", jnp.maximum, aliases=("_MaximumScalar",))
_s("_minimum_scalar", jnp.minimum, aliases=("_MinimumScalar",))
_s("_hypot_scalar", jnp.hypot, aliases=("_HypotScalar",))
_s("_equal_scalar", lambda x, s: (x == s).astype(x.dtype),
   differentiable=False)
_s("_not_equal_scalar", lambda x, s: (x != s).astype(x.dtype),
   differentiable=False)
_s("_greater_scalar", lambda x, s: (x > s).astype(x.dtype),
   differentiable=False)
_s("_greater_equal_scalar", lambda x, s: (x >= s).astype(x.dtype),
   differentiable=False)
_s("_lesser_scalar", lambda x, s: (x < s).astype(x.dtype),
   differentiable=False)
_s("_lesser_equal_scalar", lambda x, s: (x <= s).astype(x.dtype),
   differentiable=False)


@register("clip", arg_names=("data",),
          defaults={"a_min": 0.0, "a_max": 1.0})
def _clip(x, a_min=0.0, a_max=1.0, **_):
    return jnp.clip(x, a_min, a_max)


@register("smooth_l1", arg_names=("data",), defaults={"scalar": 1.0})
def _smooth_l1(x, scalar=1.0, **_):
    s2 = scalar * scalar
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * x * x, absx - 0.5 / s2)
