"""Operator catalog (SURVEY.md §2 N9/N11/N12, Appendix A).

Every module registers pure-JAX ops into the shared registry; importing this
package populates the full catalog, from which ``mx.nd.*`` and ``mx.sym.*``
namespaces are generated.
"""
from . import registry
from .registry import get_op, list_ops, register

from . import elemwise      # noqa: F401
from . import reduce_ops    # noqa: F401
from . import matrix        # noqa: F401
from . import indexing      # noqa: F401
from . import init_ops      # noqa: F401
from . import nn            # noqa: F401
from . import loss          # noqa: F401
from . import random_ops    # noqa: F401
from . import linalg        # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import ctc           # noqa: F401
from . import rnn_op        # noqa: F401
from . import detection_ops  # noqa: F401
from . import warp_ops      # noqa: F401
from . import contrib_ops   # noqa: F401
from . import rcnn_ops      # noqa: F401
from . import attention     # noqa: F401
from . import ssm           # noqa: F401
from . import custom        # noqa: F401
from . import shape_hooks   # noqa: F401  (must come after all registrations)
