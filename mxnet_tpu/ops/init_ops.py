"""Creation ops (no tensor inputs): _zeros, _ones, _arange, *_like.

Reference: src/operator/tensor/init_op.* (SURVEY.md N11).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import np_dtype
from .registry import register


@register("_zeros", arg_names=(), differentiable=False,
          defaults={"shape": (), "dtype": "float32", "ctx": None})
def _zeros(shape=(), dtype="float32", **_):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return jnp.zeros(shape, np_dtype(dtype))


@register("_ones", arg_names=(), differentiable=False,
          defaults={"shape": (), "dtype": "float32", "ctx": None})
def _ones(shape=(), dtype="float32", **_):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return jnp.ones(shape, np_dtype(dtype))


@register("_full", arg_names=(), differentiable=False,
          defaults={"shape": (), "dtype": "float32", "value": 0.0,
                    "ctx": None})
def _full(shape=(), dtype="float32", value=0.0, **_):
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    return jnp.full(shape, value, np_dtype(dtype))


@register("_arange", arg_names=(), differentiable=False,
          defaults={"start": 0.0, "stop": None, "step": 1.0, "repeat": 1,
                    "dtype": "float32", "ctx": None})
def _arange(start=0.0, stop=None, step=1.0, repeat=1, dtype="float32", **_):
    out = jnp.arange(start, stop, step, dtype=np_dtype(dtype))
    if repeat > 1:
        out = jnp.repeat(out, repeat)
    return out


@register("zeros_like", arg_names=("data",), differentiable=False)
def _zeros_like(x, **_):
    return jnp.zeros_like(x)


@register("ones_like", arg_names=("data",), differentiable=False)
def _ones_like(x, **_):
    return jnp.ones_like(x)


@register("_eye", arg_names=(), differentiable=False,
          defaults={"N": 0, "M": 0, "k": 0, "dtype": "float32", "ctx": None})
def _eye(N=0, M=0, k=0, dtype="float32", **_):
    return jnp.eye(N, M or None, k=k, dtype=np_dtype(dtype))
