"""Reductions + broadcasting helpers + softmax family.

Reference: src/operator/tensor/broadcast_reduce_op.* (SURVEY.md N11).
MXNet reduce semantics: ``axis=None`` reduces everything to shape (1,);
``keepdims`` keeps reduced dims; ``exclude`` inverts the axis set.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax, nn as jnn

from .registry import register


def _axes(x, axis, exclude=False):
    if axis is None or axis == ():
        axes = tuple(range(x.ndim))
    elif isinstance(axis, int):
        axes = (axis % x.ndim,)
    else:
        axes = tuple(a % x.ndim for a in axis)
    if exclude:
        axes = tuple(a for a in range(x.ndim) if a not in axes)
    return axes


def _reduce(name, fn, differentiable=True, aliases=()):
    @register(name, arg_names=("data",), differentiable=differentiable,
              aliases=aliases,
              defaults={"axis": None, "keepdims": False, "exclude": False})
    def _f(x, axis=None, keepdims=False, exclude=False, **_):
        axes = _axes(x, axis, exclude)
        out = fn(x, axes, keepdims)
        if axis is None and not keepdims:
            out = out.reshape((1,)) if out.ndim == 0 else out
        return out
    return _f


_reduce("sum", lambda x, a, k: jnp.sum(x, axis=a, keepdims=k),
        aliases=("sum_axis",))
_reduce("mean", lambda x, a, k: jnp.mean(x, axis=a, keepdims=k))
_reduce("prod", lambda x, a, k: jnp.prod(x, axis=a, keepdims=k))
_reduce("nansum", lambda x, a, k: jnp.nansum(x, axis=a, keepdims=k))
_reduce("nanprod", lambda x, a, k: jnp.nanprod(x, axis=a, keepdims=k))
_reduce("max", lambda x, a, k: jnp.max(x, axis=a, keepdims=k),
        aliases=("max_axis",))
_reduce("min", lambda x, a, k: jnp.min(x, axis=a, keepdims=k),
        aliases=("min_axis",))


@register("argmax", arg_names=("data",), differentiable=False,
          defaults={"axis": None, "keepdims": False})
def _argmax(x, axis=None, keepdims=False, **_):
    out = jnp.argmax(x.reshape(-1) if axis is None else x, axis=0 if axis is None else axis)
    out = out.astype(jnp.float32)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out


@register("argmin", arg_names=("data",), differentiable=False,
          defaults={"axis": None, "keepdims": False})
def _argmin(x, axis=None, keepdims=False, **_):
    out = jnp.argmin(x.reshape(-1) if axis is None else x, axis=0 if axis is None else axis)
    out = out.astype(jnp.float32)
    if keepdims and axis is not None:
        out = jnp.expand_dims(out, axis)
    return out


@register("argmax_channel", arg_names=("data",), differentiable=False)
def _argmax_channel(x, **_):
    return jnp.argmax(x, axis=-1).astype(jnp.float32)


@register("norm", arg_names=("data",),
          defaults={"ord": 2, "axis": None, "keepdims": False})
def _norm(x, ord=2, axis=None, keepdims=False, **_):
    if axis is None:
        out = jnp.sqrt(jnp.sum(jnp.square(x)))
        return out.reshape((1,))
    return jnp.linalg.norm(x, ord=ord, axis=axis, keepdims=keepdims)


@register("L2Normalization", arg_names=("data",),
          defaults={"eps": 1e-10, "mode": "instance"})
def _l2norm(x, eps=1e-10, mode="instance", **_):
    if mode == "instance":
        n = jnp.sqrt(jnp.sum(jnp.square(x.reshape(x.shape[0], -1)),
                             axis=1) + eps)
        return x / n.reshape((-1,) + (1,) * (x.ndim - 1))
    if mode == "channel":
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + eps)
        return x / n
    if mode == "spatial":
        axes = tuple(range(2, x.ndim))
        n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
        return x / n
    raise ValueError("unknown mode %r" % mode)


@register("broadcast_axis", arg_names=("data",), aliases=("broadcast_axes",),
          defaults={"axis": (), "size": ()})
def _broadcast_axis(x, axis=(), size=(), **_):
    axis = (axis,) if isinstance(axis, int) else tuple(axis)
    size = (size,) if isinstance(size, int) else tuple(size)
    shape = list(x.shape)
    for a, s in zip(axis, size):
        shape[a] = s
    return jnp.broadcast_to(x, tuple(shape))


@register("broadcast_to", arg_names=("data",), defaults={"shape": ()})
def _broadcast_to(x, shape=(), **_):
    tgt = tuple(s if s != 0 else x.shape[i] for i, s in enumerate(shape))
    return jnp.broadcast_to(x, tgt)


@register("broadcast_like", arg_names=("lhs", "rhs"), nondiff_inputs=(1,))
def _broadcast_like(lhs, rhs, **_):
    return jnp.broadcast_to(lhs, rhs.shape)


# -- softmax family ----------------------------------------------------------

@register("softmax", arg_names=("data",),
          defaults={"axis": -1, "temperature": None})
def _softmax(x, axis=-1, temperature=None, **_):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jnn.softmax(x, axis=axis)


@register("log_softmax", arg_names=("data",),
          defaults={"axis": -1, "temperature": None})
def _log_softmax(x, axis=-1, temperature=None, **_):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return jnn.log_softmax(x, axis=axis)


@register("softmax_cross_entropy", arg_names=("data", "label"),
          nondiff_inputs=(1,))
def _softmax_xent(data, label, **_):
    logp = jnn.log_softmax(data, axis=-1)
    idx = label.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, idx[:, None], axis=-1)
    return -jnp.sum(picked).reshape((1,))
