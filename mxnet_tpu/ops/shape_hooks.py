"""Symbolic-composition hooks: which tensor args an op exposes under given
attrs, and backward shape inference for parameter variables.

Reference parity: OperatorProperty::ListArguments (e.g. `no_bias` removes
"bias" — src/operator/fully_connected-inl.h) and InferShape's backward
direction (weight shapes derived from data shape), which is what lets
``Symbol.simple_bind`` allocate parameters from just the data shape.
"""
from __future__ import annotations

from .registry import set_arg_select, set_param_shapes


def _prod(xs):
    out = 1
    for x in xs:
        out *= int(x)
    return out


def _pair(v, n):
    if isinstance(v, (tuple, list)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


# -- FullyConnected ---------------------------------------------------------

set_arg_select("FullyConnected", lambda a: (
    ("data", "weight") if a.get("no_bias") else ("data", "weight", "bias")))


def _fc_shapes(shapes, attrs):
    data = shapes[0]
    nh = int(attrs.get("num_hidden", 0))
    if data is None:
        return shapes
    in_dim = _prod(data[1:]) if attrs.get("flatten", True) else data[-1]
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (nh, in_dim)
    if len(out) > 2 and out[2] is None:
        out[2] = (nh,)
    return out


set_param_shapes("FullyConnected", _fc_shapes)


# -- Convolution / Deconvolution -------------------------------------------

set_arg_select("Convolution", lambda a: (
    ("data", "weight") if a.get("no_bias") else ("data", "weight", "bias")))
set_arg_select("Deconvolution", lambda a: (
    ("data", "weight") if a.get("no_bias", True)
    else ("data", "weight", "bias")))


def _conv_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return shapes
    kernel = tuple(int(k) for k in attrs.get("kernel", ()))
    nf = int(attrs.get("num_filter", 0))
    ng = int(attrs.get("num_group", 1))
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (nf, data[1] // ng) + kernel
    if len(out) > 2 and out[2] is None:
        out[2] = (nf,)
    return out


set_param_shapes("Convolution", _conv_shapes)


def _deconv_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return shapes
    kernel = tuple(int(k) for k in attrs.get("kernel", ()))
    nf = int(attrs.get("num_filter", 0))
    ng = int(attrs.get("num_group", 1))
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        # reference layout: (in_channels, num_filter/g, kh, kw)
        out[1] = (data[1], nf // ng) + kernel
    if len(out) > 2 and out[2] is None:
        out[2] = (nf,)
    return out


set_param_shapes("Deconvolution", _deconv_shapes)


# -- Norm layers ------------------------------------------------------------

def _bn_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return shapes
    axis = int(attrs.get("axis", 1)) % len(data)
    c = (data[axis],)
    return [data] + [c if s is None else s for s in shapes[1:]]


set_param_shapes("BatchNorm", _bn_shapes)
set_param_shapes("InstanceNorm", _bn_shapes)


def _ln_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return shapes
    axis = int(attrs.get("axis", -1)) % len(data)
    c = (data[axis],)
    return [data] + [c if s is None else s for s in shapes[1:]]


set_param_shapes("LayerNorm", _ln_shapes)


# -- Embedding --------------------------------------------------------------

def _embedding_shapes(shapes, attrs):
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (int(attrs.get("input_dim", 0)),
                  int(attrs.get("output_dim", 0)))
    return out


set_param_shapes("Embedding", _embedding_shapes)


# -- LeakyReLU (gamma only for prelu) ---------------------------------------

set_arg_select("LeakyReLU", lambda a: (
    ("data", "gamma") if a.get("act_type") == "prelu" else ("data",)))


def _prelu_shapes(shapes, attrs):
    data = shapes[0]
    out = list(shapes)
    if len(out) > 1 and out[1] is None and data is not None:
        out[1] = (data[1] if len(data) > 1 else 1,)
    return out


set_param_shapes("LeakyReLU", _prelu_shapes)


# -- DeformableConvolution: weight/bias from data like Convolution ----------

set_arg_select("_contrib_DeformableConvolution", lambda a: (
    ("data", "offset", "weight") if a.get("no_bias")
    else ("data", "offset", "weight", "bias")))


def _deform_conv_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return shapes
    kernel = tuple(int(k) for k in attrs.get("kernel", ()))
    nf = int(attrs.get("num_filter", 0))
    ng = int(attrs.get("num_group", 1))
    out = list(shapes)
    if len(out) > 2 and out[2] is None:
        out[2] = (nf, data[1] // ng) + kernel
    if len(out) > 3 and out[3] is None:
        out[3] = (nf,)
    return out


set_param_shapes("_contrib_DeformableConvolution", _deform_conv_shapes)

set_arg_select("_contrib_DeformablePSROIPooling", lambda a: (
    ("data", "rois") if a.get("no_trans")
    else ("data", "rois", "trans")))


# -- RNN (fused): parameters blob + state shapes from data ------------------
# (reference: rnn-inl.h RNNProp::InferShape — param size is a function of
# input size, state size, layers, directions)

set_arg_select("RNN", lambda a: (
    ("data", "parameters", "state", "state_cell")
    if a.get("mode", "lstm") == "lstm"
    else ("data", "parameters", "state")))


def _rnn_shapes(shapes, attrs):
    data = shapes[0]
    if data is None:
        return shapes
    from .rnn_op import rnn_param_size
    mode = attrs.get("mode", "lstm")
    h = int(attrs.get("state_size", 0))
    layers = int(attrs.get("num_layers", 1))
    dirs = 2 if attrs.get("bidirectional") else 1
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = (rnn_param_size(mode, int(data[2]), h, layers,
                                 attrs.get("bidirectional", False)),)
    state_shape = (layers * dirs, int(data[1]), h)
    for i in (2, 3):
        if len(out) > i and out[i] is None:
            out[i] = state_shape
    return out


set_param_shapes("RNN", _rnn_shapes)


# -- Sequence ops: sequence_length only when enabled ------------------------

for _name in ("SequenceMask", "SequenceLast", "SequenceReverse"):
    set_arg_select(_name, lambda a: (
        ("data", "sequence_length") if a.get("use_sequence_length")
        else ("data",)))


# -- output/loss ops: label shape from data shape ---------------------------
# (reference: SoftmaxOutputProp::InferShape — label = data shape minus the
# class axis; regression outputs use label with data's shape)

def _softmax_label_shapes(shapes, attrs):
    data = shapes[0]
    out = list(shapes)
    if data is not None and len(out) > 1 and out[1] is None:
        if attrs.get("multi_output"):
            out[1] = (data[0],) + tuple(data[2:])
        elif attrs.get("preserve_shape"):
            out[1] = tuple(data[:-1])
        else:
            out[1] = (data[0],) if len(data) <= 2 else tuple(data[:-1])
    return out


set_param_shapes("SoftmaxOutput", _softmax_label_shapes)
set_param_shapes("SVMOutput", _softmax_label_shapes)


def _regression_label_shapes(shapes, attrs):
    data = shapes[0]
    out = list(shapes)
    if data is not None and len(out) > 1 and out[1] is None:
        out[1] = tuple(data)
    return out


for _name in ("LinearRegressionOutput", "MAERegressionOutput",
              "LogisticRegressionOutput"):
    set_param_shapes(_name, _regression_label_shapes)


# -- CachedAttention (decode KV caches sized by the max_len attr) -----------

def _cached_attention_shapes(shapes, attrs):
    q = shapes[0]
    k = shapes[1] if len(shapes) > 1 else None
    out = list(shapes)
    tmax = int(attrs.get("max_len", 0))
    if q is not None and tmax:
        # cache head count follows the KEY projection, not the query —
        # under grouped-query attention Hkv < H and the cache stores
        # only the kv heads
        heads = k[1] if k is not None else q[1]
        cache = (q[0], heads, tmax, q[3])
        if len(out) > 3 and out[3] is None:
            out[3] = cache
        if len(out) > 4 and out[4] is None:
            out[4] = cache
    if len(out) > 5 and out[5] is None:
        out[5] = (1,)
    return out


set_param_shapes("_contrib_CachedAttention", _cached_attention_shapes)


# -- QuantizedFullyConnected ------------------------------------------------

set_arg_select("_contrib_QuantizedFullyConnected", lambda a: (
    ("data", "weight", "scale") if str(a.get("no_bias", False)) in
    ("True", "true", "1") else ("data", "weight", "scale", "bias")))


def _quant_fc_shapes(shapes, attrs):
    # data/weight/bias follow FullyConnected's rule; the extra scale
    # slot (index 2) is (num_hidden,)
    fc = _fc_shapes([shapes[0], shapes[1],
                     shapes[3] if len(shapes) > 3 else None], attrs)
    out = list(shapes)
    if len(out) > 1 and out[1] is None:
        out[1] = fc[1]
    if len(out) > 2 and out[2] is None and int(attrs.get(
            "num_hidden", 0)):
        out[2] = (int(attrs["num_hidden"]),)
    if len(out) > 3 and out[3] is None and len(fc) > 2:
        out[3] = fc[2]
    return out


set_param_shapes("_contrib_QuantizedFullyConnected", _quant_fc_shapes)


def _quant_embedding_shapes(shapes, attrs):
    out = list(shapes)
    vd = (int(attrs.get("input_dim", 0)), int(attrs.get("output_dim",
                                                        0)))
    if len(out) > 1 and out[1] is None:
        out[1] = vd
    if len(out) > 2 and out[2] is None:
        out[2] = (vd[0],)
    return out


set_param_shapes("_contrib_QuantizedEmbedding", _quant_embedding_shapes)


set_param_shapes("_contrib_RollingCachedAttention",
                 _cached_attention_shapes)


def _cached_attention_q8_shapes(shapes, attrs):
    """Int8 variant: slots 3/4 are the int8 caches, 5/6 the per-token
    (B, Hkv, Tmax) scale caches, 7 the pos scalar. NOTE on dtypes:
    infer_type's same-dtype propagation cannot express the int8/f32
    aux split — Generator._fresh_aux (the supported allocator for this
    op) creates them by suffix; Executor-bound users must supply aux
    explicitly."""
    q = shapes[0]
    k = shapes[1] if len(shapes) > 1 else None
    out = list(shapes)
    tmax = int(attrs.get("max_len", 0))
    if q is not None and tmax:
        heads = k[1] if k is not None else q[1]
        cache = (q[0], heads, tmax, q[3])
        for i in (3, 4):
            if len(out) > i and out[i] is None:
                out[i] = cache
        for i in (5, 6):
            if len(out) > i and out[i] is None:
                out[i] = cache[:3]
    if len(out) > 7 and out[7] is None:
        out[7] = (1,)
    return out


set_param_shapes("_contrib_CachedAttentionQ8",
                 _cached_attention_q8_shapes)


# -- SSMCached (O(1) decode state — a fixed blob, no length axis) -----------

def _ssm_cached_shapes(shapes, attrs):
    """Slot 4 is the (B, H, hd, hd) recurrent state — sized entirely
    from the query projection; max_len never appears (THE point of the
    op). Slot 5 is the pos scalar, accepted for cached-attention attr
    parity and ignored by the op."""
    q = shapes[0]
    out = list(shapes)
    if q is not None and len(out) > 4 and out[4] is None:
        out[4] = (q[0], q[1], q[3], q[3])
    if len(out) > 5 and out[5] is None:
        out[5] = (1,)
    return out


set_param_shapes("_contrib_SSMCached", _ssm_cached_shapes)
