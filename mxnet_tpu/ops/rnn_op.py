"""Fused multi-layer RNN op — the TPU replacement for the reference's
cuDNN-only RNN operator (src/operator/rnn-inl.h:124; CPU path fatals,
src/operator/rnn.cc:32 — here every backend works).

Design: one `lax.scan` per layer/direction — the XLA-native fused
recurrence (compiler unrolls + pipelines the gate matmuls onto the MXU;
weights stay resident in HBM across steps). Parameter blob layout matches
the reference's cuDNN packing so FusedRNNCell pack/unpack and trained
checkpoints are interchangeable:

  all weights (layer-major, direction-inner): W_i2h(G*H, in), W_h2h(G*H, H)
  then all biases: b_i2h(G*H), b_h2h(G*H)

Gate order: lstm [i, f, c, o], gru [r, z, n] (cuDNN order, equal to the
unfused cells')."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _layer_param_sizes(mode, input_size, state_size, num_layers,
                       bidirectional):
    """Per-(layer, direction) weight/bias sizes in blob order."""
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    sizes = []
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * dirs
        for _d in range(dirs):
            sizes.append(("w_i2h", gates * state_size * isz,
                          (gates * state_size, isz)))
            sizes.append(("w_h2h", gates * state_size * state_size,
                          (gates * state_size, state_size)))
    for layer in range(num_layers):
        for _d in range(dirs):
            sizes.append(("b_i2h", gates * state_size,
                          (gates * state_size,)))
            sizes.append(("b_h2h", gates * state_size,
                          (gates * state_size,)))
    return sizes


def rnn_param_size(mode, input_size, state_size, num_layers,
                   bidirectional):
    """Total packed parameter count (FusedRNNCell needs this)."""
    return sum(s for _, s, _ in _layer_param_sizes(
        mode, input_size, state_size, num_layers, bidirectional))


def _unpack_params(params, mode, input_size, state_size, num_layers,
                   bidirectional):
    """Split flat blob into {(layer, dir): dict of arrays}."""
    sizes = _layer_param_sizes(mode, input_size, state_size, num_layers,
                               bidirectional)
    dirs = 2 if bidirectional else 1
    out = {}
    pos = 0
    # weights
    i = 0
    for layer in range(num_layers):
        for d in range(dirs):
            w_i2h_sz, w_i2h_shape = sizes[i][1], sizes[i][2]
            w_h2h_sz, w_h2h_shape = sizes[i + 1][1], sizes[i + 1][2]
            i += 2
            out[(layer, d)] = {
                "w_i2h": params[pos:pos + w_i2h_sz].reshape(w_i2h_shape)}
            pos += w_i2h_sz
            out[(layer, d)]["w_h2h"] = \
                params[pos:pos + w_h2h_sz].reshape(w_h2h_shape)
            pos += w_h2h_sz
    for layer in range(num_layers):
        for d in range(dirs):
            sz = _GATES[mode] * state_size
            out[(layer, d)]["b_i2h"] = params[pos:pos + sz]
            pos += sz
            out[(layer, d)]["b_h2h"] = params[pos:pos + sz]
            pos += sz
    return out


def _cell_step(mode, state_size):
    """One-step transition fn for lax.scan: (h[,c]), x_t -> new state,
    output."""
    if mode in ("rnn_relu", "rnn_tanh"):
        act = jnp.tanh if mode == "rnn_tanh" else \
            (lambda v: jnp.maximum(v, 0))

        def step(p, carry, x_t):
            (h,) = carry
            pre = x_t @ p["w_i2h"].T + p["b_i2h"] + \
                h @ p["w_h2h"].T + p["b_h2h"]
            h2 = act(pre)
            return (h2,), h2
        return step
    if mode == "lstm":
        def step(p, carry, x_t):
            h, c = carry
            pre = x_t @ p["w_i2h"].T + p["b_i2h"] + \
                h @ p["w_h2h"].T + p["b_h2h"]
            i_g, f_g, c_g, o_g = jnp.split(pre, 4, axis=-1)
            i_g = jax.nn.sigmoid(i_g)
            f_g = jax.nn.sigmoid(f_g)
            c_g = jnp.tanh(c_g)
            o_g = jax.nn.sigmoid(o_g)
            c2 = f_g * c + i_g * c_g
            h2 = o_g * jnp.tanh(c2)
            return (h2, c2), h2
        return step
    if mode == "gru":
        def step(p, carry, x_t):
            (h,) = carry
            xi = x_t @ p["w_i2h"].T + p["b_i2h"]
            hh = h @ p["w_h2h"].T + p["b_h2h"]
            xr, xz, xn = jnp.split(xi, 3, axis=-1)
            hr, hz, hn = jnp.split(hh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h2 = (1 - z) * n + z * h
            return (h2,), h2
        return step
    raise ValueError("unknown RNN mode %r" % mode)


@register("RNN", arg_names=("data", "parameters", "state", "state_cell"),
          takes_is_train=True, needs_rng=True,
          defaults={"state_size": 0, "num_layers": 1,
                    "bidirectional": False, "mode": "lstm", "p": 0.0,
                    "state_outputs": False, "lstm_state_clip_min": None,
                    "lstm_state_clip_max": None})
def _rnn_op(data, parameters, state, state_cell=None, state_size=0,
            num_layers=1, bidirectional=False, mode="lstm", p=0.0,
            state_outputs=False, is_train=False, rng=None, **_):
    """data: (T, N, input); state: (L*D, N, H); lstm also state_cell."""
    seq_len, batch, input_size = data.shape
    dirs = 2 if bidirectional else 1
    params = _unpack_params(parameters, mode, input_size, state_size,
                            num_layers, bidirectional)
    step = _cell_step(mode, state_size)

    x = data
    out_h = []
    out_c = []
    for layer in range(num_layers):
        layer_outs = []
        for d in range(dirs):
            p_ld = params[(layer, d)]
            sidx = layer * dirs + d
            # initial states may carry a broadcast batch dim of 1 (the
            # symbolic RNN toolkit's begin_state zeros) — expand so the
            # scan carry shape is static
            def _full_batch(s):
                if s.shape[0] != batch:
                    return jnp.broadcast_to(s, (batch,) + s.shape[1:])
                return s
            h0 = _full_batch(state[sidx])
            carry = (h0, _full_batch(state_cell[sidx])) \
                if mode == "lstm" else (h0,)
            xs = x[::-1] if d == 1 else x

            def scan_fn(carry, x_t, _p=p_ld):
                return step(_p, carry, x_t)

            final, ys = lax.scan(scan_fn, carry, xs)
            if d == 1:
                ys = ys[::-1]
            layer_outs.append(ys)
            out_h.append(final[0])
            if mode == "lstm":
                out_c.append(final[1])
        x = jnp.concatenate(layer_outs, axis=-1) if dirs == 2 \
            else layer_outs[0]
        if is_train and p > 0 and layer < num_layers - 1 and \
                rng is not None:
            keep = 1.0 - p
            mask = jax.random.bernoulli(
                jax.random.fold_in(rng, layer), keep, x.shape)
            x = jnp.where(mask, x / keep, 0).astype(x.dtype)

    outputs = [x]
    if state_outputs:
        outputs.append(jnp.stack(out_h))
        if mode == "lstm":
            outputs.append(jnp.stack(out_c))
    return tuple(outputs) if len(outputs) > 1 else outputs[0]
