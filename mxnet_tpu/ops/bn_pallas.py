"""Pallas BatchNorm training kernels — the below-XLA experiment for
the ResNet-50 MFU gap (docs/mfu_analysis.md measured BN statistics at
~18% of the step; reference hand-optimized BN too,
src/operator/batch_norm.cc).

Why Pallas here: training BN's HBM floor is 2 reads of x + 1 write of
y forward (stats pass, then apply pass) and 2 reads of (dy, x) + 1
write of dx backward. ops/nn.py's one-pass rewrite reaches that floor
only IF XLA fuses the sibling sum(x)/sum(x^2) reductions into one loop
and the apply into its consumer — a fusion decision we cannot pin from
the HLO level. These kernels make the pass structure EXPLICIT:

* `_stats` — one sequential-grid pass over x accumulating the shifted
  sibling sums (s1, s2) in f32 VMEM accumulators (grid over N, one
  sample's (C, HW) tile per step);
* `_apply` — one pass computing y = A*x + B with per-channel A/B
  precomputed host-side (tiny (C,) math);
* `_bwd_reduce` — one pass over (dy, x) accumulating sum(dy) and
  sum(dy*(x-mean));
* `_bwd_dx` — one pass computing dx = A*dy + C2*(x-mean) + B.

Numerics match ops/nn.py's shifted one-pass core: the same per-channel
shift c (first sample's channel mean) guards the E[x^2]-E[x]^2
cancellation, and the same closed-form backward (including the
mean/var output cotangents) is used.

Routing: `MXNET_BN_PALLAS=1` switches ops/nn.py's training BatchNorm
core to this path for 4-D NCHW inputs on TPU; anywhere else it runs in
Pallas interpret mode (tests pin it against the jnp core on CPU).
Measured A/B vs the XLA one-pass core: `benchmark/bench_bn.py`.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def _interpret():
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# kernels (grid over N; one (1, C, HW) sample tile per step)
# ---------------------------------------------------------------------------

def _stats_kernel(x_ref, c_ref, s1_ref, s2_ref):
    i = pl.program_id(0)
    x = x_ref[...].astype(jnp.float32)          # (1, C, HW)
    xc = x - c_ref[...][:, :, None]             # shift: kills E[x^2]
    ps1 = jnp.sum(xc, axis=(0, 2))              # cancellation
    ps2 = jnp.sum(xc * xc, axis=(0, 2))

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    s1_ref[...] += ps1[None]
    s2_ref[...] += ps2[None]


def _apply_kernel(x_ref, a_ref, b_ref, y_ref):
    x = x_ref[...].astype(jnp.float32)
    y = x * a_ref[...][:, :, None] + b_ref[...][:, :, None]
    y_ref[...] = y.astype(y_ref.dtype)


def _bwd_reduce_kernel(dy_ref, x_ref, mean_ref, db_ref, dxc_ref):
    i = pl.program_id(0)
    dy = dy_ref[...].astype(jnp.float32)
    xc = x_ref[...].astype(jnp.float32) - mean_ref[...][:, :, None]
    pdb = jnp.sum(dy, axis=(0, 2))
    pdxc = jnp.sum(dy * xc, axis=(0, 2))

    @pl.when(i == 0)
    def _init():
        db_ref[...] = jnp.zeros_like(db_ref)
        dxc_ref[...] = jnp.zeros_like(dxc_ref)

    db_ref[...] += pdb[None]
    dxc_ref[...] += pdxc[None]


def _bwd_dx_kernel(dy_ref, x_ref, a_ref, c2_ref, b_ref, mean_ref,
                   dx_ref):
    dy = dy_ref[...].astype(jnp.float32)
    xc = x_ref[...].astype(jnp.float32) - mean_ref[...][:, :, None]
    dx = (dy * a_ref[...][:, :, None]
          + xc * c2_ref[...][:, :, None]
          + b_ref[...][:, :, None])
    dx_ref[...] = dx.astype(dx_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------

def _sample_spec(C, HW):
    return pl.BlockSpec((1, C, HW), lambda i: (i, 0, 0))


def _chan_spec(C):
    return pl.BlockSpec((1, C), lambda i: (0, 0))


def _stats(x3, c):
    N, C, HW = x3.shape
    s1, s2 = pl.pallas_call(
        _stats_kernel,
        grid=(N,),
        in_specs=[_sample_spec(C, HW), _chan_spec(C)],
        out_specs=[_chan_spec(C), _chan_spec(C)],
        out_shape=[jax.ShapeDtypeStruct((1, C), jnp.float32)] * 2,
        interpret=_interpret(),
    )(x3, c[None])
    return s1[0], s2[0]


def _apply(x3, a, b):
    N, C, HW = x3.shape
    return pl.pallas_call(
        _apply_kernel,
        grid=(N,),
        in_specs=[_sample_spec(C, HW), _chan_spec(C), _chan_spec(C)],
        out_specs=_sample_spec(C, HW),
        out_shape=jax.ShapeDtypeStruct((N, C, HW), x3.dtype),
        interpret=_interpret(),
    )(x3, a[None], b[None])


def _bwd_reduce(dy3, x3, mean):
    N, C, HW = x3.shape
    db, dxc = pl.pallas_call(
        _bwd_reduce_kernel,
        grid=(N,),
        in_specs=[_sample_spec(C, HW), _sample_spec(C, HW),
                  _chan_spec(C)],
        out_specs=[_chan_spec(C), _chan_spec(C)],
        out_shape=[jax.ShapeDtypeStruct((1, C), jnp.float32)] * 2,
        interpret=_interpret(),
    )(dy3, x3, mean[None])
    return db[0], dxc[0]


def _bwd_dx(dy3, x3, a, c2, b, mean, out_dtype):
    N, C, HW = x3.shape
    return pl.pallas_call(
        _bwd_dx_kernel,
        grid=(N,),
        in_specs=[_sample_spec(C, HW), _sample_spec(C, HW),
                  _chan_spec(C), _chan_spec(C), _chan_spec(C),
                  _chan_spec(C)],
        out_specs=_sample_spec(C, HW),
        out_shape=jax.ShapeDtypeStruct((N, C, HW), out_dtype),
        interpret=_interpret(),
    )(dy3, x3, a[None], c2[None], b[None], mean[None])


# ---------------------------------------------------------------------------
# the training core (same contract as ops/nn.py:_bn_train_core for the
# NCHW case: returns (y, mean, var) with the closed-form custom VJP)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def bn_train_pallas(x, g, beta, eps):
    y, mean, var, _ = _fwd_impl(x, g, beta, eps)
    return y, mean, var


def _fwd_impl(x, g, beta, eps):
    N, C, H, W = x.shape
    x3 = x.reshape(N, C, H * W)
    m = N * H * W
    # per-channel shift: first sample's channel mean (tiny 1/N pass)
    c = lax.stop_gradient(
        jnp.mean(x3[0].astype(jnp.float32), axis=1))
    s1, s2 = _stats(x3, c)
    mean_s = s1 / m
    mean = c + mean_s
    var = jnp.maximum(s2 / m - jnp.square(mean_s), 0.0)
    inv = lax.rsqrt(var + eps)
    # y = A*x + B with per-channel A/B (tiny host-side math)
    a = g.astype(jnp.float32) * inv
    b = beta.astype(jnp.float32) - mean * a
    y = _apply(x3, a, b).reshape(x.shape)
    return y, mean, var, inv


def _fwd(x, g, beta, eps):
    y, mean, var, inv = _fwd_impl(x, g, beta, eps)
    return (y, mean, var), (x, g, jnp.zeros((), beta.dtype),
                            mean, inv)


def _bwd(eps, res, cts):
    # dy stays in its incoming dtype: an .astype here would
    # materialize a full f32 copy that XLA cannot fuse into the
    # pallas_call operand (the kernels upcast tile-wise internally,
    # exactly like they do for x) — casting would break the 2-read
    # backward this module exists to guarantee
    dy = cts[0]
    dmean = cts[1].astype(jnp.float32)
    dvar = cts[2].astype(jnp.float32)
    x, g, beta_proto, mean, inv = res
    N, C, H, W = x.shape
    m = N * H * W
    x3 = x.reshape(N, C, H * W)
    dy3 = dy.reshape(N, C, H * W)
    db, dxc = _bwd_reduce(dy3, x3, mean)
    dgx = dxc * inv                      # = sum(dy * xhat)
    gf = g.astype(jnp.float32)
    k = gf * inv / m
    # dx = A*dy + C2*(x-mean) + B, coefficients per channel:
    a = gf * inv                         # k*m
    c2 = -k * inv * dgx + (2.0 / m) * dvar
    b = -k * db + dmean / m
    dx = _bwd_dx(dy3, x3, a, c2, b, mean, x.dtype).reshape(x.shape)
    return (dx, dgx.astype(g.dtype),
            db.astype(beta_proto.dtype))


bn_train_pallas.defvjp(_fwd, _bwd)
