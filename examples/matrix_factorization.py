"""Matrix factorization recommender (reference
example/recommenders/demo1-MF.ipynb): user/item embeddings trained on
synthetic ratings with row-sparse lazy updates — only the rows touched by
a batch pay optimizer traffic.

Run: python examples/matrix_factorization.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

USERS, ITEMS, RANK = 200, 300, 8


def synth(n, rng):
    u_emb = rng.randn(USERS, RANK).astype(np.float32)
    i_emb = rng.randn(ITEMS, RANK).astype(np.float32)
    u = rng.randint(0, USERS, n)
    i = rng.randint(0, ITEMS, n)
    r = (u_emb[u] * i_emb[i]).sum(1) * 0.3
    return (u.astype(np.float32), i.astype(np.float32),
            r.astype(np.float32))


def build():
    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    score = mx.sym.Variable("score_label")
    ue = mx.sym.Embedding(user, input_dim=USERS, output_dim=RANK,
                          name="user_embed")
    ie = mx.sym.Embedding(item, input_dim=ITEMS, output_dim=RANK,
                          name="item_embed")
    pred = mx.sym.sum(ue * ie, axis=1)
    return mx.sym.LinearRegressionOutput(pred, score, name="pred")


def main():
    rng = np.random.RandomState(0)
    u, i, r = synth(20000, rng)
    it = mx.io.NDArrayIter({"user": u, "item": i},
                           {"score_label": r}, batch_size=256,
                           shuffle=True, label_name="score_label")
    mod = mx.mod.Module(build(), context=mx.cpu(),
                        data_names=("user", "item"),
                        label_names=("score_label",))
    mod.fit(it, num_epoch=10, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            eval_metric="mse")
    mse = mod.score(it, "mse")[0][1]
    var = float(np.var(r))
    print("rating MSE %.4f vs variance %.4f" % (mse, var))
    assert mse < 0.3 * var


if __name__ == "__main__":
    main()
