"""Speech-style acoustic model: variable-length CONTINUOUS-feature
utterances, frame-level labels, bucketed batching, and an LSTM with a
projected recurrent state — reference example/speech-demo/
(train_lstm_proj.py + lstm_proj.py + io_util.py). That example fed
Kaldi filterbank utterances of wildly varying length through custom
bucket iterators into an LSTMP acoustic model with per-frame
cross-entropy; this is the same seam, zero-egress:

* LSTMPCell — the projection matrix inside the recurrence
  (lstm_proj.py's num_hidden_proj: h_t is replaced by r_t = W_p m_t,
  shrinking both the recurrent matmul and the state), defined HERE as
  a BaseRNNCell subclass, exactly how the reference example carried
  its own cell.
* a speech bucket iterator — float (B, T, F) features + (B, T) frame
  labels, utterances padded to the bucket length with label -1 (the
  reference padded with zero frames and masked); SoftmaxOutput's
  use_ignore drops the padded frames from the loss.
* BucketingModule — one jit specialization per bucket length sharing
  one parameter set (the XLA-native answer to dynamic shapes).

TPU notes: frames stream through time-major unrolled matmuls that
batch over utterances (MXU-friendly); buckets keep shapes static so
each length compiles once.

Self-checking:
1. frame accuracy on real (non-padded) frames > 0.9 after training;
2. causal padding invariance: the same short utterance padded into
   two DIFFERENT buckets yields identical predictions on its real
   frames (padding can never leak backward into a unidirectional
   LSTM — the bucketing analogue of the masking guarantee).

Run: python examples/speech_lstm_bucketing.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io
from mxnet_tpu.ndarray import op as _nop  # noqa: F401 (parity import)
from mxnet_tpu.symbol import op as _op

K = 6            # phoneme classes
F = 20           # filterbank-ish feature dim
HIDDEN = 96
PROJ = 48
BATCH = 8
BUCKETS = (16, 32, 48)


class LSTMPCell(mx.rnn.BaseRNNCell):
    """LSTM with projected recurrent state (reference
    example/speech-demo/lstm_proj.py): memory cell m_t keeps
    num_hidden units, but the state fed back (and emitted) is
    r_t = W_p m_t with num_proj < num_hidden — the h2h matmul runs at
    proj width, the classic speech-model compute saver."""

    def __init__(self, num_hidden, num_proj, prefix="lstmp_",
                 params=None, forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_proj = num_proj
        self._iW = self.params.get("i2h_weight")
        self._hW = self.params.get("h2h_weight")
        self._iB = self.params.get(
            "i2h_bias",
            init=mx.init.LSTMBias(forget_bias=forget_bias))
        self._hB = self.params.get("h2h_bias")
        self._pW = self.params.get("proj_weight")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_proj), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("i", "f", "c", "o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = _op.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=4 * self._num_hidden,
                                 name="%si2h" % name)
        h2h = _op.FullyConnected(states[0], self._hW, self._hB,
                                 num_hidden=4 * self._num_hidden,
                                 name="%sh2h" % name)
        gates = _op.SliceChannel(i2h + h2h, num_outputs=4, axis=-1)
        i_g = _op.Activation(gates[0], act_type="sigmoid")
        f_g = _op.Activation(gates[1], act_type="sigmoid")
        c_t = _op.Activation(gates[2], act_type="tanh")
        o_g = _op.Activation(gates[3], act_type="sigmoid")
        next_m = f_g * states[1] + i_g * c_t
        h = o_g * _op.Activation(next_m, act_type="tanh")
        r = _op.FullyConnected(h, self._pW, no_bias=True,
                               num_hidden=self._num_proj,
                               name="%sproj" % name)
        return r, [r, next_m]


def sym_gen(seq_len):
    data = mx.sym.Variable("data")               # (B, T, F)
    label = mx.sym.Variable("softmax_label")     # (B, T), -1 = pad
    cell = LSTMPCell(HIDDEN, PROJ)
    outputs, _ = cell.unroll(seq_len, data, layout="NTC",
                             merge_outputs=True)     # (B, T, PROJ)
    flat = mx.sym.Reshape(outputs, shape=(-1, PROJ))
    fc = mx.sym.FullyConnected(flat, num_hidden=K, name="frame_fc")
    sm = mx.sym.SoftmaxOutput(fc, mx.sym.Reshape(label, shape=(-1,)),
                              use_ignore=True, ignore_label=-1,
                              normalization="valid", name="softmax")
    return sm, ("data",), ("softmax_label",)


def synth_utterance(rng, protos):
    """Phoneme prototypes + noise, random durations — a caricature of
    filterbank frames with alignments."""
    n_ph = rng.randint(2, 7)
    frames, labels = [], []
    for _ in range(n_ph):
        ph = rng.randint(0, K)
        dur = rng.randint(3, 9)
        frames.append(protos[ph][None].repeat(dur, 0)
                      + 0.4 * rng.randn(dur, F))
        labels.append(np.full(dur, ph))
    return (np.concatenate(frames).astype(np.float32),
            np.concatenate(labels).astype(np.float32))


def bucket_for(n):
    for b in BUCKETS:
        if n <= b:
            return b
    return None


def make_batches(utts, rng=None):
    """Group utterances by bucket, pad to the bucket length (features
    with zero frames, labels with -1), emit full DataBatches — the
    example-local bucket iterator, like the reference's io_util.py."""
    by_bucket = {b: [] for b in BUCKETS}
    for x, y in utts:
        b = bucket_for(len(x))
        if b is not None:
            by_bucket[b].append((x, y))
    batches = []
    for b, items in by_bucket.items():
        for i in range(0, len(items) - BATCH + 1, BATCH):
            X = np.zeros((BATCH, b, F), np.float32)
            Y = np.full((BATCH, b), -1.0, np.float32)
            for j, (x, y) in enumerate(items[i:i + BATCH]):
                X[j, :len(x)] = x
                Y[j, :len(y)] = y
            batches.append(io.DataBatch(
                data=[mx.nd.array(X)], label=[mx.nd.array(Y)],
                bucket_key=b,
                provide_data=[("data", (BATCH, b, F))],
                provide_label=[("softmax_label", (BATCH, b))]))
    if rng is not None:
        rng.shuffle(batches)
    return batches


def frame_accuracy(mod, batches):
    correct = total = 0
    for batch in batches:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
        lab = batch.label[0].asnumpy().reshape(-1)
        real = lab >= 0
        correct += (pred[real] == lab[real]).sum()
        total += real.sum()
    return correct / float(total)


def check_padding_invariance(mod, protos):
    """One short utterance, padded into bucket 16 AND bucket 48: the
    predictions on its real frames must match exactly-ish (causality:
    pad frames sit in the future of every real frame)."""
    rng = np.random.RandomState(99)
    x, y = synth_utterance(rng, protos)
    x, y = x[:14], y[:14]
    preds = {}
    for b in (BUCKETS[0], BUCKETS[-1]):
        X = np.zeros((BATCH, b, F), np.float32)
        Y = np.full((BATCH, b), -1.0, np.float32)
        X[0, :len(x)] = x
        Y[0, :len(y)] = y
        batch = io.DataBatch(
            data=[mx.nd.array(X)], label=[mx.nd.array(Y)],
            bucket_key=b,
            provide_data=[("data", (BATCH, b, F))],
            provide_label=[("softmax_label", (BATCH, b))])
        mod.forward(batch, is_train=False)
        out = mod.get_outputs()[0].asnumpy().reshape(BATCH, b, K)
        preds[b] = out[0, :len(x)]
    np.testing.assert_allclose(preds[BUCKETS[0]], preds[BUCKETS[-1]],
                               rtol=1e-4, atol=1e-5)
    print("padding invariance OK: identical real-frame predictions "
          "across buckets %d and %d" % (BUCKETS[0], BUCKETS[-1]))


def main():
    rng = np.random.RandomState(0)
    protos = rng.randn(K, F).astype(np.float32) * 2.0
    utts = [synth_utterance(rng, protos) for _ in range(480)]

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=BUCKETS[-1],
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (BATCH, BUCKETS[-1], F))],
             label_shapes=[("softmax_label", (BATCH, BUCKETS[-1]))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 2e-3,
                                         "rescale_grad": 1.0 / BATCH})
    for epoch in range(4):
        batches = make_batches(utts, rng)
        for batch in batches:
            mod.forward_backward(batch)
            mod.update()
        print("epoch %d frame-acc %.3f"
              % (epoch, frame_accuracy(mod, batches[:12])))

    batches = make_batches(utts)
    acc = frame_accuracy(mod, batches)
    print("final frame accuracy (non-pad frames): %.3f" % acc)
    assert acc > 0.9, "acoustic model failed to train: %.3f" % acc

    check_padding_invariance(mod, protos)
    print("speech_lstm_bucketing OK")


if __name__ == "__main__":
    main()
