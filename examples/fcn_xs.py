"""FCN-xs semantic segmentation — reference example/fcn-xs (FCN-32s/
16s/8s over VGG): downsampling backbone, 1x1 score heads, stride-2
Deconvolution upsampling with a skip fusion, per-pixel SoftmaxOutput
(multi_output over the channel axis, ignore_label capable).

This exercises the seam the reference example exists for —
Deconvolution as a LEARNED upsampler composed with elementwise skip
fusion at full resolution — on a synthetic shape-segmentation task
small enough for CI: images contain a filled rectangle and a filled
disc on noise; the net labels each pixel {background, rectangle, disc}.

Self-checking: pixel accuracy and per-class IoU gates on held-out
images. Run: python examples/fcn_xs.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import numpy as np

import mxnet_tpu as mx

IM = 32
NCLS = 3


def make_dataset(n, rng):
    X = rng.uniform(0, 0.2, (n, 3, IM, IM)).astype(np.float32)
    Y = np.zeros((n, IM, IM), np.float32)
    yy, xx = np.mgrid[0:IM, 0:IM]
    for i in range(n):
        # rectangle (class 1)
        w, h = rng.randint(8, 14), rng.randint(8, 14)
        x1, y1 = rng.randint(1, IM - w - 1), rng.randint(1, IM - h - 1)
        X[i, 0, y1:y1 + h, x1:x1 + w] += 0.8
        Y[i, y1:y1 + h, x1:x1 + w] = 1
        # disc (class 2) — may overlap; later wins, as drawn
        r = rng.randint(4, 7)
        cx, cy = rng.randint(r + 1, IM - r - 1), rng.randint(
            r + 1, IM - r - 1)
        mask = (yy - cy) ** 2 + (xx - cx) ** 2 <= r * r
        X[i, 1][mask] += 0.8
        Y[i][mask] = 2
    return X, Y


def fcn_symbol():
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    # encoder: /2 then /4 (the "VGG pool" stand-ins)
    c1 = mx.sym.Activation(mx.sym.Convolution(
        data, kernel=(3, 3), stride=(2, 2), pad=(1, 1), num_filter=16,
        name="conv1"), act_type="relu")
    c2 = mx.sym.Activation(mx.sym.Convolution(
        c1, kernel=(3, 3), stride=(2, 2), pad=(1, 1), num_filter=32,
        name="conv2"), act_type="relu")
    # FCN heads: score at /4, upsample x2 by LEARNED Deconvolution,
    # fuse with the /2 skip score, upsample x2 back to full res
    score4 = mx.sym.Convolution(c2, kernel=(1, 1), num_filter=NCLS,
                                name="score4")
    up2 = mx.sym.Deconvolution(score4, kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=NCLS,
                               name="up2")            # -> /2
    score2 = mx.sym.Convolution(c1, kernel=(1, 1), num_filter=NCLS,
                                name="score2")
    fused = up2 + score2                               # FCN-16s fusion
    up1 = mx.sym.Deconvolution(fused, kernel=(4, 4), stride=(2, 2),
                               pad=(1, 1), num_filter=NCLS,
                               name="up1")            # -> full res
    return mx.sym.SoftmaxOutput(up1, label, multi_output=True,
                                normalization="valid", name="softmax")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=8)
    args = p.parse_args()
    B = args.batch_size

    rng = np.random.RandomState(0)
    X, Y = make_dataset(64, rng)
    Xe, Ye = make_dataset(16, np.random.RandomState(9))

    train = mx.io.NDArrayIter(X, Y, batch_size=B, shuffle=True,
                              label_name="softmax_label")
    mod = mx.mod.Module(fcn_symbol(), context=mx.cpu())
    mod.fit(train, num_epoch=args.epochs, optimizer="sgd",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9,
                              "rescale_grad": 1.0 / B})

    # -- held-out evaluation ------------------------------------------------
    it = mx.io.NDArrayIter(Xe, Ye, batch_size=B,
                           label_name="softmax_label")
    preds = []
    for batch in it:
        mod.forward(batch, is_train=False)
        preds.append(mod.get_outputs()[0].asnumpy().argmax(axis=1))
    pred = np.concatenate(preds)[:len(Ye)]

    acc = float((pred == Ye).mean())
    ious = []
    for c in range(NCLS):
        inter = ((pred == c) & (Ye == c)).sum()
        union = ((pred == c) | (Ye == c)).sum()
        ious.append(inter / max(union, 1))
    print("pixel accuracy %.3f, per-class IoU %s"
          % (acc, np.round(ious, 3).tolist()))
    assert acc > 0.90, "pixel accuracy gate: %.3f" % acc
    assert min(ious) > 0.55, "class IoU gate: %s" % ious
    print("fcn_xs: PASS")


if __name__ == "__main__":
    main()
