"""Image-folder -> im2rec -> ImageRecordIter -> fit: the full data
plane end to end — reference example/kaggle-ndsb1 (+ the
image-classification README's data-prep recipe): class-per-directory
images packed into RecordIO with tools/im2rec, streamed back through
the augmenting record iterator, trained with Module.fit.

This is the one seam no other example drives whole: PNG files on disk
-> im2rec listing/packing (multi-threaded JPEG re-encode) -> .rec +
.lst -> ImageRecordIter (native C++ batched decode when available,
PIL fallback otherwise) with mean-subtraction + mirror augmentation ->
fit -> accuracy gate.

Synthetic dataset: 3 classes of 24x24 shape images (filled disc,
cross, stripes) with noise/jitter — drawn with numpy, saved as real
PNGs via PIL, classified from PIXELS after the full encode/decode
round trip.

Self-checking: train accuracy > 0.88 after a few epochs, and the
im2rec artifacts are structurally sound (.lst row count, .rec
readable by the plain RecordIO reader).

Run: python examples/image_folder_training.py
"""
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import recordio

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_PER_CLASS = 60
SIZE = 24
BATCH = 16


def draw(cls, rng):
    """One 24x24 RGB image of class `cls` with jitter + noise."""
    img = rng.uniform(0, 40, (SIZE, SIZE, 3))
    cx, cy = SIZE // 2 + rng.randint(-3, 4), \
        SIZE // 2 + rng.randint(-3, 4)
    color = rng.uniform(150, 255, 3)
    yy, xx = np.mgrid[0:SIZE, 0:SIZE]
    if cls == 0:                                  # filled disc
        mask = (yy - cy) ** 2 + (xx - cx) ** 2 < rng.randint(
            16, 36)
    elif cls == 1:                                # cross
        w = rng.randint(1, 3)
        mask = (np.abs(yy - cy) < w) | (np.abs(xx - cx) < w)
    else:                                         # stripes
        mask = ((xx + rng.randint(0, 4)) // 3) % 2 == 0
    img[mask] = color + rng.uniform(-20, 20, 3)
    return np.clip(img, 0, 255).astype(np.uint8)


def build_folder(root, rng):
    from PIL import Image
    names = ("disc", "cross", "stripes")
    for c, name in enumerate(names):
        d = os.path.join(root, name)
        os.makedirs(d)
        for i in range(N_PER_CLASS):
            Image.fromarray(draw(c, rng)).save(
                os.path.join(d, "%s_%03d.png" % (name, i)))
    return names


def main():
    rng = np.random.RandomState(0)
    tmp = tempfile.mkdtemp(prefix="imgfolder_")
    img_root = os.path.join(tmp, "images")
    os.makedirs(img_root)
    build_folder(img_root, rng)

    # the reference workflow, verbatim: list pass then packing pass
    prefix = os.path.join(tmp, "shapes")
    im2rec = os.path.join(REPO, "tools", "im2rec.py")
    subprocess.run([sys.executable, im2rec, prefix, img_root,
                    "--list"], check=True)
    subprocess.run([sys.executable, im2rec, prefix, img_root,
                    "--resize", str(SIZE), "--quality", "95"],
                   check=True)

    with open(prefix + ".lst") as f:
        n_rows = sum(1 for _ in f)
    assert n_rows == 3 * N_PER_CLASS, n_rows
    # the packed file is plain RecordIO — readable without the iter
    reader = recordio.MXRecordIO(prefix + ".rec", "r")
    first = reader.read()
    assert first and len(first) > 100
    reader.close()

    # mean/std normalization inside the iterator (the reference's
    # mean_r/std_r knobs) — raw 0-255 pixels would need a far smaller
    # learning rate
    it = mx.io.ImageRecordIter(
        path_imgrec=prefix + ".rec", data_shape=(3, SIZE, SIZE),
        batch_size=BATCH, shuffle=True, rand_mirror=True,
        mean_r=66, mean_g=66, mean_b=66,
        std_r=70, std_g=70, std_b=70, preprocess_threads=2)

    net = mx.sym.Variable("data")
    net = mx.sym.Convolution(net, num_filter=16, kernel=(3, 3),
                             pad=(1, 1), name="c1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Convolution(net, num_filter=32, kernel=(3, 3),
                             pad=(1, 1), name="c2")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1))
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=3,
                                name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=10, optimizer="sgd",
            initializer=mx.init.Xavier(factor_type="in",
                                       magnitude=2.0),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / BATCH})
    it.reset()
    score = mod.score(it, "acc")
    acc = score[0][1] if isinstance(score, list) else float(score)
    print("train accuracy through the full record pipeline: %.3f"
          % acc)
    assert acc > 0.88, "pipeline training failed: %.3f" % acc
    shutil.rmtree(tmp, ignore_errors=True)
    print("image_folder_training OK")


if __name__ == "__main__":
    main()
