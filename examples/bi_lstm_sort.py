"""Sort short digit sequences with a bidirectional LSTM (reference
example/bi-lstm-sort/sort_io.py + lstm_sort.py): read the sequence both
ways, emit the sorted sequence position-wise.

Run: python examples/bi_lstm_sort.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import rnn

SEQ, VOCAB, HID = 5, 10, 64


def batches(n, rng):
    x = rng.randint(0, VOCAB, (n, SEQ)).astype(np.float32)
    y = np.sort(x, axis=1)
    return x, y


def build():
    data = mx.sym.Variable("data")                      # (N, SEQ)
    label = mx.sym.Variable("softmax_label")            # (N, SEQ)
    embed = mx.sym.Embedding(data, input_dim=VOCAB, output_dim=32,
                             name="embed")
    cell = rnn.BidirectionalCell(rnn.LSTMCell(HID, prefix="l_"),
                                 rnn.LSTMCell(HID, prefix="r_"))
    outputs, _ = cell.unroll(SEQ, inputs=embed, merge_outputs=True)
    pred = mx.sym.Reshape(outputs, shape=(-1, 2 * HID))
    pred = mx.sym.FullyConnected(pred, num_hidden=VOCAB, name="cls")
    lab = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, lab, name="softmax")


def main():
    rng = np.random.RandomState(0)
    Xtr, ytr = batches(2048, rng)
    it = mx.io.NDArrayIter(Xtr, ytr, batch_size=64, shuffle=True)
    mod = mx.mod.Module(build(), context=mx.cpu())
    mod.fit(it, num_epoch=60, optimizer="adam",
            optimizer_params={"learning_rate": 3e-3})

    Xte, yte = batches(256, np.random.RandomState(1))
    acc = mod.score(mx.io.NDArrayIter(Xte, yte, batch_size=64),
                    "acc")[0][1]
    print("bi-lstm sort per-position accuracy: %.3f" % acc)
    assert acc > 0.80


if __name__ == "__main__":
    main()
