"""User-defined Python operator (reference example/numpy-ops/custom_softmax.py):
a numpy softmax + cross-entropy output layer registered as a Custom op and
trained inside a Module graph.

TPU note: the Custom op body runs host-side via jax.pure_callback with a
custom_vjp for the backward (mxnet_tpu/ops/custom.py) — the rest of the
graph stays compiled on device.

Run: python examples/custom_op_softmax.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
import mxnet_tpu.operator as op


class NumpySoftmax(op.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        e = np.exp(x - x.max(axis=1, keepdims=True))
        self.assign(out_data[0], req[0], e / e.sum(axis=1, keepdims=True))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        prob = out_data[0].asnumpy()
        label = in_data[1].asnumpy().astype(np.int64)
        grad = prob.copy()
        grad[np.arange(len(label)), label] -= 1.0
        self.assign(in_grad[0], req[0], grad / len(label))


@op.register("numpy_softmax")
class NumpySoftmaxProp(op.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["data", "label"]

    def infer_shape(self, in_shape):
        data = in_shape[0]
        return [data, (data[0],)], [data], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return NumpySoftmax()


def main():
    rng = np.random.RandomState(0)
    X = rng.randn(256, 16).astype(np.float32)
    y = (X[:, :8].sum(1) > X[:, 8:].sum(1)).astype(np.float32)

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    net = mx.sym.Custom(fc, label, op_type="numpy_softmax", name="softmax")

    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=32, shuffle=True)
    mod.fit(it, num_epoch=8, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    acc = mod.score(it, "acc")[0][1]
    print("custom-op softmax accuracy: %.3f" % acc)
    assert acc > 0.9


if __name__ == "__main__":
    main()
