"""Workload config #2, TPU-native: ResNet over a device mesh with the
compiled SPMD TrainStep (dp x tp mesh, bf16 compute, f32 master
weights) — the path bench.py measures. Runs on any device count:
`JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
   python examples/train_resnet_spmd.py --num-devices 8`
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models import resnet
from mxnet_tpu.parallel import make_mesh, make_train_step


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-layers", type=int, default=18)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--num-devices", type=int, default=1)
    p.add_argument("--model-axis", type=int, default=1)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--dtype", default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--zero1", action="store_true",
                   help="shard optimizer state 1/N over the data axis "
                        "(reduce-scatter -> update -> all-gather)")
    args = p.parse_args()

    import jax
    mesh = None
    if args.num_devices > 1:
        mesh = make_mesh({"data": args.num_devices // args.model_axis,
                          "model": args.model_axis},
                         devices=jax.devices()[:args.num_devices])

    sym = resnet.get_symbol(num_classes=args.num_classes,
                            num_layers=args.num_layers,
                            image_shape=(3, args.image_size,
                                         args.image_size))
    step = make_train_step(
        sym, optimizer="sgd",
        optimizer_params={"momentum": 0.9, "wd": 1e-4},
        mesh=mesh,
        compute_dtype=None if args.dtype == "float32" else args.dtype,
        optimizer_sharding="zero1" if args.zero1 else None)

    shapes = {"data": (args.batch_size, 3, args.image_size,
                       args.image_size),
              "softmax_label": (args.batch_size,)}
    state = step.init_state(mx.init.Xavier(factor_type="in",
                                           magnitude=2.0), shapes)
    rng = jax.random.PRNGKey(0)
    X = np.random.RandomState(0).randn(*shapes["data"]) \
        .astype(np.float32)
    y = np.random.RandomState(1).randint(
        0, args.num_classes, shapes["softmax_label"]).astype(np.float32)
    batch = step.place_batch({"data": X, "softmax_label": y})

    import time
    state, outs = step(state, batch, 0.1, rng)     # compile
    np.asarray(jax.device_get(outs[0][0, 0]))
    t0 = time.time()
    for _ in range(args.steps):
        state, outs = step(state, batch, 0.1, rng)
    np.asarray(jax.device_get(outs[0][0, 0]))
    dt = (time.time() - t0) / args.steps
    print("step %.2f ms  ->  %.0f img/s" % (dt * 1e3,
                                            args.batch_size / dt))


if __name__ == "__main__":
    main()
