"""Workload config #5: SSD-style detector training — reference
example/ssd/train.py (multibox prior/target/detection stack over
ImageDetIter). Synthesizes a tiny detection .rec so it is
self-contained: `python examples/ssd_train.py`.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import image as img_mod, recordio


def make_det_rec(tmp, n=32, size=32):
    rec = os.path.join(tmp, "ssd.rec")
    idx = os.path.join(tmp, "ssd.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        cls = i % 3
        im = np.full((size, size, 3), 30 * (cls + 1), np.uint8)
        im += rng.randint(0, 20, im.shape).astype(np.uint8)
        box = [0.1 + 0.2 * cls, 0.2, 0.4 + 0.2 * cls, 0.7]
        label = np.array([2, 5, cls, *box], np.float32)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, label, i, 0), im, img_fmt=".png"))
    w.close()
    return rec


def ssd_symbol(num_classes=3):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("label")
    body = mx.sym.Activation(mx.sym.Convolution(
        data, kernel=(3, 3), stride=(2, 2), pad=(1, 1), num_filter=16,
        name="conv1"), act_type="relu")
    body = mx.sym.Activation(mx.sym.Convolution(
        body, kernel=(3, 3), stride=(2, 2), pad=(1, 1), num_filter=32,
        name="conv2"), act_type="relu")
    anchors = mx.sym.MultiBoxPrior(body, sizes=(0.3, 0.6),
                                   ratios=(1.0, 2.0))
    n_anchor_per_cell = 3
    C = num_classes + 1
    cls_head = mx.sym.Convolution(
        body, kernel=(3, 3), pad=(1, 1),
        num_filter=n_anchor_per_cell * C, name="cls_pred")
    # (B, K*C, H, W) -> (B, C, A): class-major anchor predictions
    cls_pred = mx.sym.transpose(cls_head, axes=(0, 2, 3, 1))
    cls_pred = mx.sym.Reshape(mx.sym.Flatten(cls_pred), shape=(0, -1, C))
    cls_pred = mx.sym.transpose(cls_pred, axes=(0, 2, 1))
    loc_pred = mx.sym.Convolution(
        body, kernel=(3, 3), pad=(1, 1),
        num_filter=n_anchor_per_cell * 4, name="loc_pred")
    loc_pred = mx.sym.Flatten(
        mx.sym.transpose(loc_pred, axes=(0, 2, 3, 1)))

    loc_target, loc_mask, cls_target = mx.sym.MultiBoxTarget(
        anchors, label, cls_pred, overlap_threshold=0.5,
        negative_mining_ratio=3, name="target")
    cls_prob = mx.sym.SoftmaxOutput(
        cls_pred, cls_target,
        multi_output=True, use_ignore=True, ignore_label=-1,
        normalization="valid", name="cls_prob")
    loc_diff = loc_mask * (loc_pred - loc_target)
    loc_loss = mx.sym.MakeLoss(mx.sym.smooth_l1(loc_diff, scalar=1.0),
                               grad_scale=1.0, name="loc_loss")
    return mx.sym.Group([cls_prob, loc_loss,
                         mx.sym.BlockGrad(cls_target)])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--epochs", type=int, default=5)
    args = p.parse_args()

    with tempfile.TemporaryDirectory() as tmp:
        rec = make_det_rec(tmp)
        it = img_mod.ImageDetIter(batch_size=args.batch_size,
                                  data_shape=(3, 32, 32),
                                  path_imgrec=rec)
        mod = mx.mod.Module(ssd_symbol(), data_names=("data",),
                            label_names=("label",))
        first = next(it)
        it.reset()
        mod.bind(data_shapes=[("data", first.data[0].shape)],
                 label_shapes=[("label", first.label[0].shape)])
        mod.init_params(mx.init.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1,
                                             "momentum": 0.9})
        for epoch in range(args.epochs):
            it.reset()
            total = count = 0
            for b in it:
                mod.forward(b, is_train=True)
                cls_prob, loc_loss, cls_target = \
                    [o.asnumpy() for o in mod.get_outputs()]
                mod.backward()
                mod.update()
                tgt = cls_target.astype(int)
                valid = tgt >= 0
                bi, ai = np.nonzero(valid)
                p_t = cls_prob[bi, tgt[bi, ai], ai]
                total += -np.log(np.maximum(p_t, 1e-9)).mean() + \
                    loc_loss.sum()
                count += 1
            print("epoch %d loss %.4f" % (epoch, total / count))


if __name__ == "__main__":
    main()
