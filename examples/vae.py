"""Variational autoencoder — reference example/vae (MLP encoder/decoder
over MNIST with the reparameterization trick and the analytic Gaussian
KL; the example exists to exercise stochastic layers + composite losses
through autograd).

Data: the committed real handwritten-digit fixture (8x8, scaled to
[0,1]). Encoder -> (mu, logvar) -> z = mu + eps*exp(logvar/2) ->
decoder -> Bernoulli reconstruction loss + KL(q || N(0,1)).

Self-checking: (a) the ELBO must improve substantially over training;
(b) reconstructions must beat a mean-image baseline on held-out data.
Run: python examples/vae.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd

FIXTURE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures", "digits_8x8.npz")

LATENT = 8
HIDDEN = 64
DIM = 64


class VAE:
    def __init__(self, rng):
        def init(shape, scale=0.1):
            return nd.array(rng.randn(*shape).astype(np.float32) * scale)

        self.p = {
            "enc_w": init((HIDDEN, DIM)), "enc_b": nd.zeros((HIDDEN,)),
            "mu_w": init((LATENT, HIDDEN)), "mu_b": nd.zeros((LATENT,)),
            "lv_w": init((LATENT, HIDDEN)), "lv_b": nd.zeros((LATENT,)),
            "dec_w": init((HIDDEN, LATENT)), "dec_b": nd.zeros((HIDDEN,)),
            "out_w": init((DIM, HIDDEN)), "out_b": nd.zeros((DIM,)),
        }
        for v in self.p.values():
            v.attach_grad()

    def encode(self, x):
        h = nd.tanh(nd.FullyConnected(x, self.p["enc_w"],
                                      self.p["enc_b"],
                                      num_hidden=HIDDEN))
        mu = nd.FullyConnected(h, self.p["mu_w"], self.p["mu_b"],
                               num_hidden=LATENT)
        logvar = nd.FullyConnected(h, self.p["lv_w"], self.p["lv_b"],
                                   num_hidden=LATENT)
        return mu, logvar

    def decode(self, z):
        h = nd.tanh(nd.FullyConnected(z, self.p["dec_w"],
                                      self.p["dec_b"],
                                      num_hidden=HIDDEN))
        return nd.sigmoid(nd.FullyConnected(h, self.p["out_w"],
                                            self.p["out_b"],
                                            num_hidden=DIM))

    def loss(self, x, eps):
        mu, logvar = self.encode(x)
        z = mu + eps * nd.exp(logvar * 0.5)       # reparameterization
        xhat = self.decode(z)
        # Bernoulli NLL + analytic KL(q(z|x) || N(0, 1)), per sample
        rec = -nd.sum(x * nd.log(xhat + 1e-7)
                      + (1 - x) * nd.log(1 - xhat + 1e-7)) \
            / x.shape[0]
        kl = -0.5 * nd.sum(1 + logvar - nd.square(mu)
                           - nd.exp(logvar)) / x.shape[0]
        return rec + kl


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=2e-3)
    args = p.parse_args()
    B = args.batch_size

    with np.load(FIXTURE) as z:
        X = z["images"].astype(np.float32).reshape(-1, DIM) / 16.0
    test = np.arange(len(X)) % 5 == 0
    Xtr, Xte = X[~test], X[test]

    rng = np.random.RandomState(0)
    model = VAE(rng)
    mx.random.seed(0)
    states = {k: (nd.zeros(v.shape), nd.zeros(v.shape))
              for k, v in model.p.items()}

    first_elbo = last_elbo = None
    n_batches = len(Xtr) // B
    for epoch in range(args.epochs):
        perm = rng.permutation(len(Xtr))
        total = 0.0
        for k in range(n_batches):
            xb = nd.array(Xtr[perm[k * B:(k + 1) * B]])
            eps = nd.array(rng.randn(B, LATENT).astype(np.float32))
            with autograd.record():
                loss = model.loss(xb, eps)
            loss.backward()
            for name, prm in model.p.items():
                m, v = states[name]
                nd.adam_update(prm, prm.grad, m, v, lr=args.lr,
                               out=prm)
            total += float(loss.asscalar())
        elbo = -total / n_batches
        if first_elbo is None:
            first_elbo = elbo
        last_elbo = elbo
        if (epoch + 1) % 10 == 0:
            print("epoch %d ELBO %.2f" % (epoch + 1, elbo))

    # -- gates ---------------------------------------------------------------
    assert last_elbo > first_elbo + 5.0, \
        "ELBO did not improve: %.2f -> %.2f" % (first_elbo, last_elbo)

    # reconstruction must beat predicting the training mean image
    xte = nd.array(Xte)
    mu, _ = model.encode(xte)
    xhat = model.decode(mu).asnumpy()            # mean-latent decode
    rec_err = float(np.mean((xhat - Xte) ** 2))
    base_err = float(np.mean((Xtr.mean(axis=0)[None] - Xte) ** 2))
    print("recon MSE %.4f vs mean-image baseline %.4f (ELBO %.2f -> "
          "%.2f)" % (rec_err, base_err, first_elbo, last_elbo))
    assert rec_err < 0.6 * base_err, \
        "reconstruction gate: %.4f vs %.4f" % (rec_err, base_err)
    print("vae: PASS")


if __name__ == "__main__":
    main()
