"""Memory-cost accounting for gradient mirroring — reference
example/memcost/inception_memcost.py: train an inception-style tower
with MXNET_BACKWARD_DO_MIRROR and compare activation memory / extra
compute against the plain backward.

TPU-first redesign. The reference's mirror pass edits the NNVM graph
to recompute cheap forward nodes in the backward
(graph_executor.cc:276-287) and the example reads the memory planner's
pool sizes. Under XLA the same lever is `jax.checkpoint` around the
forward (TrainStep(remat=True), honoring the reference's
MXNET_BACKWARD_DO_MIRROR env var), and the ledger comes from the
compiler itself:

* `TrainStep.cost_analysis` (lowered-HLO flops) shows the PRICE:
  rematerialization re-runs the forward inside the backward, so step
  flops rise by roughly the forward's share;
* `compiled.memory_analysis()` (XLA's buffer assignment) shows the
  PAYOFF: temp/activation bytes drop — the backward re-derives
  activations tile-by-tile instead of holding every conv/BN output
  alive across the whole forward->backward span. The CPU backend
  reports temp_size 0 (no buffer-assignment stats), so the bytes
  table is asserted only where the backend reports it (TPU); the
  flops price and the numerics are asserted everywhere.

Self-checking:
1. remat raises lowered step flops (the recompute really is in the
   program) but by less than the full forward twice-over;
2. three SGD steps from identical inits produce allclose losses —
   mirroring is a schedule change, not a math change;
3. where the backend reports temp bytes, remat strictly shrinks them.

Run: python examples/memcost_remat.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.parallel import make_train_step

BATCH = 16
IMG = 16            # small inception-ish tower: enough depth that
DEPTH = 4           # activations dominate parameters, as in inception


def conv_factory(net, num_filter, idx):
    """Conv->BN->ReLU, the reference example's ConvFactory unit."""
    net = mx.sym.Convolution(net, num_filter=num_filter, kernel=(3, 3),
                             pad=(1, 1), name="conv%d" % idx)
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn%d" % idx)
    return mx.sym.Activation(net, act_type="relu")


def get_symbol():
    data = mx.sym.Variable("data")
    net = data
    for i in range(DEPTH):
        net = conv_factory(net, 32, i)
    net = mx.sym.Pooling(net, global_pool=True, pool_type="avg",
                         kernel=(1, 1), name="gap")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=10,
                                name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def build(remat):
    # identical weights for both variants: the initializer draws from
    # the framework seed (check 2 compares the two trajectories)
    mx.random.seed(42)
    step = make_train_step(get_symbol(), optimizer="sgd",
                           optimizer_params={"momentum": 0.9,
                                             "rescale_grad": 1.0 / BATCH},
                           remat=remat, donate=False)
    state = step.init_state(mx.init.Xavier(),
                            {"data": (BATCH, 3, IMG, IMG),
                             "softmax_label": (BATCH,)})
    return step, state


def ledger(step, state, batch, rng):
    # one AOT compile feeds both ledgers (the trace-level
    # lowered.cost_analysis() is backend-dependent — the CPU backend
    # only fills it in post-compile) AND the training loop below —
    # losses() drives this same executable, so each variant compiles
    # exactly once
    compiled = step.lower(state, batch, 0.05, rng).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    flops = float((ca or {}).get("flops", 0.0))
    mem = compiled.memory_analysis()
    temp = int(getattr(mem, "temp_size_in_bytes", 0) or 0)
    return flops, temp, compiled


def losses(compiled, state, batch, rng, n=3):
    lr = jax.numpy.asarray(0.05, jax.numpy.float32)
    out = []
    for _ in range(n):
        params, opt_state, aux = state
        state, outs = compiled(params, opt_state, aux, batch, lr, rng)
        softmax = np.asarray(jax.device_get(outs[0]))
        lbl = np.asarray(batch["softmax_label"]).astype(int)
        p = softmax[np.arange(len(lbl)), lbl]
        out.append(float(-np.log(np.maximum(p, 1e-9)).mean()))
    return out


def main():
    rng_np = np.random.RandomState(0)
    batch_np = {"data": rng_np.randn(BATCH, 3, IMG, IMG)
                .astype(np.float32),
                "softmax_label": rng_np.randint(0, 10, BATCH)
                .astype(np.float32)}
    rng = jax.random.PRNGKey(0)

    plain, state_p = build(remat=False)
    mirror, state_m = build(remat=True)
    batch_p = plain.place_batch(batch_np)
    batch_m = mirror.place_batch(batch_np)

    f_plain, t_plain, c_plain = ledger(plain, state_p, batch_p, rng)
    f_mirror, t_mirror, c_mirror = ledger(mirror, state_m, batch_m, rng)

    print("%-22s %14s %14s" % ("", "plain", "mirror(remat)"))
    if f_plain > 0:
        print("%-22s %14.3e %14.3e  (x%.2f)"
              % ("step flops", f_plain, f_mirror, f_mirror / f_plain))
    print("%-22s %14d %14d" % ("temp bytes", t_plain, t_mirror))

    # 1. the recompute is really in the program: flops rise, but by
    #    less than a whole extra fwd+bwd (sanity bound: < 2x). Only
    #    where the backend fills the flops ledger in at all.
    if f_plain > 0:
        assert f_mirror > f_plain * 1.05, \
            "remat did not add recompute flops (%.3e vs %.3e)" \
            % (f_mirror, f_plain)
        assert f_mirror < f_plain * 2.0
    else:
        print("(backend reports no flops ledger; skipping flops check)")

    # 2. schedule change, not math change
    l_p = losses(c_plain, state_p, batch_p, rng)
    l_m = losses(c_mirror, state_m, batch_m, rng)
    print("losses plain : %s" % ["%.5f" % v for v in l_p])
    print("losses mirror: %s" % ["%.5f" % v for v in l_m])
    np.testing.assert_allclose(l_p, l_m, rtol=2e-3, atol=2e-4)

    # 3. the payoff, where the backend keeps the ledger. Strict shrink
    #    is asserted on TPU only: the CPU backend either reports 0 or
    #    schedules this toy net into the same slab either way — at
    #    real scale the drop is the whole point (bench.py --remat
    #    trains 32k-token context that OOMs without it)
    if t_plain > 0:
        assert t_mirror <= t_plain, \
            "remat INCREASED temp memory (%d -> %d)" \
            % (t_plain, t_mirror)
        if jax.default_backend() == "tpu":
            assert t_mirror < t_plain, \
                "remat did not shrink temp memory (%d -> %d)" \
                % (t_plain, t_mirror)
        if t_mirror < t_plain:
            print("temp memory saved: %.1f%%"
                  % (100.0 * (1 - t_mirror / t_plain)))

    print("memcost_remat OK")


if __name__ == "__main__":
    main()
