"""Elastic SPMD training with TrainStep.fit — Module.fit ergonomics on
the compiled data-parallel step, plus kill-anywhere restart.

The script trains a small MLP twice with the SAME command: the first
call stops "mid-job" (few epochs), the second picks up from the latest
checkpoint automatically and finishes. Run it:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/fit_spmd_elastic.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io
from mxnet_tpu.parallel import data_parallel_mesh, make_train_step


def command(prefix, num_epoch):
    """One 'job submission': same code for the first run and restarts."""
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, name="fc1", num_hidden=64)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, name="fc2", num_hidden=4)
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    rng = np.random.RandomState(7)
    X = rng.randn(256, 32).astype(np.float32)
    y = (rng.randn(4, 32) @ X.T).argmax(0).astype(np.float32)

    step = make_train_step(
        net, optimizer="sgd",
        optimizer_params={"momentum": 0.9, "rescale_grad": 1.0 / 64},
        mesh=data_parallel_mesh(), compute_dtype="bfloat16")
    train = io.NDArrayIter(X, y, batch_size=64, shuffle=True)
    return step.fit(train, num_epoch=num_epoch,
                    initializer=mx.init.Xavier(), lr=0.5,
                    checkpoint_prefix=prefix,
                    batch_end_callback=mx.callback.Speedometer(64, 2))


def main():
    import logging
    logging.basicConfig(level=logging.INFO)
    np.random.seed(0)   # NDArrayIter shuffle uses the global rng

    with tempfile.TemporaryDirectory() as d:
        prefix = os.path.join(d, "job")

        print("== first submission (will 'die' after 6 of 25 epochs) ==")
        command(prefix, 6)

        print("== resubmission of the SAME command ==")
        state, acc = command(prefix, 25)
        print("final train accuracy: %.3f (resumed, not restarted)"
              % acc)
        assert acc > 0.95, acc


if __name__ == "__main__":
    main()
