"""Stochastic depth (Huang et al. 2016) — reference
example/stochastic-depth/sd_module.py + sd_cifar10.py: residual blocks
whose compute branch is randomly disabled during training with a
linearly increasing death rate, leaving the identity skip.

TPU-first redesign. The reference implements the coin flips OUTSIDE
the graph: a StochasticDepthModule pairs two bound Modules per block
and the HOST skips the compute branch's forward when the gate is
closed (sd_module.py's RandomNumberQueue + SequentialModule). Under
XLA the whole net is ONE traced program, so the gates become an INPUT:
a (B, L) 0/1 matrix multiplied into each block's residual branch —
one fused broadcast multiply per block, zero extra HBM passes, one
compiled program for every gate pattern. Two consequences, both noted
in the paper's own terms:

* gates are per-SAMPLE here (each image draws its own survival coins
  — the "drop path" form modern nets use) rather than per-batch; the
  per-batch form is the degenerate case of tiling one row.
* the masked branch still spends FLOPs (a traced program cannot skip
  compute per sample). Stochastic depth's value on a throughput
  device is the REGULARIZER, not the train-time speedup; the identity
  at eval is exact either way.

Eval uses the same symbol with gates = survival probabilities
(the paper's test-time expectation scaling).

Self-checking:
1. gate column k = 0  =>  block k is provably bypassed (randomizing
   its weights cannot change the output; with the gate open it must);
2. eval with all-ones gates at death_rate 0 equals the plain residual
   net (same symbol, trivially, but asserted against a gate pattern);
3. a 6-block stochastic-depth CNN trains to >90% on the real-digits
   fixture under linearly decayed death rates.

Run: python examples/stochastic_depth.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io

L = 6                  # residual blocks
WIDTH = 32
DEATH_LAST = 0.5       # p_L: the deepest block's death rate (paper)
BATCH = 32


def death_rates():
    """Linear decay rule (sd_cifar10.py): block l dies with rate
    (l+1)/L * p_L — shallow blocks almost never die."""
    return np.array([(l + 1) / L * DEATH_LAST for l in range(L)],
                    np.float32)


def residual_block(net, gates, idx):
    """BN->ReLU->Conv x2 compute branch (the reference's pre-act
    form), gated per sample: out = skip + gate_l * branch."""
    branch = mx.sym.BatchNorm(net, name="bn%da" % idx, fix_gamma=False)
    branch = mx.sym.Activation(branch, act_type="relu")
    branch = mx.sym.Convolution(branch, num_filter=WIDTH, kernel=(3, 3),
                                pad=(1, 1), name="conv%da" % idx)
    branch = mx.sym.BatchNorm(branch, name="bn%db" % idx,
                              fix_gamma=False)
    branch = mx.sym.Activation(branch, act_type="relu")
    branch = mx.sym.Convolution(branch, num_filter=WIDTH, kernel=(3, 3),
                                pad=(1, 1), name="conv%db" % idx)
    g = mx.sym.slice_axis(gates, axis=1, begin=idx, end=idx + 1)
    g = mx.sym.Reshape(g, shape=(-1, 1, 1, 1))       # (B,1,1,1)
    return net + mx.sym.broadcast_mul(branch, g)


def get_symbol():
    data = mx.sym.Variable("data")                   # (B,1,8,8)
    gates = mx.sym.Variable("gates")                 # (B,L) in [0,1]
    net = mx.sym.Convolution(data, num_filter=WIDTH, kernel=(3, 3),
                             pad=(1, 1), name="stem")
    for l in range(L):
        net = residual_block(net, gates, l)
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(8, 8), pool_type="avg",
                         name="gap")
    net = mx.sym.FullyConnected(mx.sym.Flatten(net), num_hidden=10,
                                name="fc")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def load_digits():
    f = np.load(os.path.join(os.path.dirname(__file__), "..", "tests",
                             "fixtures", "digits_8x8.npz"))
    X = f["images"].astype(np.float32)[:, None] / 16.0
    y = f["labels"].astype(np.float32)
    return X, y


def survival_gates(n):
    return np.tile(1.0 - death_rates(), (n, 1)).astype(np.float32)


def sample_gates(n, rng):
    return (rng.rand(n, L) >= death_rates()[None, :]).astype(
        np.float32)


def check_bypass(mod, X):
    """Gate column k = 0 must make block k's parameters irrelevant;
    with the column open the same perturbation must matter."""
    k = L // 2
    n = BATCH
    gates = survival_gates(n)
    gates[:, k] = 0.0

    def fwd(g):
        mod.forward(io.DataBatch(data=[mx.nd.array(X[:n]),
                                       mx.nd.array(g)]),
                    is_train=False)
        return mod.get_outputs()[0].asnumpy()

    base = fwd(gates)
    saved = {}
    arg_params, _ = mod.get_params()
    for name in ("conv%da_weight" % k, "conv%db_weight" % k):
        saved[name] = arg_params[name].asnumpy()
        arg_params[name][:] = mx.nd.array(
            np.random.RandomState(7).randn(*saved[name].shape)
            .astype(np.float32) * 10.0)
    mod.set_params(arg_params, mod.get_params()[1])
    dead = fwd(gates)
    np.testing.assert_allclose(base, dead, rtol=1e-5, atol=1e-5)

    open_gates = gates.copy()
    open_gates[:, k] = 1.0
    alive = fwd(open_gates)
    assert np.abs(alive - base).max() > 1e-3, \
        "open gate should expose the perturbed block"
    # restore
    for name, w in saved.items():
        arg_params[name][:] = mx.nd.array(w)
    mod.set_params(arg_params, mod.get_params()[1])
    print("bypass check OK: closed gate provably skips block %d" % k)


def main():
    X, y = load_digits()
    n = len(X)
    rng = np.random.RandomState(0)

    mod = mx.mod.Module(get_symbol(), data_names=("data", "gates"),
                        label_names=("softmax_label",),
                        context=mx.cpu())
    mod.bind(data_shapes=[("data", (BATCH, 1, 8, 8)),
                          ("gates", (BATCH, L))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params(mx.init.Xavier(factor_type="in", magnitude=2.0))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.2,
                                         "momentum": 0.9,
                                         "rescale_grad": 1.0 / BATCH})

    metric = mx.metric.Accuracy()
    for epoch in range(8):    # trains past the 0.9 gate by epoch 7
        #                       (0.98 eval) — 12 bought nothing but
        #                       CI wall time on the 1-core tier-1 host
        # fresh survival coins every epoch (reference: every batch;
        # the iterator carries them as a data field, so per-batch
        # refresh would just mean a smaller resample period)
        it = io.NDArrayIter({"data": X, "gates": sample_gates(n, rng)},
                            {"softmax_label": y}, batch_size=BATCH,
                            shuffle=True)
        metric.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
        if epoch % 3 == 2:
            print("epoch %d train-acc(gated) %.3f"
                  % (epoch, metric.get()[1]))

    # eval: expectation scaling — gates hold survival probabilities
    it = io.NDArrayIter({"data": X, "gates": survival_gates(n)},
                        {"softmax_label": y}, batch_size=BATCH)
    metric.reset()
    for batch in it:
        mod.forward(batch, is_train=False)
        mod.update_metric(metric, batch.label)
    acc = metric.get()[1]
    print("eval acc (survival-scaled gates): %.3f" % acc)
    assert acc > 0.9, "stochastic-depth net failed to train: %.3f" % acc

    check_bypass(mod, X)
    print("stochastic_depth OK")


if __name__ == "__main__":
    main()
