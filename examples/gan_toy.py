"""GAN on a synthetic 2-D ring distribution (reference example/gan/
dcgan.py shrunk to an MLP so it is self-contained and fast): exercises
two-optimizer adversarial training under gluon autograd.

Run: python examples/gan_toy.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd

LATENT = 8


def real_batch(n, rng):
    theta = rng.rand(n) * 2 * np.pi
    pts = np.stack([np.cos(theta), np.sin(theta)], 1)
    return (pts + rng.randn(n, 2) * 0.05).astype(np.float32)


def mlp(sizes, out):
    net = gluon.nn.Sequential()
    for s in sizes:
        net.add(gluon.nn.Dense(s, activation="relu"))
    net.add(gluon.nn.Dense(out))
    return net


def main():
    rng = np.random.RandomState(0)
    G = mlp([64, 64], 2)
    D = mlp([64, 64], 2)
    G.initialize(mx.init.Xavier())
    D.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    gt = gluon.Trainer(G.collect_params(), "adam",
                       {"learning_rate": 1e-3})
    dt = gluon.Trainer(D.collect_params(), "adam",
                       {"learning_rate": 1e-3})

    B = 128
    ones, zeros = nd.ones((B,)), nd.zeros((B,))
    for it in range(400):
        # --- discriminator step: real -> 1, fake -> 0
        z = nd.array(rng.randn(B, LATENT).astype(np.float32))
        real = nd.array(real_batch(B, rng))
        with autograd.record():
            fake = G(z)
            dl = loss_fn(D(real), ones) + loss_fn(D(fake.detach()), zeros)
        dl.backward()
        dt.step(B)
        # --- generator step: fool D
        with autograd.record():
            gl = loss_fn(D(G(z)), ones)
        gl.backward()
        gt.step(B)

    z = nd.array(rng.randn(1024, LATENT).astype(np.float32))
    samples = G(z).asnumpy()
    radii = np.linalg.norm(samples, axis=1)
    print("generated radius mean %.3f (target 1.0), std %.3f"
          % (radii.mean(), radii.std()))
    # the generator should have learned the ring's scale
    assert 0.7 < radii.mean() < 1.3


if __name__ == "__main__":
    main()
