"""Workload config #3: gluon imperative + hybridized training —
reference example/gluon/image_classification.py (Trainer, autograd,
net.hybridize()). Self-contained synthetic data:
`python examples/gluon_image_classification.py`.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse
import logging

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def synthetic_cifar(n=512, classes=10):
    rng = np.random.RandomState(0)
    y = rng.randint(0, classes, n)
    X = rng.randn(n, 3, 32, 32).astype(np.float32) * 0.1
    for i in range(n):
        X[i, y[i] % 3, (y[i] * 3) % 28:(y[i] * 3) % 28 + 4] += 1.0
    return X, y.astype(np.float32)


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18_v1")
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--hybridize", action=argparse.BooleanOptionalAction,
                   default=True)
    args = p.parse_args()

    net = gluon.model_zoo.vision.get_model(args.model, classes=10)
    net.initialize(mx.init.Xavier(magnitude=2.24))
    if args.hybridize:
        net.hybridize()

    X, y = synthetic_cifar()
    dataset = gluon.data.ArrayDataset(nd.array(X), nd.array(y))
    loader = gluon.data.DataLoader(dataset,
                                   batch_size=args.batch_size,
                                   shuffle=True)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        for data, label in loader:
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update([label], [out])
        logging.info("epoch %d train %s=%.4f", epoch, *metric.get())


if __name__ == "__main__":
    main()
