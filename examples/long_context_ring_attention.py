"""Long-context attention via ring sequence parallelism — the
capability the reference lacked entirely (its long-sequence story was
bucketing + truncated BPTT; SURVEY §2.3). Each device holds T/n of the
sequence; KV blocks rotate over the mesh axis with collective-permute
while the flash-style online softmax merges them, so max context grows
linearly with the mesh.

`JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
   python examples/long_context_ring_attention.py --seq-len 4096`
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mxnet_tpu.ops.attention import flash_attention
from mxnet_tpu.parallel import ring_attention


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=4096)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--causal", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--window", type=int, default=0,
                   help="banded (sliding-window) ring attention: "
                        "compute and ring hops scale with the window, "
                        "not the context (causal only)")
    args = p.parse_args()

    devs = jax.devices()
    n = len(devs)
    assert args.seq_len % n == 0, "device count must divide the sequence length"
    mesh = Mesh(np.array(devs), ("sp",))
    print("mesh: %d-way sequence parallel; each device holds %d of %d "
          "positions" % (n, args.seq_len // n, args.seq_len))

    rng = np.random.RandomState(0)
    shape = (args.batch, args.heads, args.seq_len, args.head_dim)
    shard = NamedSharding(mesh, P(None, None, "sp", None))
    q, k, v = (jax.device_put(
        rng.randn(*shape).astype("float32") * 0.1, shard)
        for _ in range(3))

    fn = jax.jit(lambda a, b, c: ring_attention(
        a, b, c, mesh, "sp", causal=args.causal,
        window=args.window))
    out = fn(q, k, v)
    np.asarray(jax.device_get(out[0, 0, 0, :1]))   # sync
    t0 = time.time()
    out = fn(q, k, v)
    np.asarray(jax.device_get(out[0, 0, 0, :1]))
    print("ring attention step: %.1f ms, output sharding %s"
          % ((time.time() - t0) * 1e3, out.sharding.spec))

    if args.seq_len <= 8192:
        ref = flash_attention(
            jnp.asarray(jax.device_get(q)).reshape(-1, args.seq_len,
                                                   args.head_dim),
            jnp.asarray(jax.device_get(k)).reshape(-1, args.seq_len,
                                                   args.head_dim),
            jnp.asarray(jax.device_get(v)).reshape(-1, args.seq_len,
                                                   args.head_dim),
            causal=args.causal, window=args.window or None)
        err = float(jnp.abs(jnp.asarray(jax.device_get(out)).reshape(
            ref.shape) - ref).max())
        print("max |ring - single_device_flash| = %.2e" % err)

    # The same capability through the ordinary symbol API: a whole LM
    # whose every attention layer rings over the mesh — one flag, no
    # hand-rolled collectives (see docs/parallelism.md).
    from mxnet_tpu.initializer import Xavier
    from mxnet_tpu.models import transformer
    from mxnet_tpu.parallel import make_mesh, make_train_step

    sym = transformer.get_symbol(
        vocab_size=256, seq_len=args.seq_len, num_layers=1,
        num_heads=args.heads, dim=args.heads * args.head_dim,
        seq_axis="sp", attention_window=args.window)
    step = make_train_step(sym, optimizer="adam",
                           mesh=make_mesh({"sp": n}))
    state = step.init_state(
        Xavier(), {"data": (2, args.seq_len),
                   "softmax_label": (2, args.seq_len)})
    toks = rng.randint(0, 256, (2, args.seq_len)).astype(np.float32)
    batch = step.place_batch(
        {"data": toks, "softmax_label": np.roll(toks, -1, axis=1)})
    state, outs = step(state, batch, 1e-3, jax.random.PRNGKey(0))
    np.asarray(jax.device_get(outs[0][0, 0]))
    t0 = time.time()
    state, outs = step(state, batch, 1e-3, jax.random.PRNGKey(0))
    np.asarray(jax.device_get(outs[0][0, 0]))
    print("full LM train step (symbol seq_axis='sp'): %.1f ms for "
          "%d-token context" % ((time.time() - t0) * 1e3, args.seq_len))


if __name__ == "__main__":
    main()
