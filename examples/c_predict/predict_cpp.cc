// C++ host example over mxtpu_cpp.hpp (the predict-only cpp-package
// analogue). Same CLI contract as predict.c; CI diffs both against the
// in-process Python forward.
//
//   g++ -std=c++17 predict_cpp.cc -o predict_cpp \
//       -L<_native> -lpredict_shim -Wl,-rpath,<_native>
#include <cstdio>
#include <cstdlib>
#include <fstream>

#include "mxtpu_cpp.hpp"

int main(int argc, char** argv) {
  if (argc != 4) {
    std::fprintf(stderr,
                 "usage: %s <model_prefix> <input.f32> <num_floats>\n",
                 argv[0]);
    return 2;
  }
  uint64_t n = std::strtoull(argv[3], nullptr, 10);
  std::vector<float> input(n);
  std::ifstream f(argv[2], std::ios::binary);
  if (!f.read(reinterpret_cast<char*>(input.data()),
              n * sizeof(float))) {
    std::fprintf(stderr, "cannot read %llu floats from %s\n",
                 (unsigned long long)n, argv[2]);
    return 2;
  }

  try {
    mxtpu::Predictor pred(argv[1]);
    pred.set_input("data", input);
    pred.forward();
    for (uint32_t i = 0;; ++i) {
      std::vector<uint32_t> shape;
      try {
        shape = pred.output_shape(i);
      } catch (const mxtpu::Error&) {
        if (i == 0) throw;
        break;
      }
      std::printf("output %u shape", i);
      for (uint32_t d : shape) std::printf(" %u", d);
      std::printf("\n");
      for (float v : pred.output(i)) std::printf("%.8g\n", v);
    }
  } catch (const std::exception& e) {
    std::fprintf(stderr, "error: %s\n", e.what());
    return 1;
  }
  return 0;
}
