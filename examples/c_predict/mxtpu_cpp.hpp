// Header-only C++ wrapper over the MXTpuPred C ABI — the predict-only
// analogue of the reference's cpp-package (SURVEY N28:
// cpp-package/include/mxnet-cpp/*, a header-only front end over the C
// ABI). Training lives in Python/JAX by design; deployment-side C++
// gets a typed RAII surface:
//
//   #include "mxtpu_cpp.hpp"
//   mxtpu::Predictor pred("model");            // model.stablehlo + meta
//   pred.set_input("data", buf);               // std::vector<float>
//   pred.forward();
//   std::vector<float> out = pred.output(0);
//   std::vector<uint32_t> shape = pred.output_shape(0);
//
// Link against libpredict_shim.so (build_predict_shim() or an
// amalgamated bundle's build.sh).
#pragma once

#include <cstdint>
#include <stdexcept>
#include <string>
#include <vector>

extern "C" {
void* MXTpuPredCreate(const char* model_prefix);
int MXTpuPredSetInput(void* h, const char* key, const float* data,
                      uint64_t size);
int MXTpuPredForward(void* h);
int MXTpuPredGetOutputShape(void* h, uint32_t index, uint32_t* shape,
                            uint32_t* ndim);
int MXTpuPredGetOutput(void* h, uint32_t index, float* data,
                       uint64_t size);
void MXTpuPredFree(void* h);
const char* MXTpuGetLastError(void);
}

namespace mxtpu {

class Error : public std::runtime_error {
 public:
  explicit Error(const std::string& what)
      : std::runtime_error(what + ": " + MXTpuGetLastError()) {}
};

class Predictor {
 public:
  explicit Predictor(const std::string& model_prefix)
      : handle_(MXTpuPredCreate(model_prefix.c_str())) {
    if (!handle_) throw Error("MXTpuPredCreate");
  }
  ~Predictor() { MXTpuPredFree(handle_); }
  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;
  Predictor(Predictor&& o) noexcept : handle_(o.handle_) {
    o.handle_ = nullptr;
  }

  void set_input(const std::string& key, const std::vector<float>& v) {
    if (MXTpuPredSetInput(handle_, key.c_str(), v.data(), v.size()))
      throw Error("MXTpuPredSetInput(" + key + ")");
  }

  void forward() {
    if (MXTpuPredForward(handle_)) throw Error("MXTpuPredForward");
  }

  std::vector<uint32_t> output_shape(uint32_t index) const {
    uint32_t shape[8];
    uint32_t ndim = 8;
    if (MXTpuPredGetOutputShape(handle_, index, shape, &ndim))
      throw Error("MXTpuPredGetOutputShape");
    return std::vector<uint32_t>(shape, shape + ndim);
  }

  std::vector<float> output(uint32_t index) const {
    uint64_t total = 1;
    for (uint32_t d : output_shape(index)) total *= d;
    std::vector<float> out(total);
    if (MXTpuPredGetOutput(handle_, index, out.data(), total))
      throw Error("MXTpuPredGetOutput");
    return out;
  }

 private:
  void* handle_;
};

}  // namespace mxtpu
