/* Minimal C host for the MXTpuPred ABI — the deployment analogue of
 * the reference's image-classification/predict-cpp example over
 * MXPredCreate/SetInput/Forward/GetOutput (src/c_api/c_predict_api.cc).
 *
 * Usage: predict <model_prefix> <input.f32> <num_floats>
 *   model_prefix : path prefix of Predictor.export artifacts
 *                  (<prefix>.stablehlo + <prefix>.meta.json)
 *   input.f32    : raw little-endian float32 buffer for input "data"
 *
 * Prints, for each model output: "output <i> shape d0 d1 ..." then the
 * values, one per line (%.8g). The CI smoke test diffs this against
 * the in-process Python forward.
 *
 * Build (see tests/test_c_predict.py):
 *   gcc predict.c -o predict -L<_native> -lpredict_shim -Wl,-rpath,<_native>
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

extern void* MXTpuPredCreate(const char* model_prefix);
extern int MXTpuPredSetInput(void* h, const char* key, const float* data,
                             uint64_t size);
extern int MXTpuPredForward(void* h);
extern int MXTpuPredGetOutputShape(void* h, uint32_t index,
                                   uint32_t* shape, uint32_t* ndim);
extern int MXTpuPredGetOutput(void* h, uint32_t index, float* data,
                              uint64_t size);
extern void MXTpuPredFree(void* h);
extern const char* MXTpuGetLastError(void);

static void die(const char* what) {
  fprintf(stderr, "%s: %s\n", what, MXTpuGetLastError());
  exit(1);
}

int main(int argc, char** argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <model_prefix> <input.f32> <num_floats>\n",
            argv[0]);
    return 2;
  }
  const char* prefix = argv[1];
  uint64_t n = (uint64_t)strtoull(argv[3], NULL, 10);

  float* input = (float*)malloc(n * sizeof(float));
  FILE* f = fopen(argv[2], "rb");
  if (!f || fread(input, sizeof(float), n, f) != n) {
    fprintf(stderr, "cannot read %llu floats from %s\n",
            (unsigned long long)n, argv[2]);
    return 2;
  }
  fclose(f);

  void* h = MXTpuPredCreate(prefix);
  if (!h) die("create");
  if (MXTpuPredSetInput(h, "data", input, n) != 0) die("set_input");
  if (MXTpuPredForward(h) != 0) die("forward");

  for (uint32_t i = 0;; ++i) {
    uint32_t shape[8];
    uint32_t ndim = 8;
    if (MXTpuPredGetOutputShape(h, i, shape, &ndim) != 0) {
      if (i == 0) die("get_output_shape");
      break; /* index out of range: all outputs printed */
    }
    uint64_t total = 1;
    printf("output %u shape", i);
    for (uint32_t d = 0; d < ndim; ++d) {
      printf(" %u", shape[d]);
      total *= shape[d];
    }
    printf("\n");
    float* out = (float*)malloc(total * sizeof(float));
    if (MXTpuPredGetOutput(h, i, out, total) != 0) die("get_output");
    for (uint64_t k = 0; k < total; ++k) printf("%.8g\n", out[k]);
    free(out);
  }
  MXTpuPredFree(h);
  free(input);
  return 0;
}
