/* C host for the MXTpuTrain* training ABI: loads an exported
 * compiled-train-step artifact (TrainStep.export), feeds one batch,
 * runs N optimizer steps, then prints the last step's first output
 * and a named trained parameter — no Python source in this program.
 *
 *   train <model_prefix> <data.f32> <data_size> <label.f32>
 *         <label_size> <n_steps> <lr> <param_name>
 *
 * Reference parity: the training half of include/mxnet/c_api.h —
 * redesigned as ONE entry over the compiled step program instead of
 * 146 per-op calls (decision memo: docs/c_abi.md). */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

extern void* MXTpuTrainCreate(const char* prefix);
extern int MXTpuTrainSetBatch(void* h, const char* key,
                              const float* data, uint64_t size);
extern int MXTpuTrainStep(void* h, float lr);
extern int MXTpuTrainGetOutputShape(void* h, uint32_t index,
                                    uint32_t* shape, uint32_t* ndim);
extern int MXTpuTrainGetOutput(void* h, uint32_t index, float* data,
                               uint64_t size);
extern int MXTpuTrainGetParamShape(void* h, const char* name,
                                   uint32_t* shape, uint32_t* ndim);
extern int MXTpuTrainGetParam(void* h, const char* name, float* data,
                              uint64_t size);
extern void MXTpuTrainFree(void* h);
extern const char* MXTpuGetLastError(void);

static float* read_f32(const char* path, uint64_t n) {
  FILE* f = fopen(path, "rb");
  if (!f) return NULL;
  float* buf = (float*)malloc(n * sizeof(float));
  size_t got = fread(buf, sizeof(float), n, f);
  fclose(f);
  if (got != n) { free(buf); return NULL; }
  return buf;
}

static void die(const char* what) {
  fprintf(stderr, "%s: %s\n", what, MXTpuGetLastError());
  exit(1);
}

int main(int argc, char** argv) {
  if (argc != 9) {
    fprintf(stderr,
            "usage: %s prefix data.f32 dsize label.f32 lsize "
            "n_steps lr param_name\n", argv[0]);
    return 2;
  }
  uint64_t dsize = strtoull(argv[3], NULL, 10);
  uint64_t lsize = strtoull(argv[5], NULL, 10);
  int n_steps = atoi(argv[6]);
  float lr = (float)atof(argv[7]);
  float* data = read_f32(argv[2], dsize);
  float* label = read_f32(argv[4], lsize);
  if (!data || !label) { fprintf(stderr, "bad input files\n"); return 2; }

  void* h = MXTpuTrainCreate(argv[1]);
  if (!h) die("create");
  if (MXTpuTrainSetBatch(h, "data", data, dsize) != 0) die("set data");
  if (MXTpuTrainSetBatch(h, "softmax_label", label, lsize) != 0)
    die("set label");

  for (int i = 0; i < n_steps; ++i)
    if (MXTpuTrainStep(h, lr) != 0) die("step");

  uint32_t shape[8], ndim = 8;
  if (MXTpuTrainGetOutputShape(h, 0, shape, &ndim) != 0) die("oshape");
  uint64_t osize = 1;
  printf("output 0 shape");
  for (uint32_t i = 0; i < ndim; ++i) {
    printf(" %u", shape[i]);
    osize *= shape[i];
  }
  printf("\n");
  float* out = (float*)malloc(osize * sizeof(float));
  if (MXTpuTrainGetOutput(h, 0, out, osize) != 0) die("output");
  for (uint64_t i = 0; i < osize; ++i) printf("%.6e\n", out[i]);

  ndim = 8;
  if (MXTpuTrainGetParamShape(h, argv[8], shape, &ndim) != 0)
    die("pshape");
  uint64_t psize = 1;
  printf("param %s shape", argv[8]);
  for (uint32_t i = 0; i < ndim; ++i) {
    printf(" %u", shape[i]);
    psize *= shape[i];
  }
  printf("\n");
  float* pw = (float*)malloc(psize * sizeof(float));
  if (MXTpuTrainGetParam(h, argv[8], pw, psize) != 0) die("param");
  for (uint64_t i = 0; i < psize; ++i) printf("%.6e\n", pw[i]);

  MXTpuTrainFree(h);
  free(out); free(pw); free(data); free(label);
  return 0;
}
