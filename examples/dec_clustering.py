"""DEC — Deep Embedded Clustering (Xie et al. 2016) — reference
example/dec/dec.py: pretrain an autoencoder, initialize cluster
centres with KMeans on the embedded features, then refine encoder AND
centres jointly by minimizing KL(p || q), where q is a Student-t
soft assignment and the target p is recomputed from q every
`update_interval` steps; stop when fewer than 0.1% of the hard
assignments change between refreshes.

The reference seam this exercises is the CUSTOM TRAINING LOOP: DEC
does not fit the fit()/epoch mold — it interleaves full-dataset
feature extraction, host-side KMeans/target computation, a bespoke
NumpyOp loss (dec.py:DECLoss with a hand-written backward), and a
convergence test on cluster assignments.

TPU-first redesign: the hand-written DECLoss backward disappears —
q and KL(p||q) are expressed in autograd-recorded nd ops (the
Student-t kernel is two matmul-shaped reductions, MXU-friendly) and
the gradient to both the encoder weights and the centres `mu` comes
from autograd.backward. The periodic refresh stays a host decision
(it is control flow over the WHOLE dataset, exactly what should not
live inside a traced step), matching the reference's iter callback.

Self-checking: on the real-digits fixture (10 classes), DEC must
(a) terminate via the assignment-change criterion, and (b) end with
Hungarian-matched cluster accuracy above 0.65 without degrading its
own KMeans-in-embedding-space initialization. (The raw-pixel KMeans
baseline is printed for context only: 8x8 digits are easy enough that
pixels already cluster well — DEC's MNIST-scale win is over data
where they don't.)

Run: python examples/dec_clustering.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, io, nd

EMBED = 10
HIDDEN = 64
ALPHA = 1.0
BATCH = 64


def load_digits():
    f = np.load(os.path.join(os.path.dirname(__file__), "..", "tests",
                             "fixtures", "digits_8x8.npz"))
    X = f["images"].astype(np.float32).reshape(len(f["images"]), -1)
    X /= 16.0
    return X, f["labels"].astype(np.int64)


def cluster_acc(y_pred, y):
    """Hungarian-matched accuracy (dec.py:cluster_acc, re-derived on
    scipy's modern assignment API)."""
    from scipy.optimize import linear_sum_assignment
    D = int(max(y_pred.max(), y.max())) + 1
    w = np.zeros((D, D), np.int64)
    for yp, yt in zip(y_pred, y):
        w[int(yp), int(yt)] += 1
    rows, cols = linear_sum_assignment(w.max() - w)
    return w[rows, cols].sum() / float(len(y))


def pretrain_autoencoder(X):
    """Reconstruction pretraining via the normal Module surface (the
    reference used layerwise pretraining over 150k steps; one joint
    phase is plenty at this scale)."""
    data = mx.sym.Variable("data")
    enc = mx.sym.Activation(mx.sym.FullyConnected(
        data, num_hidden=HIDDEN, name="enc1"), act_type="relu")
    enc = mx.sym.FullyConnected(enc, num_hidden=EMBED, name="enc2")
    dec = mx.sym.Activation(mx.sym.FullyConnected(
        enc, num_hidden=HIDDEN, name="dec1"), act_type="relu")
    dec = mx.sym.FullyConnected(dec, num_hidden=X.shape[1],
                                name="dec2")
    loss = mx.sym.LinearRegressionOutput(dec, mx.sym.Variable(
        "label"), name="recon")
    mod = mx.mod.Module(loss, label_names=("label",), context=mx.cpu())
    it = io.NDArrayIter({"data": X}, {"label": X}, batch_size=BATCH,
                        shuffle=True)
    mod.fit(it, num_epoch=30, optimizer="adam",
            initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 1e-3,
                              "rescale_grad": 1.0 / BATCH})
    args, _ = mod.get_params()
    return {k: args[k] for k in ("enc1_weight", "enc1_bias",
                                 "enc2_weight", "enc2_bias")}


def encode(params, x):
    h = nd.relu(nd.FullyConnected(x, params["enc1_weight"],
                                  params["enc1_bias"],
                                  num_hidden=HIDDEN))
    return nd.FullyConnected(h, params["enc2_weight"],
                             params["enc2_bias"], num_hidden=EMBED)


def soft_assign(z, mu):
    """Student-t similarity q_ij (dec.py:DECLoss.forward, re-derived
    as autograd-recorded ops: ||z-mu||^2 via the Gram expansion keeps
    it matmul-shaped for the MXU)."""
    zz = nd.sum(z * z, axis=1, keepdims=True)            # (N,1)
    mm = nd.sum(mu * mu, axis=1, keepdims=True)          # (K,1)
    d2 = zz + nd.transpose(mm) - 2.0 * nd.dot(z, nd.transpose(mu))
    q = (1.0 + d2 / ALPHA) ** (-(ALPHA + 1.0) / 2.0)
    return nd.broadcast_div(q, nd.sum(q, axis=1, keepdims=True))


def target_distribution(q):
    """p = q^2 / f, renormalized (the self-sharpening target;
    frequency weighting f = per-cluster soft count)."""
    w = (q ** 2) / np.maximum(q.sum(axis=0, keepdims=True), 1e-9)
    return (w.T / w.sum(axis=1)).T.astype(np.float32)


def main():
    X, y = load_digits()
    N = len(X)
    rng = np.random.RandomState(0)

    from sklearn.cluster import KMeans
    pixel_acc = cluster_acc(
        KMeans(10, n_init=10, random_state=0).fit_predict(X), y)
    print("raw-pixel KMeans baseline: %.3f" % pixel_acc)

    params = pretrain_autoencoder(X)
    for p in params.values():
        p.attach_grad()

    z0 = encode(params, nd.array(X)).asnumpy()
    km = KMeans(10, n_init=20, random_state=0).fit(z0)
    mu = nd.array(km.cluster_centers_.astype(np.float32))
    mu.attach_grad()
    init_acc = cluster_acc(km.labels_, y)
    print("AE-feature KMeans init: %.3f" % init_acc)

    trainable = list(params.values()) + [mu]
    update_interval = 4 * (N // BATCH)       # ~4 epochs per refresh
    tol = 0.001
    y_last = np.zeros(N, np.int64) - 1
    p_full = None
    converged = False
    step = 0
    order = np.arange(N)
    while step < 400 * (N // BATCH):
        if step % update_interval == 0:
            q_full = soft_assign(encode(params, nd.array(X)),
                                 mu).asnumpy()
            y_pred = q_full.argmax(axis=1)
            p_full = target_distribution(q_full)
            changed = np.mean(y_pred != y_last)
            print("refresh @%d: acc %.3f, %.4f changed"
                  % (step, cluster_acc(y_pred, y), changed))
            if y_last[0] >= 0 and changed < tol:
                converged = True
                break
            y_last = y_pred
            rng.shuffle(order)
        idx = order[(step * BATCH) % N:(step * BATCH) % N + BATCH]
        if len(idx) < BATCH:
            step += 1
            continue
        xb = nd.array(X[idx])
        pb = nd.array(p_full[idx])
        with autograd.record():
            q = soft_assign(encode(params, xb), mu)
            # KL(p||q): the -sum(p log q) half carries the gradient
            loss = -nd.sum(pb * nd.log(q + 1e-9))
        loss.backward()
        for prm in trainable:
            nd.sgd_update(prm, prm.grad, lr=0.01,
                          rescale_grad=1.0 / BATCH, out=prm)
        step += 1

    assert converged, "DEC never hit the assignment-change criterion"
    final = cluster_acc(y_last, y)
    print("final DEC accuracy: %.3f (init %.3f, pixel baseline %.3f)"
          % (final, init_acc, pixel_acc))
    assert final > 0.65, "DEC accuracy too low: %.3f" % final
    assert final >= init_acc - 0.01, \
        "DEC refinement degraded its own init: %.3f < %.3f" \
        % (final, init_acc)
    print("dec_clustering OK")


if __name__ == "__main__":
    main()
