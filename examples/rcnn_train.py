"""Faster-RCNN-lite end to end — reference example/rcnn (train_end2end
.py): RPN + Fast-RCNN head trained jointly, with the two target-
assignment layers done exactly the way the reference does them — as
Python Custom ops (reference rcnn/symbol/proposal_target.py registers
"proposal_target" via CustomOp; here AnchorTarget + ProposalTarget).

The graph composes the already-registered contrib ops:
  backbone convs -> rpn head
    -> SoftmaxOutput over AnchorTarget labels     (RPN cls loss)
    -> smooth_l1 over AnchorTarget bbox targets    (RPN bbox loss)
    -> _contrib_Proposal (decode + NMS, fixed top-N)
    -> ProposalTarget (sample rois, assign cls/bbox targets)
    -> ROIPooling -> FC head
    -> SoftmaxOutput                               (head cls loss)
    -> smooth_l1                                   (head bbox loss)

Self-checking: trains on a synthetic single-object dataset and asserts
(a) the best proposal localizes the object (IoU gate) and (b) the head
classifies sampled rois above an accuracy gate.

Run: python examples/rcnn_train.py    (CPU-sized; CI smokes it)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import numpy as np

import mxnet_tpu as mx

# geometry: 64x64 images, two stride-2 convs -> stride 4, 16x16 feature
IM = 64
STRIDE = 4
FEAT = IM // STRIDE
SCALES = (2, 4, 8)          # anchor sides 8/16/32 px at stride 4
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)
NUM_FG_CLASSES = 2          # head classes: 0 = background, 1..2 = fg
POST_NMS = 8                # proposals kept per image


def _host_anchors():
    """(H*W*A, 4) anchors in Proposal's h-major/w/a order — host twin
    of ops/rcnn_ops._shifted_anchors (same rounding), so AnchorTarget
    labels line up with the op's decode."""
    from mxnet_tpu.ops.rcnn_ops import _shifted_anchors
    return _shifted_anchors(FEAT, FEAT, STRIDE, SCALES, RATIOS)


def _iou(boxes, gt):
    """boxes (N,4), gt (4,) -> (N,) corner-format IoU (+1 widths, the
    proposal.cc convention)."""
    ix1 = np.maximum(boxes[:, 0], gt[0])
    iy1 = np.maximum(boxes[:, 1], gt[1])
    ix2 = np.minimum(boxes[:, 2], gt[2])
    iy2 = np.minimum(boxes[:, 3], gt[3])
    iw = np.maximum(ix2 - ix1 + 1, 0)
    ih = np.maximum(iy2 - iy1 + 1, 0)
    inter = iw * ih
    area = ((boxes[:, 2] - boxes[:, 0] + 1)
            * (boxes[:, 3] - boxes[:, 1] + 1))
    garea = (gt[2] - gt[0] + 1) * (gt[3] - gt[1] + 1)
    return inter / np.maximum(area + garea - inter, 1e-9)


def _encode(anchors, gt):
    """bbox regression targets, inverse of _decode_rpn."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + 0.5 * (aw - 1)
    ay = anchors[:, 1] + 0.5 * (ah - 1)
    gw = gt[2] - gt[0] + 1.0
    gh = gt[3] - gt[1] + 1.0
    gx = gt[0] + 0.5 * (gw - 1)
    gy = gt[1] + 0.5 * (gh - 1)
    return np.stack([(gx - ax) / aw, (gy - ay) / ah,
                     np.log(gw / aw), np.log(gh / ah)], axis=1)


@mx.operator.register("rcnn_anchor_target")
class AnchorTargetProp(mx.operator.CustomOpProp):
    """RPN training targets (reference rcnn AnchorTargetLayer):
    in: gt_boxes (B, 5) [cls, x1, y1, x2, y2] (one object per image)
    out: label (B, A*H*W) a-major {-1 ignore, 0 bg, 1 fg},
         bbox_target/bbox_weight (B, A*4, H, W) conv-layout."""

    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["gt_boxes"]

    def list_outputs(self):
        return ["label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        B = in_shape[0][0]
        return ([in_shape[0]],
                [(B, A * FEAT * FEAT), (B, A * 4, FEAT, FEAT),
                 (B, A * 4, FEAT, FEAT)], [])

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return AnchorTargetOp()


class AnchorTargetOp(mx.operator.CustomOp):
    def __init__(self):
        super().__init__()
        self._rng = np.random.RandomState(11)

    def forward(self, is_train, req, in_data, out_data, aux):
        gt = in_data[0].asnumpy()                    # (B, 5)
        B = gt.shape[0]
        anchors = _host_anchors()                    # (H*W*A, 4)
        label = np.full((B, A * FEAT * FEAT), -1, np.float32)
        tgt = np.zeros((B, A * 4, FEAT, FEAT), np.float32)
        wgt = np.zeros((B, A * 4, FEAT, FEAT), np.float32)
        # anchor i (h-major h*W*A + w*A + a) <-> label index a*H*W+h*W+w
        hh, ww, aa = np.meshgrid(np.arange(FEAT), np.arange(FEAT),
                                 np.arange(A), indexing="ij")
        lab_idx = (aa * FEAT * FEAT + hh * FEAT + ww).reshape(-1)
        for b in range(B):
            iou = _iou(anchors, gt[b, 1:])
            pos = iou > 0.5
            pos[np.argmax(iou)] = True               # best anchor always fg
            neg = iou < 0.3
            # SUBSAMPLE negatives (reference anchor_target: 256 samples
            # per image, fg:bg capped): without it ~760 bg vs ~3 fg
            # anchors make all-background the loss minimum and the RPN
            # collapses (measured: fg prob -> 0 at labeled anchors)
            neg_idx = np.nonzero(neg & ~pos)[0]
            keep_n = min(len(neg_idx), max(16, 8 * int(pos.sum())))
            neg_keep = self._rng.choice(neg_idx, keep_n, replace=False)
            label[b, lab_idx[pos]] = 1.0
            label[b, lab_idx[neg_keep]] = 0.0
            deltas = _encode(anchors[pos], gt[b, 1:])  # (P, 4)
            ph = hh.reshape(-1)[pos]
            pw = ww.reshape(-1)[pos]
            pa = aa.reshape(-1)[pos]
            for c in range(4):
                tgt[b, pa * 4 + c, ph, pw] = deltas[:, c]
                wgt[b, pa * 4 + c, ph, pw] = 1.0
        self.assign(out_data[0], req[0], label)
        self.assign(out_data[1], req[1], tgt)
        self.assign(out_data[2], req[2], wgt)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], 0.0)


@mx.operator.register("rcnn_proposal_target")
class ProposalTargetProp(mx.operator.CustomOpProp):
    """Fast-RCNN head targets (reference proposal_target.py):
    in: rois (R, 5) [batch, x1, y1, x2, y2], gt_boxes (B, 5)
    out: rois passthrough, label (R,), bbox_target/weight (R, 4)."""

    def __init__(self):
        super().__init__(need_top_grad=False)

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_out", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        R = in_shape[0][0]
        return (in_shape, [(R, 5), (R,), (R, 4), (R, 4)], [])

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return ProposalTargetOp()


class ProposalTargetOp(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        rois = in_data[0].asnumpy()                  # (R, 5)
        gt = in_data[1].asnumpy()                    # (B, 5)
        R = rois.shape[0]
        label = np.zeros((R,), np.float32)
        tgt = np.zeros((R, 4), np.float32)
        wgt = np.zeros((R, 4), np.float32)
        for r in range(R):
            b = int(rois[r, 0])
            iou = _iou(rois[r:r + 1, 1:], gt[b, 1:])[0]
            if iou > 0.5:
                label[r] = gt[b, 0]                  # fg class (1..K)
                tgt[r] = _encode(rois[r:r + 1, 1:], gt[b, 1:])[0]
                wgt[r] = 1.0
        self.assign(out_data[0], req[0], rois)
        self.assign(out_data[1], req[1], label)
        self.assign(out_data[2], req[2], tgt)
        self.assign(out_data[3], req[3], wgt)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], 0.0)
        self.assign(in_grad[1], req[1], 0.0)


def faster_rcnn_symbol():
    data = mx.sym.Variable("data")
    im_info = mx.sym.Variable("im_info")
    gt_boxes = mx.sym.Variable("gt_boxes")

    body = mx.sym.Activation(mx.sym.Convolution(
        data, kernel=(3, 3), stride=(2, 2), pad=(1, 1), num_filter=16,
        name="conv1"), act_type="relu")
    body = mx.sym.Activation(mx.sym.Convolution(
        body, kernel=(3, 3), stride=(2, 2), pad=(1, 1), num_filter=32,
        name="conv2"), act_type="relu")

    rpn = mx.sym.Activation(mx.sym.Convolution(
        body, kernel=(3, 3), pad=(1, 1), num_filter=32,
        name="rpn_conv"), act_type="relu")
    # channels [0..A-1] background, [A..2A-1] foreground (Proposal's
    # score layout: fg scores are channels A:)
    rpn_cls = mx.sym.Convolution(rpn, kernel=(1, 1), num_filter=2 * A,
                                 name="rpn_cls")
    rpn_bbox = mx.sym.Convolution(rpn, kernel=(1, 1), num_filter=4 * A,
                                  name="rpn_bbox")

    tgt = mx.sym.Custom(gt_boxes=gt_boxes, name="anchor_target",
                        op_type="rcnn_anchor_target")
    rpn_label, rpn_tgt, rpn_wgt = tgt[0], tgt[1], tgt[2]

    # (B, 2A, H, W) -> (B, 2, A*H*W): p-major channel split matches the
    # bg/fg block layout above
    rpn_cls_2 = mx.sym.Reshape(rpn_cls, shape=(0, 2, -1))
    rpn_cls_prob = mx.sym.SoftmaxOutput(
        rpn_cls_2, rpn_label, multi_output=True, use_ignore=True,
        ignore_label=-1, normalization="valid", name="rpn_cls_prob")
    rpn_bbox_loss = mx.sym.MakeLoss(
        mx.sym.smooth_l1(rpn_wgt * (rpn_bbox - rpn_tgt), scalar=3.0),
        grad_scale=1.0 / (A * FEAT * FEAT), name="rpn_bbox_loss")

    # proposals from the softmaxed scores (bg/fg blocks restored)
    score = mx.sym.Reshape(mx.sym.SoftmaxActivation(
        rpn_cls_2, mode="channel"), shape=(0, 2 * A, FEAT, FEAT))
    rois_raw = mx.sym.Custom(
        rois=mx.sym._contrib_Proposal(
            mx.sym.BlockGrad(score), mx.sym.BlockGrad(rpn_bbox),
            im_info, rpn_pre_nms_top_n=64, rpn_post_nms_top_n=POST_NMS,
            threshold=0.7, rpn_min_size=4, scales=SCALES, ratios=RATIOS,
            feature_stride=STRIDE, name="proposal"),
        gt_boxes=gt_boxes, name="proposal_target",
        op_type="rcnn_proposal_target")
    rois, head_label, head_tgt, head_wgt = (rois_raw[0], rois_raw[1],
                                            rois_raw[2], rois_raw[3])

    # the head trains on DETACHED trunk features: early-training head
    # gradients through ROIPooling otherwise overwhelm the RPN's
    # valid-normalized signal and collapse the shared trunk (the
    # reference's historical fix was alternating RPN/head training —
    # same idea, one graph)
    pooled = mx.sym.ROIPooling(mx.sym.BlockGrad(body), rois,
                               pooled_size=(4, 4),
                               spatial_scale=1.0 / STRIDE, name="roi_pool")
    flat = mx.sym.Flatten(pooled)
    fc = mx.sym.Activation(mx.sym.FullyConnected(
        flat, num_hidden=64, name="fc6"), act_type="relu")
    head_cls = mx.sym.FullyConnected(fc, num_hidden=NUM_FG_CLASSES + 1,
                                     name="head_cls")
    head_bbox = mx.sym.FullyConnected(fc, num_hidden=4, name="head_bbox")
    head_cls_prob = mx.sym.SoftmaxOutput(head_cls, head_label,
                                         normalization="valid",
                                         name="head_cls_prob")
    head_bbox_loss = mx.sym.MakeLoss(
        mx.sym.smooth_l1(head_wgt * (head_bbox - head_tgt), scalar=1.0),
        grad_scale=1.0 / POST_NMS, name="head_bbox_loss")

    return mx.sym.Group([rpn_cls_prob, rpn_bbox_loss, head_cls_prob,
                         head_bbox_loss, mx.sym.BlockGrad(rois),
                         mx.sym.BlockGrad(head_label)])


def make_dataset(n, rng):
    """Single bright object per 64x64 image; class 1 = square ~18px,
    class 2 = wide rectangle ~30x12. gt: (cls, x1, y1, x2, y2)."""
    X = rng.uniform(0, 0.15, (n, 3, IM, IM)).astype(np.float32)
    gt = np.zeros((n, 5), np.float32)
    for i in range(n):
        cls = 1 + (i % 2)
        if cls == 1:
            w = h = rng.randint(14, 22)
        else:
            w = rng.randint(26, 34)
            h = rng.randint(10, 14)
        x1 = rng.randint(2, IM - w - 2)
        y1 = rng.randint(2, IM - h - 2)
        # distinct channel signatures per class
        X[i, cls - 1, y1:y1 + h, x1:x1 + w] += 0.9
        X[i, 2, y1:y1 + h, x1:x1 + w] += 0.4
        gt[i] = (cls, x1, y1, x1 + w - 1, y1 + h - 1)
    return X, gt


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=4)
    p.add_argument("--epochs", type=int, default=10)
    args = p.parse_args()
    B = args.batch_size

    rng = np.random.RandomState(0)
    X, gt = make_dataset(48, rng)
    im_info = np.tile(np.array([IM, IM, 1.0], np.float32), (B, 1))

    mod = mx.mod.Module(faster_rcnn_symbol(),
                        data_names=("data", "im_info"),
                        label_names=("gt_boxes",))
    mod.bind(data_shapes=[("data", (B, 3, IM, IM)),
                          ("im_info", (B, 3))],
             label_shapes=[("gt_boxes", (B, 5))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.02,
                                         "momentum": 0.9,
                                         "rescale_grad": 1.0 / B})

    from mxnet_tpu.io import DataBatch
    n_batches = len(X) // B
    for epoch in range(args.epochs):
        losses = []
        for k in range(n_batches):
            sl = slice(k * B, (k + 1) * B)
            batch = DataBatch(data=[mx.nd.array(X[sl]),
                                    mx.nd.array(im_info)],
                              label=[mx.nd.array(gt[sl])])
            mod.forward(batch, is_train=True)
            outs = [o.asnumpy() for o in mod.get_outputs()]
            mod.backward()
            mod.update()
            rpn_prob, _, head_prob, _, rois, head_label = outs
            # monitored loss: RPN fg/bg cross-entropy on valid anchors
            losses.append(float(np.mean(rpn_prob.max(axis=1))))
        print("epoch %d rpn-conf %.4f" % (epoch, np.mean(losses)))

    # -- self-check on fresh data -------------------------------------------
    Xe, gte = make_dataset(16, np.random.RandomState(7))
    ious, correct, n_fg = [], 0, 0
    for k in range(len(Xe) // B):
        sl = slice(k * B, (k + 1) * B)
        batch = DataBatch(data=[mx.nd.array(Xe[sl]),
                                mx.nd.array(im_info)],
                          label=[mx.nd.array(gte[sl])])
        mod.forward(batch, is_train=False)
        outs = [o.asnumpy() for o in mod.get_outputs()]
        head_prob, rois, head_label = outs[2], outs[4], outs[5]
        for b in range(B):
            mask = rois[:, 0] == b
            rb = rois[mask][:, 1:]
            pb = head_prob[mask]
            gtb = gte[sl][b]
            # best proposal by head foreground confidence
            fg_conf = pb[:, 1:].sum(axis=1)
            best = int(np.argmax(fg_conf))
            ious.append(_iou(rb[best:best + 1], gtb[1:])[0])
            # head accuracy over rois the target-assigner called fg
            lab = head_label[mask]
            pred = pb.argmax(axis=1)
            fg = lab > 0
            n_fg += int(fg.sum())
            correct += int((pred[fg] == lab[fg]).sum())

    mean_iou = float(np.mean(ious))
    acc = correct / max(n_fg, 1)
    print("eval: best-proposal IoU %.3f (n=%d), head fg accuracy %.3f "
          "(%d fg rois)" % (mean_iou, len(ious), acc, n_fg))
    assert mean_iou > 0.40, "proposal localization gate: %.3f" % mean_iou
    assert n_fg >= 8, "too few fg rois sampled: %d" % n_fg
    assert acc > 0.75, "head classification gate: %.3f" % acc
    print("rcnn_train: PASS")


if __name__ == "__main__":
    main()
