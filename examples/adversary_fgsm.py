"""Fast-gradient-sign adversarial examples (reference
example/adversary/adversary_generation.ipynb): train a small MLP, then
perturb inputs along the sign of the input gradient and watch accuracy
collapse — exercises autograd gradients w.r.t. DATA, not parameters.

Run: python examples/adversary_fgsm.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def main():
    rng = np.random.RandomState(0)
    # two well-separated gaussian blobs
    n = 1024
    X = np.concatenate([rng.randn(n // 2, 16) + 1.0,
                        rng.randn(n // 2, 16) - 1.0]).astype(np.float32)
    y = np.concatenate([np.ones(n // 2), np.zeros(n // 2)]).astype(
        np.float32)

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3})

    ds = gluon.data.ArrayDataset(nd.array(X), nd.array(y))
    loader = gluon.data.DataLoader(ds, batch_size=64, shuffle=True)
    for epoch in range(5):
        for xb, yb in loader:
            with autograd.record():
                loss = loss_fn(net(xb), yb)
            loss.backward()
            trainer.step(xb.shape[0])

    def accuracy(xs):
        pred = net(nd.array(xs)).asnumpy().argmax(1)
        return (pred == y).mean()

    clean_acc = accuracy(X)

    # FGSM: eps * sign(d loss / d x)
    xv = nd.array(X)
    xv.attach_grad()
    with autograd.record():
        loss = loss_fn(net(xv), nd.array(y))
    loss.backward()
    x_adv = X + 2.5 * np.sign(xv.grad.asnumpy())
    adv_acc = accuracy(x_adv)

    print("clean accuracy: %.3f   adversarial accuracy: %.3f"
          % (clean_acc, adv_acc))
    assert clean_acc > 0.95
    assert adv_acc < clean_acc - 0.2


if __name__ == "__main__":
    main()
