"""Workload config #4: bucketed LSTM language model via BucketingModule
— reference example/rnn/lstm_bucketing.py. Synthetic corpus fallback
keeps it self-contained: `python examples/lstm_bucketing.py`.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse
import logging

import numpy as np

import mxnet_tpu as mx


def synthetic_corpus(n=400, vocab=64):
    rng = np.random.RandomState(0)
    sents = []
    for _ in range(n):
        ln = int(rng.choice([8, 12, 16]))
        start = rng.randint(0, vocab)
        step = rng.randint(1, 4)
        sents.append([(start + i * step) % vocab for i in range(ln)])
    return sents, vocab


def main():
    logging.basicConfig(level=logging.INFO)
    p = argparse.ArgumentParser()
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--num-hidden", type=int, default=64)
    p.add_argument("--num-embed", type=int, default=32)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--num-epochs", type=int, default=5)
    p.add_argument("--corpus", default=None,
                   help="tokenized text file, one sentence per line "
                        "(falls back to a synthetic corpus)")
    args = p.parse_args()

    if args.corpus:
        with open(args.corpus) as f:
            raw = [line.split() for line in f if line.strip()]
        sents, vocab_map = mx.rnn.encode_sentences(raw, start_label=1)
        vocab = len(vocab_map) + 1
    else:
        sents, vocab = synthetic_corpus()

    buckets = [8, 12, 16, 24]
    train = mx.rnn.BucketSentenceIter(sents, args.batch_size,
                                      buckets=buckets, invalid_label=-1)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab,
                                 output_dim=args.num_embed, name="embed")
        stack = mx.rnn.SequentialRNNCell()
        for i in range(args.num_layers):
            stack.add(mx.rnn.LSTMCell(args.num_hidden,
                                      prefix="lstm_l%d_" % i))
        outputs, _ = stack.unroll(seq_len, embed, layout="NTC",
                                  merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        net = mx.sym.SoftmaxOutput(pred, label, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=train.
                                 default_bucket_key)
    mod.fit(train, num_epoch=args.num_epochs,
            eval_metric=mx.metric.Perplexity(-1), optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       10))


if __name__ == "__main__":
    main()
