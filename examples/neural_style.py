"""Neural-style texture synthesis — reference example/neural-style
(Gatys-style optimization: gradient-descend an IMAGE against Gram-
matrix style losses through a conv net; the example exists to exercise
the optimize-the-input seam — autograd w.r.t. DATA, not parameters).

No pretrained VGG is reachable in this zero-egress image, so the
feature extractor is a fixed random conv stack — random-feature Gram
losses are a known-workable texture statistic (Ulyanov et al. 2016
show random nets carry texture), and the SEAM under test (mark input
as variable, backprop to it, update it with an optimizer op) is
identical.

Self-checking: the synthesized image's style loss must fall by >10x
and end far closer to the target texture's Gram statistics than a
noise baseline. Run: python examples/neural_style.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd

SIZE = 32


def make_texture(rng):
    """A strongly structured target texture: diagonal stripes +
    per-channel color bias."""
    yy, xx = np.mgrid[0:SIZE, 0:SIZE]
    stripes = 0.5 + 0.5 * np.sin((xx + yy) * (2 * np.pi / 8.0))
    img = np.stack([stripes, 1 - stripes,
                    0.5 + 0.3 * np.sin(xx * (2 * np.pi / 16.0))])
    return img[None].astype(np.float32)          # (1, 3, S, S)


class RandomFeatures:
    """Fixed random conv stack; returns activations at two depths."""

    def __init__(self, rng):
        def w(shape):
            fan = shape[1] * shape[2] * shape[3]
            return nd.array((rng.randn(*shape) *
                             np.sqrt(2.0 / fan)).astype(np.float32))

        self.w1 = w((16, 3, 3, 3))
        self.w2 = w((32, 16, 3, 3))

    def __call__(self, x):
        h1 = nd.relu(nd.Convolution(x, self.w1, kernel=(3, 3),
                                    pad=(1, 1), num_filter=16,
                                    no_bias=True))
        h2 = nd.relu(nd.Convolution(h1, self.w2, kernel=(3, 3),
                                    stride=(2, 2), pad=(1, 1),
                                    num_filter=32, no_bias=True))
        return h1, h2


def gram(feat):
    """(1, C, H, W) -> (C, C) normalized Gram matrix."""
    C = feat.shape[1]
    f = nd.reshape(feat, shape=(C, -1))
    n = f.shape[1]
    return nd.dot(f, nd.transpose(f)) / float(n)


def style_loss(net, img, target_grams):
    feats = net(img)
    loss = None
    for f, g_t in zip(feats, target_grams):
        g = gram(f)
        term = nd.sum(nd.square(g - g_t))
        loss = term if loss is None else loss + term
    return loss


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=150)
    p.add_argument("--lr", type=float, default=0.05)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    net = RandomFeatures(rng)
    target = nd.array(make_texture(rng))
    target_grams = [nd.BlockGrad(gram(f)) for f in net(target)]

    # the variable being optimized IS the image
    img = nd.array(rng.uniform(0.3, 0.7,
                               (1, 3, SIZE, SIZE)).astype(np.float32))
    img.attach_grad()
    m = nd.zeros(img.shape)
    v = nd.zeros(img.shape)

    first = last = None
    for step in range(args.steps):
        with autograd.record():
            loss = style_loss(net, img, target_grams)
        loss.backward()
        nd.adam_update(img, img.grad, m, v, lr=args.lr, out=img)
        cur = float(loss.asscalar())
        if first is None:
            first = cur
        last = cur
        if (step + 1) % 50 == 0:
            print("step %d style loss %.5f" % (step + 1, cur))

    # noise baseline for scale
    noise = nd.array(rng.uniform(0.3, 0.7,
                                 (1, 3, SIZE, SIZE)).astype(np.float32))
    base = float(style_loss(net, noise, target_grams).asscalar())
    print("style loss %.5f -> %.5f (noise baseline %.5f)"
          % (first, last, base))
    assert last < first / 10.0, "style loss did not fall 10x"
    assert last < base / 10.0, "no closer to the texture than noise"
    print("neural_style: PASS")


if __name__ == "__main__":
    main()
