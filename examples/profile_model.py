"""Profile one training step (reference example/profiler/profiler_executor.py):
host-side chrome-trace timeline via mx.profiler plus, on TPU, an xplane
device trace — open the JSON in chrome://tracing or Perfetto.

Run: python examples/profile_model.py [out.json]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import profiler


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else "profile_step.json"
    rng = np.random.RandomState(0)
    X = rng.randn(64, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, 64).astype(np.float32)

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Activation(mx.sym.Convolution(
            mx.sym.Variable("data"), kernel=(3, 3), num_filter=16,
            name="conv"), act_type="relu"), num_hidden=10, name="fc"),
        name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it = mx.io.NDArrayIter(X, y, batch_size=64)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer()

    profiler.profiler_set_config(mode="all", filename=out)
    profiler.profiler_set_state("run")
    for batch in it:
        mod.forward(batch)
        mod.backward()
        mod.update()
    profiler.profiler_set_state("stop")
    profiler.dump_profile()

    import json
    with open(out) as f:
        events = json.load(f)["traceEvents"]
    print("wrote %s with %d trace events" % (out, len(events)))
    assert len(events) > 0


if __name__ == "__main__":
    main()
