"""CNN text classifier (reference example/cnn_text_classification/text_cnn.py,
Kim 2014): embedding -> parallel conv widths -> max-over-time pooling ->
dense. Synthetic keyword task so the script is self-contained.

Run: python examples/cnn_text_classification.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

SEQ, VOCAB, EMB = 24, 200, 32


def synth(n, rng):
    """Class 1 iff any token from the 'positive' keyword set appears."""
    x = rng.randint(10, VOCAB, (n, SEQ)).astype(np.float32)
    y = np.zeros(n, np.float32)
    pos = rng.rand(n) < 0.5
    slots = rng.randint(0, SEQ, n)
    x[pos, slots[pos]] = rng.randint(0, 5, pos.sum())
    y[pos] = 1.0
    return x, y


def build(filter_sizes=(2, 3, 4), num_filter=32):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=VOCAB, output_dim=EMB,
                             name="embed")                    # (N,SEQ,EMB)
    x = mx.sym.Reshape(embed, shape=(-1, 1, SEQ, EMB))
    pooled = []
    for fs in filter_sizes:
        conv = mx.sym.Convolution(x, kernel=(fs, EMB),
                                  num_filter=num_filter,
                                  name="conv%d" % fs)
        act = mx.sym.Activation(conv, act_type="relu")
        pooled.append(mx.sym.Pooling(act, pool_type="max",
                                     kernel=(SEQ - fs + 1, 1)))
    h = mx.sym.Concat(*pooled, dim=1)
    h = mx.sym.Flatten(h)
    h = mx.sym.Dropout(h, p=0.3)
    fc = mx.sym.FullyConnected(h, num_hidden=2, name="cls")
    return mx.sym.SoftmaxOutput(fc, label, name="softmax")


def main():
    rng = np.random.RandomState(0)
    X, y = synth(2048, rng)
    it = mx.io.NDArrayIter(X, y, batch_size=64, shuffle=True)
    mod = mx.mod.Module(build(), context=mx.cpu())
    mod.fit(it, num_epoch=6, optimizer="adam",
            optimizer_params={"learning_rate": 2e-3})
    Xte, yte = synth(512, np.random.RandomState(1))
    acc = mod.score(mx.io.NDArrayIter(Xte, yte, batch_size=64),
                    "acc")[0][1]
    print("text-cnn accuracy: %.3f" % acc)
    assert acc > 0.85


if __name__ == "__main__":
    main()
