"""NCE (noise-contrastive estimation) word embeddings — reference
example/nce-loss (nce.py): instead of a full-vocabulary softmax, each
training pair scores the TRUE context word plus k sampled noise words
by dot product against a shared embedding table, trained with
LogisticRegressionOutput — the sampled-softmax seam the reference
example exists to exercise (Embedding lookups as both input AND output
layer, broadcast_mul + sum as the scorer, logistic loss over
positives/negatives).

Task: synthetic skip-gram over a clustered vocabulary (words co-occur
only within their cluster). Self-checking: after training, a held-out
word's nearest embedding neighbour must belong to the same cluster for
>80% of the vocabulary (random chance ~9%).

Run: python examples/nce_loss.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import numpy as np

import mxnet_tpu as mx

VOCAB = 100
CLUSTER = 10                 # words per cluster -> 10 clusters
DIM = 32
NUM_LABEL = 9                # 1 positive + 8 noise samples


def nce_symbol():
    """Reference example/nce-loss/nce.py:nce_loss, same composition."""
    data = mx.sym.Variable("data")             # (B,) center word ids
    label = mx.sym.Variable("label")           # (B, NUM_LABEL) ids
    label_weight = mx.sym.Variable("label_weight")  # (B, NUM_LABEL) 1/0
    embed_weight = mx.sym.Variable("embed_weight")  # SHARED table

    hidden = mx.sym.Embedding(data, weight=embed_weight,
                              input_dim=VOCAB, output_dim=DIM,
                              name="in_embed")      # (B, DIM)
    label_embed = mx.sym.Embedding(label, weight=embed_weight,
                                   input_dim=VOCAB, output_dim=DIM,
                                   name="out_embed")  # (B, L, DIM)
    hidden = mx.sym.Reshape(hidden, shape=(-1, 1, DIM))
    pred = mx.sym.broadcast_mul(hidden, label_embed)
    pred = mx.sym.sum(pred, axis=2)                 # (B, L) dot scores
    return mx.sym.LogisticRegressionOutput(pred, label_weight,
                                           name="nce_out")


def make_pairs(n, rng):
    """Skip-gram pairs within clusters + noise negatives."""
    centers = rng.randint(0, VOCAB, n)
    cluster = centers // CLUSTER
    pos = cluster * CLUSTER + rng.randint(0, CLUSTER, n)
    labels = np.empty((n, NUM_LABEL), np.float32)
    weights = np.zeros((n, NUM_LABEL), np.float32)
    labels[:, 0] = pos
    weights[:, 0] = 1.0
    # noise: uniform over vocab (collisions with the cluster are rare
    # and act as label noise, as in the reference's sampler)
    labels[:, 1:] = rng.randint(0, VOCAB, (n, NUM_LABEL - 1))
    return centers.astype(np.float32), labels, weights


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--pairs", type=int, default=8192)
    args = p.parse_args()
    B = args.batch_size

    rng = np.random.RandomState(0)
    centers, labels, weights = make_pairs(args.pairs, rng)
    it = mx.io.NDArrayIter(
        data={"data": centers, "label": labels},
        label={"label_weight": weights},
        batch_size=B, shuffle=True)
    mod = mx.mod.Module(nce_symbol(), context=mx.cpu(),
                        data_names=("data", "label"),
                        label_names=("label_weight",))
    mod.fit(it, num_epoch=args.epochs, optimizer="sgd",
            initializer=mx.init.Uniform(0.1),
            optimizer_params={"learning_rate": 0.3, "momentum": 0.9,
                              "rescale_grad": 1.0 / B})

    # -- gate: nearest-neighbour cluster purity ----------------------------
    embed = mod.get_params()[0]["embed_weight"].asnumpy()
    norm = embed / np.maximum(
        np.linalg.norm(embed, axis=1, keepdims=True), 1e-9)
    sim = norm @ norm.T
    np.fill_diagonal(sim, -np.inf)
    nn = sim.argmax(axis=1)
    same = (nn // CLUSTER) == (np.arange(VOCAB) // CLUSTER)
    acc = float(same.mean())
    print("nearest-neighbour cluster purity: %.3f (chance ~%.3f)"
          % (acc, (CLUSTER - 1) / (VOCAB - 1)))
    assert acc > 0.80, "embedding purity gate: %.3f" % acc
    print("nce_loss: PASS")


if __name__ == "__main__":
    main()
