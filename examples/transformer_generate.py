"""Train a tiny transformer LM, then generate from it with the KV-cache
decoder — the full train -> decode round trip on one chip:

`python examples/transformer_generate.py`

The corpus is arithmetic token sequences (start + k*step mod vocab), so
a trained model's greedy continuation should keep extending the
progression — checked at the end.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import numpy as np

from mxnet_tpu.generation import Generator
from mxnet_tpu.initializer import Xavier
from mxnet_tpu.models import transformer
from mxnet_tpu.parallel import make_train_step

V, T, L, H, DIM, B = 32, 16, 2, 2, 64, 32


def corpus(n, seed=0):
    rng = np.random.RandomState(seed)
    starts = rng.randint(0, V, n)
    steps = rng.randint(1, 4, n)
    return (starts[:, None] + steps[:, None] * np.arange(T)[None, :]) \
        % V


def main():
    sym = transformer.get_symbol(V, T, num_layers=L, num_heads=H,
                                 dim=DIM)
    step = make_train_step(sym, optimizer="adam")
    state = step.init_state(Xavier(factor_type="avg", magnitude=2.0),
                            {"data": (B, T), "softmax_label": (B, T)})
    data = corpus(B * 40)
    key = jax.random.PRNGKey(0)
    for epoch in range(6):
        last_probs = None
        for i in range(0, len(data), B):
            toks = data[i:i + B].astype(np.float32)
            labels = np.roll(toks, -1, axis=1)
            labels[:, -1] = -1
            batch = step.place_batch({"data": toks,
                                      "softmax_label": labels})
            state, outs = step(state, batch, 1e-3, key)
            last_probs = (outs[0], labels)
        probs, labels = last_probs
        flat = np.asarray(probs).reshape(-1, V)
        keep = labels.ravel() >= 0
        nll = -np.log(np.maximum(
            flat[np.arange(len(flat)), labels.ravel().astype(int)],
            1e-9))[keep].mean()
        print("epoch %d  last-batch nll %.3f" % (epoch, nll))

    gen = Generator(state[0], V, max_len=T, num_layers=L, num_heads=H,
                    dim=DIM, batch_size=2)
    prompt = np.array([[3, 4, 5, 6], [10, 12, 14, 16]])
    out = gen.generate(prompt, max_new_tokens=8)
    print("greedy continuations:")
    for row in out:
        print("  ", row.tolist())
    # the first row is a +1 progression; count how far it continues
    want = (prompt[0, 0] + np.arange(12)) % V
    match = int((out[0] == want).sum())
    print("progression match: %d/12" % match)
    assert match >= 8, "decode should continue the learned progression"

    # the rest of the serving stack over the same checkpoint:
    beam = gen.beam_search(prompt, max_new_tokens=8, beam_size=4)
    print("beam-4 best:", beam[0].tolist())
    assert gen.log_likelihood(beam)[0] >= gen.log_likelihood(out)[0] \
        - 1e-6, "beam must not score below greedy"

    spec = gen.generate_speculative(gen, prompt, max_new_tokens=8,
                                    lookahead=4)
    assert (spec == out).all(), "speculative must equal greedy"
    print("speculative decode: exact greedy match")

    gen8 = Generator(state[0], V, max_len=T, num_layers=L,
                     num_heads=H, dim=DIM, batch_size=2,
                     quantize="int8")
    out8 = gen8.generate(prompt, max_new_tokens=8)
    m8 = int((out8[0] == want).sum())
    print("int8 weight-only greedy match: %d/12" % m8)
    assert m8 >= 8, "int8 decode should keep the progression"


if __name__ == "__main__":
    main()
