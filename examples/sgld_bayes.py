"""Bayesian learning via SGLD — reference example/bayesian-methods/
(sgld.ipynb / bdk.ipynb): Stochastic Gradient Langevin Dynamics turns
the ordinary training loop into an MCMC sampler — per-step Gaussian
noise at the Langevin scale sqrt(lr) makes the iterates draw from the
posterior instead of collapsing to the MAP point, and keeping
parameter snapshots after burn-in gives calibrated predictive
uncertainty.

The seam this exercises: the `SGLD` optimizer (optimizer.py — the
reference shipped it built-in, python/mxnet/optimizer.py:547) driven
by a custom Module loop that SNAPSHOTS the posterior along the way —
training as sampling, not optimization.

Recipe: optimize-then-sample (the practical Langevin warm start) —
Adam finds the mode, then `init_optimizer(force_init=True)` swaps in
SGLD with the posterior-scale gradient (rescale_grad = N/B * 1/sigma^2
— SGLD samples the posterior only when the gradient term estimates the
FULL-dataset log-likelihood; with the default 1/B mean-gradient the
Langevin noise drowns the data and the chain just random-walks).

Task: 1-D regression y = sin(3x) + noise on x in [-1, 1] with a small
MLP. Self-checking:
1. posterior predictive mean fits in-distribution (RMSE < 0.2);
2. predictive UNCERTAINTY is calibrated the Bayesian way: the
   posterior std OUT of distribution (x in [2.5, 3.5], never seen)
   must exceed the in-distribution std by >1.5x — point-estimate SGD
   has no such signal at all.

Run: python examples/sgld_bayes.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io

N = 200
BATCH = 20
WARM_EPOCHS = 80      # Adam to the mode
SGLD_EPOCHS = 80      # Langevin sampling around it
BURN_IN = 20
NOISE_SIGMA = 0.1     # the data noise the likelihood assumes


def get_symbol(with_head=True):
    net = mx.sym.Variable("data")
    net = mx.sym.Activation(mx.sym.FullyConnected(
        net, num_hidden=32, name="fc1"), act_type="tanh")
    net = mx.sym.Activation(mx.sym.FullyConnected(
        net, num_hidden=32, name="fc2"), act_type="tanh")
    net = mx.sym.FullyConnected(net, num_hidden=1, name="out")
    if not with_head:
        return net          # inference: no loss head, no label input
    return mx.sym.LinearRegressionOutput(
        net, mx.sym.Variable("label"), name="reg")


def predict(mod, params, xs):
    """Forward under a specific posterior sample."""
    mod.set_params(params, {}, force_init=True)
    mod.forward(io.DataBatch(data=[mx.nd.array(xs[:, None])]),
                is_train=False)
    return mod.get_outputs()[0].asnumpy().ravel()


def main():
    rng = np.random.RandomState(0)
    X = rng.uniform(-1, 1, N).astype(np.float32)
    y = (np.sin(3 * X) + 0.1 * rng.randn(N)).astype(np.float32)

    mod = mx.mod.Module(get_symbol(), data_names=("data",),
                        label_names=("label",), context=mx.cpu())
    mod.bind(data_shapes=[("data", (BATCH, 1))],
             label_shapes=[("label", (BATCH, 1))])
    mod.init_params(mx.init.Xavier())

    def run_epochs(n, snapshot_from=None):
        out = []
        for epoch in range(n):
            it = io.NDArrayIter({"data": X[:, None]},
                                {"label": y[:, None]},
                                batch_size=BATCH, shuffle=True)
            for batch in it:
                mod.forward_backward(batch)
                mod.update()
            if snapshot_from is not None and epoch >= snapshot_from \
                    and epoch % 4 == 0:
                args, _ = mod.get_params()
                out.append({k: v.copy() for k, v in args.items()})
        return out

    # phase 1: find the mode
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3,
                                         "rescale_grad": 1.0 / BATCH})
    run_epochs(WARM_EPOCHS)

    # phase 2: Langevin sampling. The noise scale is sqrt(lr) inside
    # the optimizer (the discretization); wd is the Gaussian prior;
    # rescale_grad up-weights the batch gradient toward the full-data
    # log-likelihood (the exact posterior scale is (N/B)/sigma^2 on
    # the batch sum; the factor used here runs the chain at a mildly
    # raised temperature, which widens the posterior uniformly — the
    # in/OOD uncertainty RATIO the check asserts is unaffected).
    mod.init_optimizer(
        optimizer="sgld",
        optimizer_params={"learning_rate": 1e-5, "wd": 1e-4,
                          "rescale_grad": 1000.0 / BATCH},
        force_init=True)
    snapshots = run_epochs(SGLD_EPOCHS, snapshot_from=BURN_IN)
    print("posterior samples: %d" % len(snapshots))
    assert len(snapshots) >= 10

    # predictive distribution = average over posterior samples
    # (inference-only module: no label names, binds cleanly)
    pred_mod = mx.mod.Module(get_symbol(with_head=False),
                             data_names=("data",),
                             label_names=None, context=mx.cpu())
    pred_mod.bind(data_shapes=[("data", (50, 1))],
                  label_shapes=None, for_training=False)
    pred_mod.init_params(mx.init.Xavier())

    x_in = np.linspace(-1, 1, 50).astype(np.float32)
    x_out = np.linspace(2.5, 3.5, 50).astype(np.float32)
    preds_in = np.stack([predict(pred_mod, s, x_in)
                         for s in snapshots])
    preds_out = np.stack([predict(pred_mod, s, x_out)
                          for s in snapshots])

    rmse = float(np.sqrt(np.mean(
        (preds_in.mean(axis=0) - np.sin(3 * x_in)) ** 2)))
    std_in = float(preds_in.std(axis=0).mean())
    std_out = float(preds_out.std(axis=0).mean())
    print("in-dist RMSE %.3f; predictive std in %.4f / OOD %.4f "
          "(ratio %.1fx)" % (rmse, std_in, std_out,
                             std_out / max(std_in, 1e-9)))
    assert rmse < 0.2, "posterior mean failed to fit: %.3f" % rmse
    assert std_out > 1.5 * std_in, \
        "OOD uncertainty not elevated: %.4f vs %.4f" % (std_out,
                                                        std_in)
    print("sgld_bayes OK")


if __name__ == "__main__":
    main()
