"""Workload config #1 (SURVEY Appendix B): LeNet/MLP on MNIST via
Module.fit — reference example/image-classification/train_mnist.py.

Runs on synthetic MNIST-shaped data when no dataset path is given, so
the script is self-contained: `python examples/train_mnist.py`.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import numpy as np

import mxnet_tpu as mx


def lenet():
    data = mx.sym.Variable("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20)
    p1 = mx.sym.Pooling(mx.sym.Activation(c1, act_type="tanh"),
                        pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=50)
    p2 = mx.sym.Pooling(mx.sym.Activation(c2, act_type="tanh"),
                        pool_type="max", kernel=(2, 2), stride=(2, 2))
    f = mx.sym.Flatten(p2)
    fc1 = mx.sym.Activation(mx.sym.FullyConnected(f, num_hidden=500),
                            act_type="tanh")
    fc2 = mx.sym.FullyConnected(fc1, num_hidden=10)
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def mlp():
    data = mx.sym.Flatten(mx.sym.Variable("data"))
    h1 = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=128),
                           act_type="relu")
    h2 = mx.sym.Activation(mx.sym.FullyConnected(h1, num_hidden=64),
                           act_type="relu")
    return mx.sym.SoftmaxOutput(mx.sym.FullyConnected(h2, num_hidden=10),
                                name="softmax")


def synthetic_mnist(n=2048):
    """Class-separable 28x28 synthetic digits."""
    rng = np.random.RandomState(42)
    y = rng.randint(0, 10, n)
    X = rng.randn(n, 1, 28, 28).astype(np.float32) * 0.1
    for i in range(n):
        d = y[i]
        X[i, 0, d * 2:d * 2 + 6, d:d + 6] += 1.0     # class-coded patch
    return X, y.astype(np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--network", default="mlp", choices=["mlp", "lenet"])
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--num-epochs", type=int, default=5)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--mnist-path", default=None,
                   help="dir with train-images-idx3-ubyte etc. "
                        "(falls back to synthetic data)")
    args = p.parse_args()

    if args.mnist_path:
        train = mx.io.MNISTIter(
            image="%s/train-images-idx3-ubyte" % args.mnist_path,
            label="%s/train-labels-idx1-ubyte" % args.mnist_path,
            batch_size=args.batch_size, shuffle=True)
    else:
        X, y = synthetic_mnist()
        train = mx.io.NDArrayIter(X, y, batch_size=args.batch_size,
                                  shuffle=True)

    net = mlp() if args.network == "mlp" else lenet()
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train, num_epoch=args.num_epochs, optimizer="sgd",
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       20),
            eval_metric="acc")
    print("final train accuracy:", mod.score(train, "acc")[0][1])


if __name__ == "__main__":
    main()
