"""Deep autoencoder on a synthetic low-rank manifold (reference
example/autoencoder/autoencoder.py): encoder/decoder stacks trained with
L2 reconstruction; reconstruction error must beat the best linear rank-k
baseline's neighbourhood.

Run: python examples/autoencoder.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd

DIM, CODE = 64, 4


# one fixed manifold shared by train and test splits
_W_RNG = np.random.RandomState(1234)
_W1 = _W_RNG.randn(CODE, 32).astype(np.float32)
_W2 = _W_RNG.randn(32, DIM).astype(np.float32) / np.sqrt(32)


def synth(n, rng):
    """Points on a fixed 4-D nonlinear manifold embedded in 64-D."""
    z = rng.randn(n, CODE).astype(np.float32)
    return np.tanh(z @ _W1) @ _W2


def main():
    rng = np.random.RandomState(0)
    X = synth(4096, rng)

    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(CODE),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(DIM))
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.L2Loss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})

    loader = gluon.data.DataLoader(
        gluon.data.ArrayDataset(nd.array(X), nd.array(X)),
        batch_size=128, shuffle=True)
    for epoch in range(40):
        total = 0.0
        for xb, _ in loader:
            with autograd.record():
                loss = loss_fn(net(xb), xb)
            loss.backward()
            trainer.step(xb.shape[0])
            total += float(loss.sum().asscalar())
        if epoch % 10 == 0:
            print("epoch %d recon loss/sample %.5f"
                  % (epoch, total / len(X)))

    Xte = synth(512, np.random.RandomState(1))
    rec = net(nd.array(Xte)).asnumpy()
    err = np.mean((rec - Xte) ** 2)
    var = np.mean(Xte ** 2)
    print("test relative reconstruction error: %.4f" % (err / var))
    assert err / var < 0.15      # 4-dim bottleneck captures the manifold


if __name__ == "__main__":
    main()
