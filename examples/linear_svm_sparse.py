"""Sparse linear classification over LibSVM data: csr batches drive a
linear model through the sparse dot kernel, gradients stay row-sparse
(reference sparse examples + iter_libsvm.cc). Self-contained:
`python examples/linear_svm_sparse.py`.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse


def synthesize_libsvm(path, n=512, dim=100, nnz=8, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(dim)
    with open(path, "w") as f:
        for _ in range(n):
            cols = np.sort(rng.choice(dim, nnz, replace=False))
            vals = rng.randn(nnz)
            label = int((vals * w[cols]).sum() > 0)
            f.write("%d %s\n" % (label, " ".join(
                "%d:%.5f" % (c, v) for c, v in zip(cols, vals))))


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--data", default=None, help="libsvm file "
                   "(synthesized when omitted)")
    p.add_argument("--dim", type=int, default=100)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--epochs", type=int, default=10)
    p.add_argument("--lr", type=float, default=0.5)
    args = p.parse_args()

    path = args.data
    tmp = None
    if path is None:
        tmp = tempfile.NamedTemporaryFile(suffix=".libsvm", delete=False)
        path = tmp.name
        synthesize_libsvm(path, dim=args.dim)

    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(args.dim,),
                          batch_size=args.batch_size)
    w = nd.zeros((args.dim, 1))
    b = nd.zeros((1,))
    for epoch in range(args.epochs):
        it.reset()
        correct = total = 0
        for batch in it:
            X, y = batch.data[0], batch.label[0]
            logits = nd.dot(X, w) + b          # sparse csr @ dense
            yy = y.asnumpy()[:, None] * 2 - 1
            margin = logits.asnumpy() * yy
            # hinge-loss subgradient, batched through the csr transpose
            mask = nd.array((margin < 1).astype(np.float32) * -yy)
            gw = nd.dot(X, mask, transpose_a=True)
            w -= args.lr / args.batch_size * gw
            b -= args.lr / args.batch_size * mask.asnumpy().sum()
            correct += int((np.sign(logits.asnumpy()) == yy).sum())
            total += len(yy)
        print("epoch %d accuracy %.3f" % (epoch, correct / total))
    if tmp is not None:
        os.unlink(path)


if __name__ == "__main__":
    main()
