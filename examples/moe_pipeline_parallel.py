"""Expert + pipeline parallelism demo (new TPU-native capabilities —
the reference predates MoE and had no pipeline schedule).

Builds an 8-device CPU mesh, trains a toy MoE regression layer under
expert parallelism, then streams microbatches through a 4-stage
pipeline and checks it against serial execution.

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     JAX_PLATFORMS=cpu python examples/moe_pipeline_parallel.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8"
                               ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_platforms", "cpu")
if len(jax.devices()) < 8:
    raise SystemExit("need >= 8 devices (is "
                     "xla_force_host_platform_device_count pinned low?)")

from jax.sharding import Mesh

from mxnet_tpu.parallel import moe_ffn, pipeline_apply


def train_moe():
    n = 8
    mesh = Mesh(np.array(jax.devices()[:n]), ("expert",))
    E, D, H, T = 16, 32, 64, 256
    rng = np.random.RandomState(0)
    params = {
        "gate": jnp.array(rng.randn(D, E).astype(np.float32) * 0.3),
        "w1": jnp.array(rng.randn(E, D, H).astype(np.float32) * 0.2),
        "w2": jnp.array(rng.randn(E, H, D).astype(np.float32) * 0.2),
    }
    x = jnp.array(rng.randn(T, D).astype(np.float32))
    target = jnp.tanh(x @ jnp.array(
        rng.randn(D, D).astype(np.float32) * 0.4))

    def loss_fn(p, x, y):
        out = x + moe_ffn(x, p["gate"], p["w1"], p["w2"], mesh)
        return jnp.mean((out - y) ** 2)

    @jax.jit
    def step(p, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(p, x, y)
        return loss, jax.tree.map(lambda pi, g: pi - 0.1 * g, p, grads)
    first = None
    for i in range(300):
        loss, params = step(params, x, target)
        first = first if first is not None else float(loss)
    print("moe loss: %.4f -> %.4f over 300 steps" % (first, float(loss)))
    # gradients flow through the all_to_all routing: steady decrease
    assert float(loss) < 0.8 * first


def run_pipeline():
    S, M, MB, D = 4, 8, 8, 32
    mesh = Mesh(np.array(jax.devices()[:S]), ("pipe",))
    rng = np.random.RandomState(1)
    stages = (jnp.array(rng.randn(S, D, D).astype(np.float32) * 0.3),
              jnp.array(rng.randn(S, D).astype(np.float32) * 0.1))
    x = jnp.array(rng.randn(M, MB, D).astype(np.float32))

    def stage(p, h):
        return jnp.tanh(h @ p[0] + p[1])

    out = jax.jit(lambda p, v: pipeline_apply(stage, p, v, mesh))(
        stages, x)
    ref = x
    for s in range(S):
        ref = jnp.tanh(ref @ stages[0][s] + stages[1][s])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    print("pipeline over %d stages matches serial (bubble %.0f%%)"
          % (S, 100 * (S - 1) / (M + S - 1)))


if __name__ == "__main__":
    train_moe()
    run_pipeline()
