"""Sequence recognition with CTC (reference example/warpctc/lstm_ocr.py
shrunk to a synthetic task): an LSTM reads a noisy stripe rendering of a
digit string and CTCLoss aligns the unsegmented outputs.

Run: python examples/ctc_ocr.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, rnn

T, LAB, CLASSES = 20, 4, 5      # frames, label length, digit classes
FEAT = 16


def render(labels, rng):
    """Each digit paints its own channel over a few consecutive frames."""
    n = len(labels)
    x = rng.randn(n, T, FEAT).astype(np.float32) * 0.3
    for i, seq in enumerate(labels):
        for j, d in enumerate(seq):
            lo = 2 + j * 4
            x[i, lo:lo + 3, int(d) * 3:int(d) * 3 + 3] += 2.0
    return x


def greedy_decode(probs):
    """argmax -> collapse repeats -> drop blanks (blank = 0)."""
    best = probs.argmax(-1)
    out = []
    for row in best:
        seq, prev = [], -1
        for t in row:
            if t != prev and t != 0:
                seq.append(int(t))
            prev = t
        out.append(seq)
    return out


def build():
    data = mx.sym.Variable("data")                    # (N, T, FEAT)
    label = mx.sym.Variable("label")                  # (N, LAB)
    cell = rnn.LSTMCell(64, prefix="lstm_")
    outs, _ = cell.unroll(T, inputs=data, merge_outputs=True)
    pred = mx.sym.Reshape(outs, shape=(-1, 64))
    pred = mx.sym.FullyConnected(pred, num_hidden=CLASSES + 1,
                                 name="cls")          # + blank
    pred = mx.sym.Reshape(pred, shape=(-1, T, CLASSES + 1))
    # CTCLoss wants (T, N, C) activations
    act = mx.sym.transpose(pred, axes=(1, 0, 2))
    loss = mx.sym.CTCLoss(act, label, name="ctc")
    return mx.sym.MakeLoss(loss), pred


def main():
    rng = np.random.RandomState(0)
    n = 512
    labels = rng.randint(1, CLASSES + 1, (n, LAB)).astype(np.float32)
    X = render(labels, rng)

    loss_sym, pred_sym = build()
    group = mx.sym.Group([loss_sym, mx.sym.BlockGrad(pred_sym)])
    mod = mx.mod.Module(group, context=mx.cpu(),
                        data_names=("data",), label_names=("label",))
    it = mx.io.NDArrayIter({"data": X}, {"label": labels},
                           batch_size=64, shuffle=True,
                           label_name="label")
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 5e-3})
    for epoch in range(15):
        it.reset()
        tot = 0.0
        for batch in it:
            mod.forward(batch)
            mod.backward()
            mod.update()
            tot += float(mod.get_outputs()[0].sum().asscalar())
        if epoch % 5 == 0:
            print("epoch %d ctc loss/sample %.4f" % (epoch, tot / n))

    mod.forward(mx.io.DataBatch([nd.array(X[:128])],
                                [nd.array(labels[:128])]), is_train=False)
    probs = mod.get_outputs()[1].asnumpy()
    decoded = greedy_decode(probs)
    hits = sum(d == list(map(int, l)) for d, l in zip(decoded, labels))
    acc = hits / 128
    print("ctc exact-sequence accuracy: %.3f" % acc)
    assert acc > 0.7


if __name__ == "__main__":
    main()
