"""Model parallelism via ctx_group annotations lowered to sharding
constraints — reference example/model-parallel-lstm/lstm.py:65-116
(AttrScope(ctx_group=...) + group2ctx bind). On TPU the PlaceDevice
pass becomes GSPMD: each group's tensors carry a sharding constraint
on the mesh 'model' axis.

`JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
   python examples/model_parallel_lstm.py`
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.parallel import make_mesh, make_train_step


def stacked_lstm_lm(vocab, num_hidden, seq_len, num_layers):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=num_hidden,
                             name="embed")
    x = embed
    for i in range(num_layers):
        # alternate layers across model groups (the reference pins
        # layers to GPUs; here groups map to mesh partitions)
        with mx.AttrScope(ctx_group="dev%d" % (i % 2)):
            cell = mx.rnn.LSTMCell(num_hidden, prefix="lstm_l%d_" % i)
            x, _ = cell.unroll(seq_len, x, layout="NTC",
                               merge_outputs=True)
    pred = mx.sym.Reshape(x, shape=(-1, num_hidden))
    pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
    label_r = mx.sym.Reshape(label, shape=(-1,))
    return mx.sym.SoftmaxOutput(pred, label_r, name="softmax")


def main():
    import jax
    n_dev = len(jax.devices())
    model_axis = 2 if n_dev >= 2 else 1
    mesh = make_mesh({"data": n_dev // model_axis, "model": model_axis})

    vocab, hidden, seq_len, batch = 32, 32, 8, 8
    sym = stacked_lstm_lm(vocab, hidden, seq_len, num_layers=2)
    step = make_train_step(sym, optimizer="adam", mesh=mesh)
    state = step.init_state(mx.init.Xavier(),
                            {"data": (batch, seq_len),
                             "softmax_label": (batch, seq_len)})
    rng = jax.random.PRNGKey(0)
    rs = np.random.RandomState(0)
    tokens = rs.randint(0, vocab, (batch, seq_len)).astype(np.float32)
    labels = np.roll(tokens, -1, axis=1)
    bv = step.place_batch({"data": tokens, "softmax_label": labels})
    for i in range(10):
        state, outs = step(state, bv, 0.01, rng)
    print("trained 10 steps on a %s mesh; output" % (dict(
        zip(mesh.axis_names, mesh.devices.shape)),),
        np.asarray(jax.device_get(outs[0])).shape)


if __name__ == "__main__":
    main()
