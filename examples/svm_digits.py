"""SVM output layer — reference example/svm_mnist (trains an MLP whose
head is SVMOutput, the margin/hinge loss, instead of softmax; the
example exists to exercise that op end to end).

Data: the committed real handwritten-digit fixture. Both SVM modes are
trained — L2-regularized squared hinge (default) and L1 hinge
(use_linear=True) — and both must clear the accuracy gate.

Run: python examples/svm_digits.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io

FIXTURE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures", "digits_8x8.npz")


def svm_symbol(use_linear):
    data = mx.sym.Variable("data")
    net = mx.sym.Activation(mx.sym.FullyConnected(
        data, num_hidden=64, name="fc1"), act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    return mx.sym.SVMOutput(net, name="svm", margin=1.0,
                            regularization_coefficient=1.0,
                            use_linear=use_linear)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=15)
    p.add_argument("--batch-size", type=int, default=64)
    args = p.parse_args()
    B = args.batch_size

    with np.load(FIXTURE) as z:
        X = z["images"].astype(np.float32).reshape(-1, 64) / 16.0
        y = z["labels"].astype(np.float32)
    test = np.arange(len(y)) % 5 == 0
    Xtr, ytr, Xte, yte = X[~test], y[~test], X[test], y[test]

    for use_linear, name in ((False, "L2 squared-hinge"),
                             (True, "L1 hinge")):
        train = io.NDArrayIter(Xtr, ytr, batch_size=B, shuffle=True,
                               label_name="svm_label")
        mod = mx.mod.Module(svm_symbol(use_linear), context=mx.cpu(),
                            label_names=("svm_label",))
        mod.fit(train, num_epoch=args.epochs, optimizer="sgd",
                initializer=mx.init.Xavier(),
                optimizer_params={"learning_rate": 0.05,
                                  "momentum": 0.9,
                                  "rescale_grad": 1.0 / B})
        it = io.NDArrayIter(Xte, yte, batch_size=B,
                            label_name="svm_label")
        correct = total = 0
        for batch in it:
            mod.forward(batch, is_train=False)
            scores = mod.get_outputs()[0].asnumpy()
            n = min(B, len(yte) - total)
            correct += int((scores.argmax(1)[:n] ==
                            batch.label[0].asnumpy()[:n]).sum())
            total += n
        acc = correct / total
        print("%s: held-out accuracy %.3f" % (name, acc))
        assert acc > 0.90, "%s gate failed: %.3f" % (name, acc)
    print("svm_digits: PASS")


if __name__ == "__main__":
    main()
