"""DSD — Dense-Sparse-Dense training (Han et al. 2016) — reference
example/dsd/: three-phase training where the middle phase prunes the
smallest-magnitude weights and retrains under the sparsity MASK, and
the final phase releases the mask and retrains densely — a
regularize-by-pruning flow that often lands above the plain dense
baseline.

The seam this exercises is MASKED TRAINING through the Module API:
per-parameter binary masks derived from trained magnitudes, re-applied
after every optimizer update (the reference applied them inside its
modified SGD). TPU-first shape: the mask multiply is a fused
elementwise op on the parameter — applied host-side between updates
here (Module owns the update loop); the compiled-step equivalent
would fold `w * mask` into the optimizer op.

Self-checking, on the real-digits fixture:
1. the sparse phase really is sparse: >= the requested fraction of
   masked weights are exactly zero after every sparse-phase epoch;
2. pruning 60% of the weights costs almost nothing (sparse-phase
   accuracy within 3 points of dense);
3. the final dense phase ends >= the phase-1 dense baseline - 1pt
   (the DSD claim, modest at this scale).

Run: python examples/dsd_pruning.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io

BATCH = 32
SPARSITY = 0.6                  # fraction of weights pruned
MASKED = ("fc1_weight", "fc2_weight")


def get_symbol():
    net = mx.sym.Flatten(mx.sym.Variable("data"))
    net = mx.sym.Activation(mx.sym.FullyConnected(
        net, num_hidden=64, name="fc1"), act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def load_digits():
    f = np.load(os.path.join(os.path.dirname(__file__), "..", "tests",
                             "fixtures", "digits_8x8.npz"))
    X = f["images"].astype(np.float32)[:, None] / 16.0
    y = f["labels"].astype(np.float32)
    return X, y


def accuracy(mod, X, y):
    metric = mx.metric.Accuracy()
    it = io.NDArrayIter({"data": X}, {"softmax_label": y},
                        batch_size=BATCH)
    for batch in it:
        mod.forward(batch, is_train=False)
        mod.update_metric(metric, batch.label)
    return metric.get()[1]


def run_epochs(mod, X, y, n, masks=None):
    """Train n epochs; with masks, re-apply them after EVERY update
    and assert the invariant at each epoch boundary (pruned weights
    stay exactly zero through the whole phase, not just at its end)."""
    for _ in range(n):
        it = io.NDArrayIter({"data": X}, {"softmax_label": y},
                            batch_size=BATCH, shuffle=True)
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            if masks:
                args, aux = mod.get_params()
                for name, m in masks.items():
                    args[name][:] = args[name] * m
                mod.set_params(args, aux, force_init=True)
        if masks:
            args, _ = mod.get_params()
            for name in masks:
                s = sparsity_of(args[name])
                assert s >= SPARSITY - 0.01, \
                    "mask violated mid-phase on %s: %.2f" % (name, s)


def sparsity_of(arr):
    a = arr.asnumpy()
    return float((a == 0).mean())


def main():
    X, y = load_digits()
    mod = mx.mod.Module(get_symbol(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (BATCH, 1, 8, 8))],
             label_shapes=[("softmax_label", (BATCH,))])
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.2,
                                         "momentum": 0.9,
                                         "rescale_grad": 1.0 / BATCH})

    # -- phase 1: DENSE -----------------------------------------------------
    run_epochs(mod, X, y, 10)
    acc_dense = accuracy(mod, X, y)
    print("phase 1 (dense) acc: %.3f" % acc_dense)

    # -- phase 2: SPARSE — prune smallest |w|, retrain under the mask -------
    args, aux = mod.get_params()
    masks = {}
    for name in MASKED:
        w = args[name].asnumpy()
        k = int(w.size * SPARSITY)
        thresh = np.partition(np.abs(w).ravel(), k)[k]
        masks[name] = mx.nd.array(
            (np.abs(w) >= thresh).astype(np.float32))
        args[name][:] = args[name] * masks[name]
    mod.set_params(args, aux, force_init=True)

    run_epochs(mod, X, y, 10, masks=masks)
    acc_sparse = accuracy(mod, X, y)
    args, _ = mod.get_params()
    for name in MASKED:
        s = sparsity_of(args[name])
        print("phase 2 (sparse) %s zeros: %.2f" % (name, s))
        assert s >= SPARSITY - 0.01, \
            "mask not enforced on %s: %.2f" % (name, s)
    print("phase 2 (sparse) acc: %.3f" % acc_sparse)
    assert acc_sparse > acc_dense - 0.03, \
        "pruning %.0f%% cost too much: %.3f vs %.3f" \
        % (SPARSITY * 100, acc_sparse, acc_dense)

    # -- phase 3: re-DENSE — drop the mask, retrain at low lr ---------------
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9,
                                         "rescale_grad": 1.0 / BATCH},
                       force_init=True)
    run_epochs(mod, X, y, 6)
    acc_final = accuracy(mod, X, y)
    print("phase 3 (re-dense) acc: %.3f (dense baseline %.3f)"
          % (acc_final, acc_dense))
    assert acc_final >= acc_dense - 0.01, \
        "DSD ended below the dense baseline: %.3f vs %.3f" \
        % (acc_final, acc_dense)
    print("dsd_pruning OK")


if __name__ == "__main__":
    main()
