"""Multi-task learning — reference example/multi-task (one trunk, two
softmax heads trained jointly on MNIST digit + parity labels; the
example exists to exercise Group-of-losses training, per-head metrics,
and label routing by name through Module).

Task here: images from the committed real handwritten-digit fixture
(tests/fixtures/digits_8x8.npz), head A classifies the digit (10-way),
head B classifies parity (2-way) — genuinely shared signal, so the
joint trunk helps both.

Self-checking: both heads must clear their accuracy gates on held-out
data. Run: python examples/multi_task.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import io

FIXTURE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures", "digits_8x8.npz")


def multi_task_symbol():
    data = mx.sym.Variable("data")
    digit_label = mx.sym.Variable("digit_label")
    parity_label = mx.sym.Variable("parity_label")
    net = mx.sym.Convolution(data, kernel=(3, 3), pad=(1, 1),
                             num_filter=16, name="conv1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, pool_type="max", kernel=(2, 2),
                         stride=(2, 2))
    net = mx.sym.Flatten(net)
    trunk = mx.sym.Activation(mx.sym.FullyConnected(
        net, num_hidden=64, name="fc_trunk"), act_type="relu")
    digit = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(trunk, num_hidden=10, name="fc_digit"),
        digit_label, name="digit")
    parity = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(trunk, num_hidden=2, name="fc_parity"),
        parity_label, name="parity")
    return mx.sym.Group([digit, parity])


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=15)
    p.add_argument("--batch-size", type=int, default=64)
    args = p.parse_args()
    B = args.batch_size

    with np.load(FIXTURE) as z:
        X = z["images"].astype(np.float32)[:, None] / 16.0
        y = z["labels"].astype(np.float32)
    test = np.arange(len(y)) % 5 == 0
    Xtr, ytr = X[~test], y[~test]
    Xte, yte = X[test], y[test]

    def make_iter(Xs, ys):
        return io.NDArrayIter(
            data={"data": Xs},
            label={"digit_label": ys,
                   "parity_label": (ys % 2).astype(np.float32)},
            batch_size=B, shuffle=Xs is Xtr)

    mod = mx.mod.Module(multi_task_symbol(),
                        data_names=("data",),
                        label_names=("digit_label", "parity_label"))
    # "acc" pairs each output with its same-position label, giving
    # per-head accuracy in one metric (reference multi-task wrote a
    # custom Multi_Accuracy for the same thing)
    mod.fit(make_iter(Xtr, ytr), num_epoch=args.epochs,
            optimizer="sgd", initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                              "rescale_grad": 1.0 / B},
            eval_metric="acc")

    it = make_iter(Xte, yte)
    d_correct = p_correct = total = 0
    for batch in it:
        mod.forward(batch, is_train=False)
        digit_prob, parity_prob = [o.asnumpy()
                                   for o in mod.get_outputs()]
        n = min(B, len(yte) - total)
        yd = batch.label[0].asnumpy()[:n]
        yp = batch.label[1].asnumpy()[:n]
        d_correct += int((digit_prob.argmax(1)[:n] == yd).sum())
        p_correct += int((parity_prob.argmax(1)[:n] == yp).sum())
        total += n
    d_acc, p_acc = d_correct / total, p_correct / total
    print("digit accuracy %.3f, parity accuracy %.3f (n=%d)"
          % (d_acc, p_acc, total))
    assert d_acc > 0.90, "digit gate failed: %.3f" % d_acc
    assert p_acc > 0.90, "parity gate failed: %.3f" % p_acc
    print("multi_task: PASS")


if __name__ == "__main__":
    main()
