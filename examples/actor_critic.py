"""Actor-critic policy gradient — reference example/reinforcement-
learning/parallel_actor_critic (Module-era A2C; its dqn/ddpg siblings
need external simulators, this one is self-contained like ours).

Environment (built in, no dependency): a 1-D corridor of N cells; the
agent starts in a random cell, the goal sits at the right end; actions
move left/right; reward -1 per step, +10 at the goal, 40-step cap.
Optimal policy: always move right.

Exercises the imperative RL seam: per-step action SAMPLING from the
policy head, trajectory collection outside the graph, then ONE
autograd.record() pass over the stacked trajectory with the policy-
gradient surrogate loss (log-prob x advantage) plus a value-baseline
MSE — the pattern every reference RL example builds from.

Self-checking: after training, greedy rollouts must reach the goal in
<= 1.3x the optimal step count on average. Run:
python examples/actor_critic.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd

N_CELLS = 12
MAX_STEPS = 40


class Corridor:
    def __init__(self, rng):
        self.rng = rng

    def reset(self):
        self.pos = int(self.rng.randint(0, N_CELLS - 1))
        self.t = 0
        return self.pos

    def step(self, action):                  # 0 = left, 1 = right
        self.pos = max(0, min(N_CELLS - 1,
                              self.pos + (1 if action == 1 else -1)))
        self.t += 1
        done = self.pos == N_CELLS - 1 or self.t >= MAX_STEPS
        reward = 10.0 if self.pos == N_CELLS - 1 else -1.0
        return self.pos, reward, done


def one_hot(states):
    out = np.zeros((len(states), N_CELLS), np.float32)
    out[np.arange(len(states)), states] = 1.0
    return out


class ActorCritic:
    """Shared trunk, policy + value heads; plain NDArrays with
    attach_grad (the imperative API end to end)."""

    def __init__(self, rng, hidden=32):
        def init(shape, scale):
            return nd.array(rng.randn(*shape).astype(np.float32) * scale)

        self.params = {
            "w1": init((hidden, N_CELLS), 0.3),
            "b1": nd.zeros((hidden,)),
            "wp": init((2, hidden), 0.1),
            "bp": nd.zeros((2,)),
            "wv": init((1, hidden), 0.1),
            "bv": nd.zeros((1,)),
        }
        for p in self.params.values():
            p.attach_grad()

    def forward(self, x):
        h = nd.relu(nd.FullyConnected(x, self.params["w1"],
                                      self.params["b1"],
                                      num_hidden=self.params["w1"].shape[0]))
        logits = nd.FullyConnected(h, self.params["wp"],
                                   self.params["bp"], num_hidden=2)
        value = nd.FullyConnected(h, self.params["wv"],
                                  self.params["bv"], num_hidden=1)
        return logits, value

    def act(self, state, rng):
        logits, _ = self.forward(nd.array(one_hot([state])))
        p = np.asarray(nd.softmax(logits).asnumpy()).ravel()
        return int(rng.choice(2, p=p / p.sum()))

    def greedy(self, state):
        logits, _ = self.forward(nd.array(one_hot([state])))
        return int(logits.asnumpy().argmax())


def run_episode(env, agent, rng):
    states, actions, rewards = [], [], []
    s = env.reset()
    done = False
    while not done:
        a = agent.act(s, rng)
        s2, r, done = env.step(a)
        states.append(s)
        actions.append(a)
        rewards.append(r)
        s = s2
    return states, actions, rewards


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--episodes", type=int, default=300)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--gamma", type=float, default=0.98)
    args = p.parse_args()

    rng = np.random.RandomState(0)
    env = Corridor(rng)
    agent = ActorCritic(rng)

    for ep in range(args.episodes):
        states, actions, rewards = run_episode(env, agent, rng)
        # discounted returns
        G, ret = 0.0, []
        for r in reversed(rewards):
            G = r + args.gamma * G
            ret.append(G)
        ret = np.array(ret[::-1], np.float32)

        x = nd.array(one_hot(states))
        a = nd.array(np.array(actions, np.float32))
        g = nd.array(ret)
        with autograd.record():
            logits, value = agent.forward(x)
            logp = nd.log_softmax(logits)                 # (T, 2)
            chosen = nd.pick(logp, a)                     # (T,)
            adv = g - nd.BlockGrad(nd.Flatten(value).reshape((-1,)))
            pg_loss = -(chosen * adv).mean()
            v_loss = nd.square(
                nd.Flatten(value).reshape((-1,)) - g).mean()
            loss = pg_loss + 0.5 * v_loss
        loss.backward()
        for name, prm in agent.params.items():
            nd.sgd_update(prm, prm.grad, lr=args.lr, out=prm)
        if (ep + 1) % 100 == 0:
            print("episode %d steps %d return %.1f" % (
                ep + 1, len(rewards), sum(rewards)))

    # -- gate: greedy policy near-optimal -----------------------------------
    eval_rng = np.random.RandomState(7)
    env_eval = Corridor(eval_rng)
    ratios = []
    for _ in range(40):
        s = env_eval.reset()
        optimal = max(1, (N_CELLS - 1) - s)
        steps, done = 0, False
        while not done and steps < MAX_STEPS:
            s, _r, done = env_eval.step(agent.greedy(s))
            steps += 1
        ratios.append(steps / optimal)
    avg = float(np.mean(ratios))
    print("avg steps / optimal: %.3f" % avg)
    assert avg <= 1.3, "policy gate failed: %.3f" % avg
    print("actor_critic: PASS")


if __name__ == "__main__":
    main()
