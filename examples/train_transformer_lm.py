"""Train the transformer LM (the long-context flagship) through the
compiled SPMD TrainStep — causal flash attention on the MXU, bf16
compute with f32 master weights. Self-contained synthetic corpus:

`python examples/train_transformer_lm.py`
(add XLA_FLAGS=--xla_force_host_platform_device_count=8 and
 --num-devices 8 for a dp x tp mesh; see
 examples/long_context_ring_attention.py for sequence parallelism)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import argparse
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models import transformer
from mxnet_tpu.parallel import make_mesh, make_train_step


def corpus(n, T, vocab, seed=0):
    rng = np.random.RandomState(seed)
    starts = rng.randint(0, vocab, n)
    step = rng.randint(1, 5, n)
    toks = (starts[:, None] + step[:, None] * np.arange(T)[None, :]) \
        % vocab
    labels = np.roll(toks, -1, axis=1).astype(np.float32)
    labels[:, -1] = -1
    return toks.astype(np.float32), labels


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--vocab", type=int, default=64)
    p.add_argument("--seq-len", type=int, default=64)
    p.add_argument("--layers", type=int, default=2)
    p.add_argument("--heads", type=int, default=4)
    p.add_argument("--dim", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--steps", type=int, default=150)
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--num-devices", type=int, default=1)
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "bfloat16"])
    args = p.parse_args()

    import jax
    mesh = None
    if args.num_devices > 1:
        model = 2 if args.num_devices % 2 == 0 else 1
        mesh = make_mesh({"data": args.num_devices // model,
                          "model": model},
                         devices=jax.devices()[:args.num_devices])

    sym = transformer.get_symbol(args.vocab, args.seq_len,
                                 num_layers=args.layers,
                                 num_heads=args.heads, dim=args.dim)
    step = make_train_step(
        sym, optimizer="adam", mesh=mesh,
        compute_dtype=None if args.dtype == "float32" else args.dtype)
    shapes = {"data": (args.batch_size, args.seq_len),
              "softmax_label": (args.batch_size, args.seq_len)}
    state = step.init_state(mx.init.Xavier(), shapes)
    toks, labels = corpus(args.batch_size, args.seq_len, args.vocab)
    bv = step.place_batch({"data": toks, "softmax_label": labels})
    rng = jax.random.PRNGKey(0)

    def nll(outs):
        pr = np.asarray(jax.device_get(outs[0])).reshape(
            args.batch_size, args.seq_len, args.vocab)
        tgt = labels.astype(int)
        bi, ti = np.nonzero(tgt >= 0)
        return float(-np.log(np.maximum(
            pr[bi, ti, tgt[bi, ti]], 1e-9)).mean())

    state, outs = step(state, bv, args.lr, rng)
    print("step 0 nll %.3f" % nll(outs))
    t0 = time.time()
    for i in range(1, args.steps + 1):
        state, outs = step(state, bv, args.lr, rng)
        if i % 50 == 0:
            print("step %d nll %.3f" % (i, nll(outs)))
    dt = (time.time() - t0) / args.steps
    tok_s = args.batch_size * args.seq_len / dt
    print("%.2f ms/step, %.0f tokens/s" % (dt * 1e3, tok_s))


if __name__ == "__main__":
    main()
